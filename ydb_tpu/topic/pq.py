"""PersQueue partition: a durable ordered message log.

Mirror of the reference's PQ tablet (TPersQueue persqueue/pq_impl.h:32,
per-partition actors partition.cpp; SURVEY.md §2.13): each partition is
an offset-ordered log with producer deduplication (producer id +
sequence numbers), per-consumer committed offsets, and retention. Built
on the tablet executor, so a partition reboots anywhere from the blob
store like every other tablet.
"""

from __future__ import annotations

import time

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.executor import TabletExecutor, Transaction


class _WriteTx(Transaction):
    def __init__(self, partition: "Partition", messages: list[dict],
                 producer: str | None, first_seqno: int | None):
        self.p = partition
        self.messages = messages
        self.producer = producer
        self.first_seqno = first_seqno
        self.offsets: list[int] = []

    def execute(self, txc, tablet):
        db = self.p.executor.db
        head = db.table("meta").get(("head",)) or {"offset": 0}
        offset = head["offset"]
        max_seen = None
        if self.producer is not None:
            row = db.table("producers").get((self.producer,))
            max_seen = row["seqno"] if row else -1
        for i, msg in enumerate(self.messages):
            seqno = (self.first_seqno + i
                     if self.first_seqno is not None else None)
            if max_seen is not None and seqno is not None and \
                    seqno <= max_seen:
                self.offsets.append(-1)  # deduplicated retry
                continue
            txc.put("msgs", (offset,), {
                "data": msg["data"],
                "key": msg.get("key"),
                "ts": msg.get("ts", self.p.now()),
                "seqno": seqno,
                "producer": self.producer,
            })
            self.offsets.append(offset)
            offset += 1
            if seqno is not None:
                max_seen = seqno
        txc.put("meta", ("head",), {"offset": offset})
        if self.producer is not None and max_seen is not None and \
                max_seen >= 0:
            txc.put("producers", (self.producer,), {"seqno": max_seen})


class _CommitTx(Transaction):
    def __init__(self, consumer: str, offset: int,
                 allow_rewind: bool = False):
        self.consumer = consumer
        self.offset = offset
        self.allow_rewind = allow_rewind

    def execute(self, txc, tablet):
        cur = txc.get("consumers", (self.consumer,))
        if cur is not None and cur["offset"] >= self.offset \
                and not self.allow_rewind:
            return  # stale/out-of-order ack: keep the monotonic offset
        txc.put("consumers", (self.consumer,), {"offset": self.offset})


class _VacuumTx(Transaction):
    def __init__(self, up_to: int):
        self.up_to = up_to

    def execute(self, txc, tablet):
        tail = txc.get("meta", ("tail",)) or {"offset": 0}
        for off in range(tail["offset"], self.up_to):
            txc.erase("msgs", (off,))
        txc.put("meta", ("tail",), {"offset": self.up_to})


class Partition:
    def __init__(self, partition_id: str, store: BlobStore,
                 now=time.time):
        self.partition_id = partition_id
        self.executor = TabletExecutor.boot(f"pq/{partition_id}", store)
        self.now = now

    # ---- write path ----

    def write(self, messages: list[dict], producer: str | None = None,
              first_seqno: int | None = None) -> list[int]:
        """Append messages ({data: str|bytes-as-str, ts}); returns the
        assigned offsets (-1 for producer-seqno duplicates)."""
        tx = _WriteTx(self, messages, producer, first_seqno)
        self.executor.execute(tx)
        return tx.offsets

    # ---- read path ----

    @property
    def head_offset(self) -> int:
        row = self.executor.db.table("meta").get(("head",))
        return row["offset"] if row else 0

    @property
    def tail_offset(self) -> int:
        row = self.executor.db.table("meta").get(("tail",))
        return row["offset"] if row else 0

    def read(self, offset: int, limit: int = 100) -> list[dict]:
        """Messages from offset (inclusive), each dict +'offset'."""
        out = []
        start = max(offset, self.tail_offset)
        for key, row in self.executor.db.table("msgs").range(
                lo=(start,), hi=(start + limit,)):
            out.append(dict(row, offset=key[0]))
        return out

    # ---- consumers ----

    def commit(self, consumer: str, offset: int,
               allow_rewind: bool = False) -> None:
        """Set the consumer's committed (next-to-read) offset. Stale
        acks are ignored unless ``allow_rewind`` (an explicit seek-back,
        e.g. a Kafka consumer reprocessing)."""
        self.executor.execute(_CommitTx(consumer, offset, allow_rewind))

    def committed(self, consumer: str) -> int:
        row = self.executor.db.table("consumers").get((consumer,))
        return row["offset"] if row else 0

    # ---- retention ----

    def vacuum(self, older_than_ts: float | None = None,
               keep_offsets: int | None = None) -> int:
        """Retention: drop the log tail. With no arguments, drops below
        the slowest consumer's commit point; an age or count policy
        expires messages regardless of consumers (the reference's
        retention semantics — unread data still ages out)."""
        cuts = []
        if older_than_ts is None and keep_offsets is None:
            rows = list(self.executor.db.table("consumers").range())
            cuts.append(min((r["offset"] for _k, r in rows),
                            default=self.tail_offset))
        if keep_offsets is not None:
            cuts.append(max(0, self.head_offset - keep_offsets))
        if older_than_ts is not None:
            cut = self.tail_offset
            for key, row in self.executor.db.table("msgs").range():
                if row["ts"] < older_than_ts:
                    cut = key[0] + 1
                else:
                    break
            cuts.append(cut)
        up_to = min(max(cuts), self.head_offset)
        removed = max(0, up_to - self.tail_offset)
        if removed:
            self.executor.execute(_VacuumTx(up_to))
        return removed
