from ydb_tpu.topic.pq import Partition
from ydb_tpu.topic.topic import Topic

__all__ = ["Partition", "Topic"]
