"""Topic: partitioned message stream + read sessions.

Mirror of the reference's topic surface (gRPC topic write/read session
actors, services/persqueue_v1; read balancer read_balancer.cpp;
SURVEY.md §2.13): writes route by message key hash (ordering per key)
or round-robin; a ReadSession drains all partitions for one consumer
with explicit commit.
"""

from __future__ import annotations

import itertools

from ydb_tpu.common import fnv1a_64 as _key_hash
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.topic.pq import Partition


class Topic:
    def __init__(self, name: str, store: BlobStore, n_partitions: int = 2,
                 now=None):
        self.name = name
        kwargs = {"now": now} if now is not None else {}
        self.partitions = [
            Partition(f"{name}/{i}", store, **kwargs)
            for i in range(n_partitions)
        ]
        self._rr = itertools.count()

    def storage_prefixes(self) -> list[str]:
        return [f"tablet/pq/{p.partition_id}/" for p in self.partitions]

    def partition_for(self, key: str | None) -> int:
        if key is None:
            return next(self._rr) % len(self.partitions)
        return _key_hash(key) % len(self.partitions)

    def write(self, data: str, key: str | None = None,
              producer: str | None = None,
              seqno: int | None = None) -> tuple[int, int]:
        """Returns (partition, offset)."""
        p = self.partition_for(key)
        offs = self.partitions[p].write(
            [{"data": data}], producer=producer, first_seqno=seqno)
        return p, offs[0]

    def reader(self, consumer: str) -> "ReadSession":
        return ReadSession(self, consumer)


class ReadSession:
    """One consumer over all partitions (explicit commit)."""

    def __init__(self, topic: Topic, consumer: str):
        self.topic = topic
        self.consumer = consumer

    def read_batch(self, limit_per_partition: int = 100) -> list[dict]:
        """Uncommitted messages across partitions, each dict carrying
        (partition, offset, data)."""
        out = []
        for pi, part in enumerate(self.topic.partitions):
            start = part.committed(self.consumer)
            for msg in part.read(start, limit_per_partition):
                out.append(dict(msg, partition=pi))
        return out

    def commit(self, partition: int, offset: int) -> None:
        """Commit offsets UP TO AND INCLUDING offset."""
        self.topic.partitions[partition].commit(
            self.consumer, offset + 1)

    def commit_batch(self, batch: list[dict]) -> None:
        tops: dict[int, int] = {}
        for msg in batch:
            tops[msg["partition"]] = max(
                tops.get(msg["partition"], -1), msg["offset"])
        for p, off in tops.items():
            self.commit(p, off)
