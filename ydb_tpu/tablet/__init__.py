from ydb_tpu.tablet.executor import TabletExecutor, Transaction
from ydb_tpu.tablet.localdb import LocalDb

__all__ = ["TabletExecutor", "Transaction", "LocalDb"]
