"""Tablet executor: the universal persistence primitive.

Mirror of the reference's tablet_flat executor (ITransaction
Execute/Complete tablet_flat_executor.h:281,297; TExecutor
flat_executor.h:320; boot stages flat_boot_*.h; SURVEY.md §3.5): a
per-tablet single-writer transaction machine whose only durable state is
a snapshot plus a redo log in the blob store. ``execute`` runs the
transaction against the local DB, persists the change set as a redo
record, applies it, then runs ``complete`` for side effects. ``boot``
replays snapshot + redo — any node can resurrect the tablet from the
store alone, which is what lets Hive restart dead tablets elsewhere
(mind/hive; SURVEY.md §5.3).

Generations fence zombie writers: each boot bumps the generation, and
log records carry it. Replay follows the highest-generation chain, so a
stale leader's appends after a takeover are ignored by the next boot
(the blob-store analog of BlobStorage's barrier/block mechanism).
"""

from __future__ import annotations

import json
import threading

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.localdb import LocalDb


class TxContext:
    """Change-staging handle passed to Transaction.execute."""

    def __init__(self, db: LocalDb, version: int):
        self.db = db
        self.version = version  # version this commit will get
        self.changes: list[tuple] = []

    # reads see committed state only (single-writer: no dirty reads needed)
    def get(self, table: str, key: tuple):
        return self.db.table(table).get(tuple(key))

    def range(self, table: str, lo=None, hi=None):
        return self.db.table(table).range(lo, hi)

    def put(self, table: str, key: tuple, row: dict) -> None:
        self.changes.append((table, tuple(key), dict(row)))

    def put_at(self, table: str, key: tuple, row: dict | None,
               version: int) -> None:
        """Write with an explicit MVCC version (global plan step) instead
        of this commit's version — the DataShard visibility clock."""
        self.changes.append((table, tuple(key),
                             dict(row) if row is not None else None,
                             version))

    def erase(self, table: str, key: tuple) -> None:
        self.changes.append((table, tuple(key), None))


class Transaction:
    def execute(self, txc: TxContext, tablet) -> None:
        raise NotImplementedError

    def complete(self, tablet) -> None:
        pass


class _ApplyTx(Transaction):
    """Single-closure transaction (the lambda-ITransaction idiom used
    by the small coordination tablets: kesus, console, nodebroker)."""

    def __init__(self, fn):
        self.fn = fn
        self.result = None

    def execute(self, txc, tablet):
        self.result = self.fn(txc)


class FencedError(Exception):
    """A higher generation has taken over this tablet; the caller is a
    zombie leader and must stop (blob-barrier analog)."""


class TabletExecutor:
    SNAP_EVERY = 64  # commits between automatic checkpoints

    def __init__(self, tablet_id: str, store: BlobStore, *,
                 generation: int = 1, db: LocalDb | None = None,
                 version: int = 0, log_index: int = 0):
        self.tablet_id = tablet_id
        self.store = store
        self.generation = generation
        self.db = db or LocalDb()
        self.version = version  # last committed version
        self.log_index = log_index  # next redo record index
        self._since_snap = 0
        # one tablet = one writer: commit paths that bypass a global
        # commit lock (volatile readset exchange) still serialize
        # per-tablet here, so version/log_index never collide. Reentrant
        # because execute() checkpoints under it and checkpoint() is
        # also a public entry point that must take it itself.
        self._exec_lock = threading.RLock()
        # per-tablet counters (tablet_counters*.cpp analog), merged
        # cluster-wide by obs.tablet_counters.aggregate
        self.counters = {
            "tx_executed": 0, "tx_committed": 0, "redo_bytes": 0,
            "checkpoints": 0,
        }

    # ---- commit path ----

    def _prefix(self) -> str:
        return f"tablet/{self.tablet_id}/"

    def run(self, fn):
        """Execute a single-closure transaction; returns fn's result."""
        tx = _ApplyTx(fn)
        self.execute(tx)
        return tx.result

    def execute(self, tx: Transaction):
        with self._exec_lock:
            txc = TxContext(self.db, self.version + 1)
            tx.execute(txc, self)
            self.counters["tx_executed"] += 1
            if txc.changes:
                record = {
                    "gen": self.generation,
                    "version": txc.version,
                    "changes": [
                        [ch[0], list(ch[1])] + list(ch[2:])
                        for ch in txc.changes
                    ],
                }
                blob_id = (f"{self._prefix()}log/"
                           f"{self.generation:08d}."
                           f"{self.log_index:010d}")
                payload = json.dumps(record).encode()
                self.store.put(blob_id, payload)
                self.counters["tx_committed"] += 1
                self.counters["redo_bytes"] += len(payload)
                self.log_index += 1
                self.db.apply(txc.changes, txc.version)
                self.version = txc.version
                self._since_snap += 1
                if self._since_snap >= self.SNAP_EVERY:
                    self.checkpoint()
            tx.complete(self)
            return tx

    def _superseded(self) -> bool:
        """True when the store shows a higher generation has booted —
        this executor is a fenced-out zombie. Both log and snapshot keys
        encode the generation, so this is a listing, not blob reads."""
        for kind in ("log", "snap"):
            for blob_id in self.store.list(f"{self._prefix()}{kind}/"):
                g = int(blob_id.rsplit("/", 1)[1].split(".")[0])
                if g > self.generation:
                    return True
        return False

    def checkpoint(self) -> None:
        # serialized against execute(): an external checkpoint racing a
        # commit could snapshot a half-applied version and truncate the
        # redo records that covered it (reentrant from execute itself)
        with self._exec_lock:
            self._checkpoint_locked()

    def _checkpoint_locked(self) -> None:
        # A stale leader must never snapshot: its snapshot would bake in
        # zombie writes past the successor's fence and boot would then
        # skip the successor's redo records (version <= snapshot
        # version). Verify we are still the highest generation before
        # writing or truncating anything.
        if self._superseded():
            raise FencedError(
                f"tablet {self.tablet_id} gen {self.generation} "
                "superseded; refusing checkpoint")
        snap = {
            "gen": self.generation,
            "version": self.version,
            "log_index": self.log_index,
            "db": self.db.dump(),
        }
        # key carries (gen, version): two generations snapshotting at the
        # same version must not collide on one blob id
        self.store.put(
            f"{self._prefix()}snap/{self.generation:08d}.{self.version:012d}",
            json.dumps(snap).encode())
        # truncate redo records covered by the snapshot
        for blob_id in self.store.list(f"{self._prefix()}log/"):
            gen, idx = blob_id.rsplit("/", 1)[1].split(".")
            if (int(gen), int(idx)) < (self.generation, self.log_index):
                self.store.delete(blob_id)
        # prune superseded snapshots (this one covers them)
        for blob_id in self.store.list(f"{self._prefix()}snap/"):
            gen, ver = blob_id.rsplit("/", 1)[1].split(".")
            if (int(gen), int(ver)) < (self.generation, self.version):
                self.store.delete(blob_id)
        self._since_snap = 0
        self.counters["checkpoints"] += 1

    # ---- boot path ----

    @classmethod
    def boot(cls, tablet_id: str, store: BlobStore) -> "TabletExecutor":
        prefix = f"tablet/{tablet_id}/"
        db, version, log_index, gen = LocalDb(), 0, 0, 0
        by_gen: dict[int, list] = {}
        for blob_id in store.list(f"{prefix}log/"):
            rec = json.loads(store.get(blob_id).decode())
            g, idx = blob_id.rsplit("/", 1)[1].split(".")
            by_gen.setdefault(int(g), []).append((int(idx), rec))
        first_version = {
            g: min(rec["version"] for _, rec in recs)
            for g, recs in by_gen.items()
        }
        # Snapshot selection applies the same fence as replay: a
        # snapshot written by generation g whose version reaches at or
        # past the first version a higher generation wrote was taken by
        # a fenced-out zombie and has its writes baked in — skip it.
        best_snap, best_key = None, (-1, -1)
        for blob_id in store.list(f"{prefix}snap/"):
            snap = json.loads(store.get(blob_id).decode())
            fence = min((fv for h, fv in first_version.items()
                         if h > snap["gen"]), default=None)
            if fence is not None and snap["version"] >= fence:
                continue  # zombie-tainted snapshot
            key = (snap["gen"], snap["version"])
            if key > best_key:
                best_snap, best_key = snap, key
        if best_snap is not None:
            db = LocalDb.load(best_snap["db"])
            version = best_snap["version"]
            log_index = best_snap["log_index"]
            gen = best_snap["gen"]
        # Replay redo records after the snapshot with zombie fencing: a
        # generation g record is only valid below the first version any
        # higher generation wrote — the successor booted without seeing
        # anything past that point, so later g-writes are a fenced-out
        # leader's and must be discarded (the blob-barrier analog).
        for g in sorted(by_gen):
            if g < gen:
                continue  # pre-snapshot stale generation
            limit = min((first_version[h] for h in by_gen if h > g),
                        default=None)
            for idx, rec in sorted(by_gen[g]):
                if rec["version"] <= version:
                    continue
                if limit is not None and rec["version"] >= limit:
                    continue  # fenced zombie write
                changes = [(ch[0], tuple(ch[1]), *ch[2:])
                           for ch in rec["changes"]]
                db.apply(changes, rec["version"])
                version = rec["version"]
                gen = max(gen, g)
                log_index = max(log_index, idx + 1)
        gen = max(gen, max(by_gen, default=0))
        return cls(tablet_id, store, generation=gen + 1, db=db,
                   version=version, log_index=log_index)
