"""Tablet pipes: reliable ordered client->tablet streams.

Mirror of the reference's pipe client (tablet/tablet_pipe_client.cpp;
SURVEY.md §2.4): a client never addresses a tablet actor directly — it
opens a pipe keyed by tablet id; the pipe resolves the current leader
through state storage, delivers messages in order, and transparently
re-resolves + retransmits when the leader dies and Hive reboots the
tablet elsewhere. Delivery is at-least-once with per-(pipe, seq) dedup
on the tablet side (TabletActor.receive), which together with in-order
retransmission gives the exactly-once-per-pipe ordering contract the
reference's pipes provide.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any

from ydb_tpu.runtime.actors import Actor, ActorId
from ydb_tpu.tablet.statestorage import SSLookup, SSLookupReply


_pipe_ids = itertools.count(1)


@dataclasses.dataclass
class PipeRequest:
    pipe_id: int
    seq: int
    payload: Any
    reply_to: ActorId  # app-level replies go here (the pipe's owner)


@dataclasses.dataclass
class PipeAck:
    pipe_id: int
    seq: int


@dataclasses.dataclass
class PipeSend:
    payload: Any


@dataclasses.dataclass
class _RetryTick:
    pass


class PipeClient(Actor):
    """Owned by one client actor; forwards its PipeSend payloads to the
    tablet's current leader with ack/retransmit."""

    RETRY_PERIOD = 2.0

    def __init__(self, tablet_id: str, ss_proxy: ActorId, owner: ActorId):
        super().__init__()
        self.tablet_id = tablet_id
        self.ss_proxy = ss_proxy
        self.owner = owner
        self.pipe_id = next(_pipe_ids)
        self.leader: ActorId | None = None
        self.leader_gen = 0
        self._seq = itertools.count()
        self._unacked: dict[int, PipeRequest] = {}
        self._resolving = False
        self._retry_armed = False

    def _resolve(self):
        if not self._resolving:
            self._resolving = True
            self.send(self.ss_proxy, SSLookup(self.tablet_id))

    def _flush(self):
        if self.leader is None:
            self._resolve()
            return
        for seq in sorted(self._unacked):
            self.send(self.leader, self._unacked[seq])
        if self._unacked and not self._retry_armed:
            self._retry_armed = True
            self.schedule(self.RETRY_PERIOD, _RetryTick())

    def receive(self, message, sender):
        if isinstance(message, PipeSend):
            req = PipeRequest(self.pipe_id, next(self._seq),
                              message.payload, self.owner)
            self._unacked[req.seq] = req
            self._flush()
        elif isinstance(message, SSLookupReply):
            self._resolving = False
            if message.leader is not None and \
                    message.generation >= self.leader_gen:
                self.leader = message.leader
                self.leader_gen = message.generation
            self._flush()
        elif isinstance(message, PipeAck):
            self._unacked.pop(message.seq, None)
        elif isinstance(message, _RetryTick):
            self._retry_armed = False
            if self._unacked:
                # leader may have moved: re-resolve, then retransmit
                self.leader = None
                self._resolve()
                self._retry_armed = True
                self.schedule(self.RETRY_PERIOD, _RetryTick())
