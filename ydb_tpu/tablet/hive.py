"""Hive: tablet placement, boot and failure recovery.

Mirror of the reference's Hive tablet + per-node Local agent
(THive mind/hive/hive_impl.h:158; mind/local.cpp; SURVEY.md §2.5, §5.3):
Hive knows every registered node (via its Local agent), decides which
node hosts each tablet (least-loaded placement, hive/balancer.cpp
analog), and — the failure-recovery half — pings agents and reboots a
dead node's tablets elsewhere. Because a tablet's durable state is
snapshot+redo in the blob store (ydb_tpu.tablet.executor), a reboot on a
new node recovers full state; state storage registration with a higher
generation fences the old leader.

The per-node LocalAgent hosts the actual TabletActor instances; tablet
behavior is supplied by a factory registry: type name -> f(tablet_id,
executor) -> TabletActor.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.runtime.actors import Actor, ActorId
from ydb_tpu.tablet.executor import TabletExecutor
from ydb_tpu.tablet.statestorage import SSUpdate, SSUpdateAck


# ---- messages ----

@dataclasses.dataclass
class RegisterNode:
    node: int


@dataclasses.dataclass
class CreateTablet:
    tablet_id: str
    tablet_type: str


@dataclasses.dataclass
class TabletCreated:
    tablet_id: str
    node: int


@dataclasses.dataclass
class BootTablet:
    tablet_id: str
    tablet_type: str


@dataclasses.dataclass
class TabletBooted:
    tablet_id: str
    actor: ActorId
    generation: int


@dataclasses.dataclass
class StopTablet:
    tablet_id: str


@dataclasses.dataclass
class Ping:
    pass


@dataclasses.dataclass
class Pong:
    node: int


@dataclasses.dataclass
class KillNode:
    """Test/nemesis hook: agent drops all its tablets and goes silent."""
    pass


class TabletActor(Actor):
    """Base for tablet impls: owns the executor; subclasses override
    handle() for app messages. Pipe traffic arrives pre-deduplicated."""

    def __init__(self, tablet_id: str, executor: TabletExecutor):
        super().__init__()
        self.tablet_id = tablet_id
        self.executor = executor
        self._pipe_seen: dict[int, int] = {}  # pipe_id -> last seq

    def receive(self, message, sender):
        from ydb_tpu.tablet.pipe import PipeAck, PipeRequest

        if isinstance(message, PipeRequest):
            last = self._pipe_seen.get(message.pipe_id, -1)
            self.send(sender, PipeAck(message.pipe_id, message.seq))
            if message.seq <= last:
                return  # duplicate retransmit
            self._pipe_seen[message.pipe_id] = message.seq
            self.handle(message.payload, message.reply_to)
        else:
            self.handle(message, sender)

    def handle(self, message, reply_to):
        raise NotImplementedError


class LocalAgent(Actor):
    """Per-node tablet host (mind/local.cpp analog)."""

    def __init__(self, store: BlobStore, ss_proxy: ActorId,
                 factories: dict[str, Callable], hive: ActorId | None = None):
        super().__init__()
        self.store = store
        self.ss_proxy = ss_proxy
        self.factories = factories
        self.hive = hive
        self.tablets: dict[str, ActorId] = {}
        self.dead = False

    def on_start(self):
        if self.hive is not None:
            self.send(self.hive, RegisterNode(self.self_id.node))

    def receive(self, message, sender):
        if self.dead:
            return
        if isinstance(message, BootTablet):
            executor = TabletExecutor.boot(message.tablet_id, self.store)
            actor = self.factories[message.tablet_type](
                message.tablet_id, executor)
            aid = self.system.register(actor)
            self.tablets[message.tablet_id] = aid
            # publish leadership; generation fences older leaders
            self.send(self.ss_proxy, SSUpdate(
                message.tablet_id, aid, executor.generation))
            self.send(sender, TabletBooted(
                message.tablet_id, aid, executor.generation))
        elif isinstance(message, SSUpdateAck):
            pass
        elif isinstance(message, StopTablet):
            aid = self.tablets.pop(message.tablet_id, None)
            if aid is not None:
                self.system.stop(aid)
        elif isinstance(message, Ping):
            self.send(sender, Pong(self.self_id.node))
        elif isinstance(message, KillNode):
            for aid in self.tablets.values():
                self.system.stop(aid)
            self.tablets.clear()
            self.dead = True


class Hive(Actor):
    PING_PERIOD = 5.0
    DEAD_AFTER_MISSED = 2

    def __init__(self):
        super().__init__()
        self.agents: dict[int, ActorId] = {}
        self.missed: dict[int, int] = {}
        self.tablets: dict[str, dict] = {}  # id -> {type, node, booted}
        self._ping_started = False

    def _load(self, node: int) -> int:
        return sum(1 for t in self.tablets.values() if t["node"] == node)

    def _pick_node(self, exclude: set[int] = frozenset()) -> int | None:
        alive = [n for n in self.agents if n not in exclude]
        if not alive:
            return None
        return min(alive, key=lambda n: (self._load(n), n))

    def _boot_on(self, tablet_id: str, node: int) -> None:
        info = self.tablets[tablet_id]
        info["node"] = node
        info["booted"] = False
        self.send(self.agents[node],
                  BootTablet(tablet_id, info["type"]))

    def receive(self, message, sender):
        if isinstance(message, RegisterNode):
            self.agents[message.node] = sender
            self.missed[message.node] = 0
            if not self._ping_started:
                self._ping_started = True
                self.schedule(self.PING_PERIOD, Ping())
        elif isinstance(message, CreateTablet):
            node = self._pick_node()
            self.tablets[message.tablet_id] = {
                "type": message.tablet_type, "node": node,
                "booted": False, "requester": sender,
            }
            if node is not None:
                self._boot_on(message.tablet_id, node)
        elif isinstance(message, TabletBooted):
            info = self.tablets.get(message.tablet_id)
            if info is not None:
                info["booted"] = True
                req = info.pop("requester", None)
                if req is not None:
                    self.send(req, TabletCreated(
                        message.tablet_id, info["node"]))
        elif isinstance(message, Ping):
            # self-scheduled tick: ping every agent, count misses
            for node, aid in list(self.agents.items()):
                self.missed[node] = self.missed.get(node, 0) + 1
                if self.missed[node] > self.DEAD_AFTER_MISSED:
                    self._on_node_dead(node)
                else:
                    self.send(aid, Ping())
            self.schedule(self.PING_PERIOD, Ping())
        elif isinstance(message, Pong):
            self.missed[message.node] = 0

    def _on_node_dead(self, node: int) -> None:
        self.agents.pop(node, None)
        self.missed.pop(node, None)
        for tablet_id, info in self.tablets.items():
            if info["node"] == node:
                new_node = self._pick_node(exclude={node})
                if new_node is not None:
                    self._boot_on(tablet_id, new_node)
