"""In-tablet sorted KV store with MVCC row versions.

Mirror of the reference's NTable local database (flat_database.h:41;
SURVEY.md §2.4): every tablet persists its state through one of these —
named tables of sorted rows, where each key holds a list of versioned
values so reads at an older snapshot still see the old row. The reference
keeps a memtable plus immutable B-tree parts; at the scale of host
control-plane state (schemas, tx queues, offsets — not user data) a
single sorted dict per table with explicit version chains carries the
same semantics, and ``freeze_part``/``compact`` keep the memtable/part
shape for the OLTP datashard built on top.

Rows are dict[str, value]; keys are tuples (the primary key columns).
Versions are monotonically increasing integers supplied by the executor
(the tablet's commit counter — the analog of the redo-log step).
"""

from __future__ import annotations

import bisect
import heapq
from typing import Any, Iterator


TOMBSTONE = object()


class _Part:
    """Immutable sorted run (the flat_part shape, flat_part_*.h): keys
    split into fixed-size PAGES with a first-key page index searched
    like a two-level B-tree, plus a BLOOM FILTER over key hashes so
    point reads skip parts that cannot hold the key. Built by
    TableStore.freeze_part from the memtable; merged away by
    compact()."""

    PAGE_ROWS = 64

    def __init__(self, items: list):
        # items: [(key, chain)] in key order; chains newest-first
        self.pages = [items[i:i + self.PAGE_ROWS]
                      for i in range(0, len(items), self.PAGE_ROWS)]
        self.index = [page[0][0] for page in self.pages]
        # bloom: ~10 bits/key, 3 hash probes (classic FP ~1%); a
        # bytearray keeps each probe O(1) (a Python big-int shift
        # would copy the whole filter per probe)
        self._m = max(64, len(items) * 10)
        bits = bytearray((self._m + 7) // 8)
        for key, _chain in items:
            for probe in self._probes(key):
                bits[probe >> 3] |= 1 << (probe & 7)
        self._bits = bits
        self.bloom_negatives = 0  # observability: point reads skipped

    def _probes(self, key: tuple):
        h = hash(key)
        for salt in (0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F,
                     0x165667B19E3779F9):
            yield (h ^ salt) % self._m

    def may_contain(self, key: tuple) -> bool:
        return all(self._bits[p >> 3] & (1 << (p & 7))
                   for p in self._probes(key))

    def get_chain(self, key: tuple) -> list | None:
        if not self.pages:
            return None
        if not self.may_contain(key):
            self.bloom_negatives += 1
            return None
        pi = bisect.bisect_right(self.index, key) - 1
        if pi < 0:
            return None
        for k, chain in self.pages[pi]:
            if k == key:
                return chain
            if k > key:
                break
        return None

    def iter_items(self, lo: tuple | None, hi: tuple | None):
        start = 0
        if lo is not None:
            start = max(bisect.bisect_right(self.index, lo) - 1, 0)
        for page in self.pages[start:]:
            for k, chain in page:
                if lo is not None and k < lo:
                    continue
                if hi is not None and k >= hi:
                    return
                yield k, chain


class TableStore:
    """One table: a MEMTABLE of sorted keys with version chains plus
    immutable frozen PARTS (page-indexed, bloom-filtered — the
    memtable/flat-part split of the reference's NTable). Writes land in
    the memtable; ``memtable_limit`` keys auto-freeze it into a part
    (the compaction strategy trigger); ``compact`` merges parts away
    under the version horizon. Version order across sources is
    guaranteed by the monotonic commit counter: memtable versions are
    newer than any part's, parts are newest-first."""

    def __init__(self, name: str, memtable_limit: int = 4096):
        self.name = name
        self.memtable_limit = memtable_limit
        self._keys: list[tuple] = []  # sorted (memtable)
        self._chains: dict[tuple, list[tuple[int, Any]]] = {}
        self._parts: list[_Part] = []  # newest first

    def put(self, key: tuple, row: dict | None, version: int) -> None:
        """row=None erases (writes a tombstone version)."""
        chain = self._chains.get(key)
        if chain is None:
            idx = bisect.bisect_left(self._keys, key)
            self._keys.insert(idx, key)
            chain = []
            self._chains[key] = chain
        value = TOMBSTONE if row is None else dict(row)
        chain.insert(0, (version, value))
        if len(self._keys) >= self.memtable_limit:
            self.freeze_part()

    def freeze_part(self) -> None:
        """Memtable -> immutable part (newest). No-op when empty."""
        if not self._keys:
            return
        items = [(k, self._chains[k]) for k in self._keys]
        self._parts.insert(0, _Part(items))
        self._keys = []
        self._chains = {}

    def _full_chain(self, key: tuple) -> list:
        """Version chain across memtable + parts, newest first."""
        chain = list(self._chains.get(key) or ())
        for part in self._parts:
            pc = part.get_chain(key)
            if pc:
                chain.extend(pc)
        return chain

    def get(self, key: tuple, version: int | None = None) -> dict | None:
        for ver, value in self._full_chain(key):
            if version is None or ver <= version:
                return None if value is TOMBSTONE else value
        return None

    def _iter_merged(self, lo: tuple | None, hi: tuple | None):
        """(key, merged chain) in key order across memtable + parts:
        ONE heap pass that carries the chains (no per-key re-probing
        of every part — scans stay O(keys) regardless of part count).
        Stream priority (memtable=0, parts newest-first) preserves
        version-descending chain order on concat."""
        def mem():
            start = (0 if lo is None
                     else bisect.bisect_left(self._keys, lo))
            for i in range(start, len(self._keys)):
                k = self._keys[i]
                if hi is not None and k >= hi:
                    return
                yield k, 0, self._chains[k]

        streams = [mem()] + [
            ((k, pi + 1, c) for k, c in p.iter_items(lo, hi))
            for pi, p in enumerate(self._parts)
        ]
        cur_key = None
        cur_chain: list = []
        for k, _pri, chain in heapq.merge(*streams):
            if k != cur_key:
                if cur_key is not None:
                    yield cur_key, cur_chain
                cur_key, cur_chain = k, list(chain)
            else:
                cur_chain.extend(chain)
        if cur_key is not None:
            yield cur_key, cur_chain

    @staticmethod
    def _visible(chain: list, version: int | None):
        for ver, value in chain:
            if version is None or ver <= version:
                return None if value is TOMBSTONE else value
        return None

    def range(self, lo: tuple | None = None, hi: tuple | None = None,
              version: int | None = None,
              ) -> Iterator[tuple[tuple, dict]]:
        """Yield (key, row) in key order for lo <= key < hi at version."""
        for key, chain in self._iter_merged(lo, hi):
            row = self._visible(chain, version)
            if row is not None:
                yield key, row

    @property
    def n_parts(self) -> int:
        return len(self._parts)

    def bloom_negatives(self) -> int:
        return sum(p.bloom_negatives for p in self._parts)

    def compact(self, keep_after: int) -> None:
        """Merge every part back through the memtable and drop versions
        shadowed by a newer one at or below keep_after (no snapshot
        older than keep_after can still read them)."""
        # fold parts into merged chains (memtable newest, parts next)
        if self._parts:
            merged = {k: c for k, c in self._iter_merged(None, None)}
            self._keys = sorted(merged)
            self._chains = merged
            self._parts = []
        dead_keys = []
        for key, chain in self._chains.items():
            kept = []
            shadowed = False
            for ver, value in chain:
                if shadowed:
                    break
                kept.append((ver, value))
                if ver <= keep_after:
                    shadowed = True  # everything older is invisible
            # a sole tombstone older than the horizon is gone entirely
            if len(kept) == 1 and kept[0][1] is TOMBSTONE and \
                    kept[0][0] <= keep_after:
                dead_keys.append(key)
            else:
                self._chains[key] = kept
        for key in dead_keys:
            del self._chains[key]
            idx = bisect.bisect_left(self._keys, key)
            if idx < len(self._keys) and self._keys[idx] == key:
                self._keys.pop(idx)

    # ---- snapshot (de)serialization ----

    def dump(self) -> list:
        out = []
        for key, full in self._iter_merged(None, None):
            chain = [[ver, None if v is TOMBSTONE else v]
                     for ver, v in full]
            out.append([list(key), chain])
        return out

    @classmethod
    def load(cls, name: str, data: list,
             memtable_limit: int = 4096) -> "TableStore":
        t = cls(name, memtable_limit=memtable_limit)
        for key_list, chain in data:
            key = tuple(key_list)
            t._keys.append(key)
            t._chains[key] = [
                (ver, TOMBSTONE if v is None else v) for ver, v in chain
            ]
        if len(t._keys) >= t.memtable_limit:
            t.freeze_part()  # keep the freeze cadence across reloads
        return t


class LocalDb:
    def __init__(self):
        self.tables: dict[str, TableStore] = {}

    def table(self, name: str) -> TableStore:
        t = self.tables.get(name)
        if t is None:
            t = self.tables[name] = TableStore(name)
        return t

    def apply(self, changes: list[tuple], version: int) -> None:
        """changes: [(table, key, row_or_None[, explicit_version]), ...]

        The optional 4th element overrides the commit version — used by
        tablets whose row visibility follows the global plan-step clock
        (DataShard MVCC) rather than the tablet's own commit counter.
        """
        for ch in changes:
            table, key, row = ch[0], ch[1], ch[2]
            ver = ch[3] if len(ch) > 3 else version
            self.table(table).put(tuple(key), row, ver)

    def dump(self) -> dict:
        return {name: t.dump() for name, t in self.tables.items()}

    @classmethod
    def load(cls, data: dict) -> "LocalDb":
        db = cls()
        for name, tdata in data.items():
            db.tables[name] = TableStore.load(name, tdata)
        return db
