"""In-tablet sorted KV store with MVCC row versions.

Mirror of the reference's NTable local database (flat_database.h:41;
SURVEY.md §2.4): every tablet persists its state through one of these —
named tables of sorted rows, where each key holds a list of versioned
values so reads at an older snapshot still see the old row. The reference
keeps a memtable plus immutable B-tree parts; at the scale of host
control-plane state (schemas, tx queues, offsets — not user data) a
single sorted dict per table with explicit version chains carries the
same semantics, and ``freeze_part``/``compact`` keep the memtable/part
shape for the OLTP datashard built on top.

Rows are dict[str, value]; keys are tuples (the primary key columns).
Versions are monotonically increasing integers supplied by the executor
(the tablet's commit counter — the analog of the redo-log step).
"""

from __future__ import annotations

import bisect
from typing import Any, Iterator


TOMBSTONE = object()


class TableStore:
    """One table: sorted keys, each with a version chain (newest first)."""

    def __init__(self, name: str):
        self.name = name
        self._keys: list[tuple] = []  # sorted
        self._chains: dict[tuple, list[tuple[int, Any]]] = {}

    def put(self, key: tuple, row: dict | None, version: int) -> None:
        """row=None erases (writes a tombstone version)."""
        chain = self._chains.get(key)
        if chain is None:
            idx = bisect.bisect_left(self._keys, key)
            self._keys.insert(idx, key)
            chain = []
            self._chains[key] = chain
        value = TOMBSTONE if row is None else dict(row)
        chain.insert(0, (version, value))

    def get(self, key: tuple, version: int | None = None) -> dict | None:
        chain = self._chains.get(key)
        if not chain:
            return None
        for ver, value in chain:
            if version is None or ver <= version:
                return None if value is TOMBSTONE else value
        return None

    def range(self, lo: tuple | None = None, hi: tuple | None = None,
              version: int | None = None,
              ) -> Iterator[tuple[tuple, dict]]:
        """Yield (key, row) in key order for lo <= key < hi at version."""
        start = 0 if lo is None else bisect.bisect_left(self._keys, lo)
        for i in range(start, len(self._keys)):
            key = self._keys[i]
            if hi is not None and key >= hi:
                break
            row = self.get(key, version)
            if row is not None:
                yield key, row

    def compact(self, keep_after: int) -> None:
        """Drop versions shadowed by a newer one at or below keep_after
        (no snapshot older than keep_after can still read them)."""
        dead_keys = []
        for key, chain in self._chains.items():
            kept = []
            shadowed = False
            for ver, value in chain:
                if shadowed:
                    break
                kept.append((ver, value))
                if ver <= keep_after:
                    shadowed = True  # everything older is invisible
            # a sole tombstone older than the horizon is gone entirely
            if len(kept) == 1 and kept[0][1] is TOMBSTONE and \
                    kept[0][0] <= keep_after:
                dead_keys.append(key)
            else:
                self._chains[key] = kept
        for key in dead_keys:
            del self._chains[key]
            idx = bisect.bisect_left(self._keys, key)
            if idx < len(self._keys) and self._keys[idx] == key:
                self._keys.pop(idx)

    # ---- snapshot (de)serialization ----

    def dump(self) -> list:
        out = []
        for key in self._keys:
            chain = [
                [ver, None if v is TOMBSTONE else v]
                for ver, v in self._chains[key]
            ]
            out.append([list(key), chain])
        return out

    @classmethod
    def load(cls, name: str, data: list) -> "TableStore":
        t = cls(name)
        for key_list, chain in data:
            key = tuple(key_list)
            t._keys.append(key)
            t._chains[key] = [
                (ver, TOMBSTONE if v is None else v) for ver, v in chain
            ]
        return t


class LocalDb:
    def __init__(self):
        self.tables: dict[str, TableStore] = {}

    def table(self, name: str) -> TableStore:
        t = self.tables.get(name)
        if t is None:
            t = self.tables[name] = TableStore(name)
        return t

    def apply(self, changes: list[tuple], version: int) -> None:
        """changes: [(table, key, row_or_None[, explicit_version]), ...]

        The optional 4th element overrides the commit version — used by
        tablets whose row visibility follows the global plan-step clock
        (DataShard MVCC) rather than the tablet's own commit counter.
        """
        for ch in changes:
            table, key, row = ch[0], ch[1], ch[2]
            ver = ch[3] if len(ch) > 3 else version
            self.table(table).put(tuple(key), row, ver)

    def dump(self) -> dict:
        return {name: t.dump() for name, t in self.tables.items()}

    @classmethod
    def load(cls, data: dict) -> "LocalDb":
        db = cls()
        for name, tdata in data.items():
            db.tables[name] = TableStore.load(name, tdata)
        return db
