"""KeyValue tablet: durable KV storage over the tablet executor.

Mirror of the reference's keyvalue tablet (ydb/core/keyvalue/
keyvalue_impl.h; SURVEY §2.3 BlobDepot/keyvalue row): a tablet exposing
write/read/range/rename/delete-range/copy-range over its local DB, with
large values spilled to the blob store and referenced from rows (the
reference likewise keeps big values in BlobStorage and metadata in the
tablet). All mutations are executor transactions — WAL'd, replayed on
boot, fenced by generations — so the tablet survives crashes and moves
(Hive can reboot it on another node).

Blob lifecycle: spilled value blobs are written BEFORE the owning tx
commits (an orphan on crash is garbage, never a dangling ref — the same
write-then-commit order portions use) and deleted only AFTER the tx that
dropped the last reference commits (side-effect phase).
"""

from __future__ import annotations

import dataclasses
import itertools

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.executor import TabletExecutor, Transaction, TxContext
from ydb_tpu.tablet.hive import TabletActor

INLINE_LIMIT = 4096  # values beyond this spill to their own blob


@dataclasses.dataclass
class KvWrite:
    key: str
    value: bytes


@dataclasses.dataclass
class KvRead:
    key: str


@dataclasses.dataclass
class KvRange:
    lo: str | None = None
    hi: str | None = None
    limit: int = 1000


@dataclasses.dataclass
class KvRename:
    old: str
    new: str


@dataclasses.dataclass
class KvDeleteRange:
    lo: str | None = None
    hi: str | None = None


@dataclasses.dataclass
class KvCopyRange:
    lo: str | None
    hi: str | None
    prefix_to: str = ""


class _KvTx(Transaction):
    def __init__(self, fn):
        self._fn = fn
        self.side_effects: list = []  # blob ids to delete post-commit

    def execute(self, txc: TxContext, tablet) -> None:
        self._fn(txc, self)


class KeyValueTablet:
    """Core state machine (actor-free surface; KeyValueActor wraps it)."""

    def __init__(self, tablet_id: str, store: BlobStore,
                 executor: TabletExecutor | None = None):
        self.tablet_id = tablet_id
        self.store = store
        self.executor = (executor if executor is not None
                         else TabletExecutor.boot(tablet_id, store))
        self._blob_seq = itertools.count(
            self.executor.generation << 32)

    # -- helpers --

    def _row_value(self, row: dict) -> bytes:
        if row.get("blob") is not None:
            return self.store.get(row["blob"])
        return row["v"].encode("latin1")

    def _run(self, fn) -> list:
        tx = _KvTx(fn)
        self.executor.execute(tx)
        # post-commit side effects: now-unreferenced blobs
        for bid in tx.side_effects:
            self.store.delete(bid)
        return tx.side_effects

    # -- commands --

    def write(self, key: str, value: bytes) -> None:
        blob_id = None
        if len(value) > INLINE_LIMIT:
            blob_id = (f"{self.tablet_id}/kvblob/"
                       f"{next(self._blob_seq):016x}")
            self.store.put(blob_id, value)  # before commit: orphan-safe

        def fn(txc: TxContext, tx: _KvTx):
            old = txc.get("kv", (key,))
            if old is not None and old.get("blob"):
                tx.side_effects.append(old["blob"])
            if blob_id is not None:
                txc.put("kv", (key,), {"v": None, "blob": blob_id,
                                       "size": len(value)})
            else:
                txc.put("kv", (key,), {"v": value.decode("latin1"),
                                       "blob": None,
                                       "size": len(value)})

        self._run(fn)

    def read(self, key: str) -> bytes | None:
        row = self.executor.db.table("kv").get((key,))
        return None if row is None else self._row_value(row)

    def read_range(self, lo=None, hi=None, limit: int = 1000):
        out = []
        for (k,), row in self.executor.db.table("kv").range(
                (lo,) if lo is not None else None,
                (hi,) if hi is not None else None):
            out.append((k, self._row_value(row)))
            if len(out) >= limit:
                break
        return out

    def rename(self, old: str, new: str) -> bool:
        if old == new:
            # no-op rename must NOT release the row's own blob
            return self.executor.db.table("kv").get((old,)) is not None
        ok = [False]

        def fn(txc: TxContext, tx: _KvTx):
            row = txc.get("kv", (old,))
            if row is None:
                return
            dst = txc.get("kv", (new,))
            if dst is not None and dst.get("blob"):
                tx.side_effects.append(dst["blob"])
            txc.erase("kv", (old,))
            txc.put("kv", (new,), dict(row))
            ok[0] = True

        self._run(fn)
        return ok[0]

    def delete_range(self, lo=None, hi=None) -> int:
        n = [0]

        def fn(txc: TxContext, tx: _KvTx):
            for (k,), row in list(txc.range(
                    "kv",
                    (lo,) if lo is not None else None,
                    (hi,) if hi is not None else None)):
                if row.get("blob"):
                    tx.side_effects.append(row["blob"])
                txc.erase("kv", (k,))
                n[0] += 1

        self._run(fn)
        return n[0]

    def copy_range(self, lo=None, hi=None, prefix_to: str = "") -> int:
        """Copy [lo, hi) under a new key prefix (spilled blobs are
        duplicated — refs stay single-owner so deletes never dangle)."""
        n = [0]

        def fn(txc: TxContext, tx: _KvTx):
            for (k,), row in list(txc.range(
                    "kv",
                    (lo,) if lo is not None else None,
                    (hi,) if hi is not None else None)):
                dst_key = prefix_to + k
                dst = txc.get("kv", (dst_key,))
                if dst is not None and dst.get("blob"):
                    # overwrite releases the destination's spilled blob
                    # (self-copy included: the new row references a
                    # fresh duplicate, so the old blob is unreferenced)
                    tx.side_effects.append(dst["blob"])
                new_row = dict(row)
                if row.get("blob"):
                    new_blob = (f"{self.tablet_id}/kvblob/"
                                f"{next(self._blob_seq):016x}")
                    self.store.put(new_blob, self.store.get(row["blob"]))
                    new_row["blob"] = new_blob
                txc.put("kv", (dst_key,), new_row)
                n[0] += 1

        self._run(fn)
        return n[0]

    @staticmethod
    def boot(tablet_id: str, store: BlobStore) -> "KeyValueTablet":
        return KeyValueTablet(tablet_id, store)


class KeyValueActor(TabletActor):
    """Actor wrapper: KV commands over tablet pipes (keyvalue API)."""

    def __init__(self, tablet_id: str, executor: TabletExecutor):
        super().__init__(tablet_id, executor)
        self.kv = KeyValueTablet(tablet_id, executor.store,
                                 executor=executor)

    def handle(self, message, reply_to):
        if isinstance(message, KvWrite):
            self.kv.write(message.key, message.value)
            self.send(reply_to, ("ok", message.key))
        elif isinstance(message, KvRead):
            self.send(reply_to, ("value", self.kv.read(message.key)))
        elif isinstance(message, KvRange):
            self.send(reply_to, ("range", self.kv.read_range(
                message.lo, message.hi, message.limit)))
        elif isinstance(message, KvRename):
            self.send(reply_to, ("renamed", self.kv.rename(
                message.old, message.new)))
        elif isinstance(message, KvDeleteRange):
            self.send(reply_to, ("deleted", self.kv.delete_range(
                message.lo, message.hi)))
        elif isinstance(message, KvCopyRange):
            self.send(reply_to, ("copied", self.kv.copy_range(
                message.lo, message.hi, message.prefix_to)))
        else:
            self.send(reply_to, ("error", f"unknown command {message}"))
