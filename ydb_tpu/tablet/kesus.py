"""Kesus: distributed coordination tablet (semaphores, locks,
sessions) + SequenceShard (durable sequence ranges).

Mirror of the reference's coordination service and sequence tablet
(ydb/core/kesus/tablet: sessions, semaphores with counts/waiter
queues, ephemeral locks released on session death;
ydb/core/tx/sequenceshard: hi-lo durable sequence allocation;
SURVEY.md §2.5 "Sequences / Kesus / Locks"). Both are ordinary
tablets over the executor: every mutation is a WAL-committed
transaction, so coordination state (who holds which semaphore, the
next sequence range) survives reboot and moves with the tablet.

Semantics:
  * sessions attach with a timeout; ``tick(now)`` expires them and
    releases everything they held (the failure-recovery contract);
  * a semaphore has a ``limit``; acquire(count) succeeds when the sum
    of held counts + count <= limit, else the session queues as a
    waiter (FIFO) and is promoted on release; waiters carry their own
    deadline and lapse out of the queue un-promoted;
  * ephemeral semaphores (locks) are created on first acquire and
    vanish when the last holder releases — the distributed-lock shape;
  * sequences allocate a durable range of ``cache`` values per refill
    (either direction of increment), so a crash skips at most one
    range and never repeats a value.
"""

from __future__ import annotations

import time

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.executor import TabletExecutor


class KesusTablet:
    """Sessions + semaphores with durable state."""

    def __init__(self, tablet_id: str, store: BlobStore, now=time.time):
        self.executor = TabletExecutor.boot(f"kesus/{tablet_id}", store)
        self.now = now

    # ---- sessions ----

    def attach_session(self, timeout_s: float = 30.0,
                       description: str = "") -> int:
        def fn(txc):
            meta = self.executor.db.table("meta").get(("next_session",))
            sid = meta["v"] if meta else 1
            txc.put("meta", ("next_session",), {"v": sid + 1})
            txc.put("sessions", (sid,), {
                "timeout": timeout_s,
                "deadline": self.now() + timeout_s,
                "description": description,
            })
            return sid
        return self.executor.run(fn)

    def ping_session(self, sid: int) -> bool:
        def fn(txc):
            row = txc.get("sessions", (sid,))
            if row is None:
                return False
            txc.put("sessions", (sid,), dict(
                row, deadline=self.now() + row["timeout"]))
            return True
        return self.executor.run(fn)

    def detach_session(self, sid: int) -> None:
        self.executor.run(
            lambda txc: self._drop_session(txc, sid, frozenset({sid})))

    def _drop_session(self, txc, sid: int, dead: frozenset) -> None:
        """Drop one session. ``dead`` is the full set of sessions being
        dropped in THIS transaction: promotions must skip them (the
        localdb view inside a tx is the committed state, so an erased
        co-dead session still looks alive to reads)."""
        txc.erase("sessions", (sid,))
        for (name, pos), row in list(
                self.executor.db.table("waiters").range()):
            if row["session"] == sid:
                txc.erase("waiters", (name, pos))
        for (name, holder), _row in list(
                self.executor.db.table("holds").range()):
            if holder == sid:
                self._release_one(txc, sid, name, skip=dead)

    def tick(self, now: float | None = None) -> list[int]:
        """Expire dead sessions (releasing their holds) and lapsed
        waiters (failure detection + recovery for coordination state)."""
        now = self.now() if now is None else now

        def fn(txc):
            for (name, pos), row in list(
                    self.executor.db.table("waiters").range()):
                if row["deadline"] < now:
                    txc.erase("waiters", (name, pos))
            dead = frozenset(
                sid for (sid,), row in
                self.executor.db.table("sessions").range()
                if row["deadline"] < now)
            for sid in dead:
                self._drop_session(txc, sid, dead)
            return sorted(dead)
        return self.executor.run(fn)

    # ---- semaphores ----

    def create_semaphore(self, name: str, limit: int,
                         data: str = "") -> None:
        def fn(txc):
            if txc.get("semaphores", (name,)) is not None:
                raise ValueError(f"semaphore {name} exists")
            txc.put("semaphores", (name,), {
                "limit": limit, "data": data, "ephemeral": False,
                "next_waiter": 0,
            })
        self.executor.run(fn)

    def delete_semaphore(self, name: str) -> None:
        def fn(txc):
            holds = [k for k, _ in
                     self.executor.db.table("holds").range()
                     if k[0] == name]
            if holds:
                raise ValueError(f"semaphore {name} is held")
            for (n, pos), _row in list(
                    self.executor.db.table("waiters").range()):
                if n == name:  # stale waiters must not survive into a
                    txc.erase("waiters", (n, pos))  # recreated name
            txc.erase("semaphores", (name,))
        self.executor.run(fn)

    def _held(self, name: str, exclude: frozenset = frozenset()) -> int:
        return sum(row["count"] for (n, sid), row in
                   self.executor.db.table("holds").range()
                   if n == name and sid not in exclude)

    def acquire(self, sid: int, name: str, count: int = 1,
                ephemeral: bool = False, timeout_s: float = 0.0) -> bool:
        """True = acquired now; False = queued as waiter (or rejected
        when timeout_s == 0 and the semaphore is full)."""
        def fn(txc):
            if txc.get("sessions", (sid,)) is None:
                raise ValueError(f"no session {sid}")
            sem = txc.get("semaphores", (name,))
            if sem is None:
                if not ephemeral:
                    raise ValueError(f"no semaphore {name}")
                sem = {"limit": count, "data": "", "ephemeral": True,
                       "next_waiter": 0}
                txc.put("semaphores", (name,), sem)
            cur = txc.get("holds", (name, sid))
            if cur is not None:
                return True  # re-acquire is idempotent
            for (n, _pos), row in \
                    self.executor.db.table("waiters").range():
                if n == name and row["session"] == sid:
                    return False  # already queued: no duplicate waiter
            if self._held(name) + count <= sem["limit"]:
                txc.put("holds", (name, sid), {"count": count})
                return True
            if timeout_s <= 0:
                return False
            pos = sem["next_waiter"]
            txc.put("semaphores", (name,), dict(
                sem, next_waiter=pos + 1))
            txc.put("waiters", (name, pos), {
                "session": sid, "count": count,
                "deadline": self.now() + timeout_s,
            })
            return False
        return self.executor.run(fn)

    def release(self, sid: int, name: str) -> list[int]:
        """Release; returns sessions promoted from the waiter queue."""
        return self.executor.run(
            lambda txc: self._release_one(txc, sid, name))

    def _release_one(self, txc, sid: int, name: str,
                     skip: frozenset = frozenset()) -> list[int]:
        if self.executor.db.table("holds").get((name, sid)) is None:
            return []
        txc.erase("holds", (name, sid))
        sem = txc.get("semaphores", (name,))
        promoted = []
        now = self.now()
        # remaining held count, excluding the hold just erased and any
        # co-dropping sessions (in-tx erasures are invisible to reads)
        held = self._held(name, exclude=skip | {sid})
        for (n, pos), row in list(
                self.executor.db.table("waiters").range()):
            if n != name:
                continue
            if row["session"] in skip or row["deadline"] < now:
                txc.erase("waiters", (n, pos))
                continue
            if held + row["count"] <= sem["limit"]:
                txc.erase("waiters", (n, pos))
                txc.put("holds", (name, row["session"]),
                        {"count": row["count"]})
                held += row["count"]
                promoted.append(row["session"])
        if sem is not None and sem["ephemeral"] and held == 0 \
                and not promoted:
            # fully-released ephemeral lock vanishes; any never-
            # promotable waiters must go with it, or they would
            # resurrect under an unrelated recreation of the name
            for (n, pos), _row in list(
                    self.executor.db.table("waiters").range()):
                if n == name:
                    txc.erase("waiters", (n, pos))
            txc.erase("semaphores", (name,))
        return promoted

    def describe(self, name: str) -> dict:
        sem = self.executor.db.table("semaphores").get((name,))
        if sem is None:
            raise KeyError(name)
        owners = {sid: row["count"] for (n, sid), row in
                  self.executor.db.table("holds").range() if n == name}
        waiters = [row["session"] for (n, _pos), row in
                   self.executor.db.table("waiters").range()
                   if n == name]
        return {"limit": sem["limit"], "data": sem["data"],
                "ephemeral": sem["ephemeral"], "owners": owners,
                "waiters": waiters}


class SequenceShard:
    """Durable sequence allocator (hi-lo ranges, either direction)."""

    def __init__(self, tablet_id: str, store: BlobStore):
        self.executor = TabletExecutor.boot(
            f"sequence/{tablet_id}", store)
        # name -> (next_value, values_remaining, increment); the lock
        # serializes the cache's read-modify-write so concurrent
        # nextval callers never receive the same value
        self._cache: dict[str, tuple[int, int, int]] = {}
        import threading

        self._lock = threading.Lock()

    def create_sequence(self, name: str, start: int = 1,
                        increment: int = 1, cache: int = 100) -> None:
        if increment == 0:
            raise ValueError("increment must be nonzero")
        if cache < 1:
            # cache 0 would never advance the durable counter: every
            # nextval would return the same value forever
            raise ValueError("cache must be >= 1")

        def fn(txc):
            if txc.get("sequences", (name,)) is not None:
                raise ValueError(f"sequence {name} exists")
            txc.put("sequences", (name,), {
                "next": start, "increment": increment, "cache": cache,
            })
        self.executor.run(fn)

    def drop_sequence(self, name: str) -> None:
        def fn(txc):
            txc.erase("sequences", (name,))
        self.executor.run(fn)
        # a nextval caching a fresh range concurrently with the drop
        # must not resurrect the entry after this pop
        with self._lock:
            self._cache.pop(name, None)

    def next_val(self, name: str) -> int:
      with self._lock:
        val, remaining, inc = self._cache.get(name, (0, 0, 1))
        if remaining <= 0:
            def fn(txc):
                row = txc.get("sequences", (name,))
                if row is None:
                    raise KeyError(f"no sequence {name}")
                nxt = row["next"]
                top = nxt + row["cache"] * row["increment"]
                txc.put("sequences", (name,), dict(row, next=top))
                return nxt, row["cache"], row["increment"]
            # the whole range is durable BEFORE any value is handed out
            val, remaining, inc = self.executor.run(fn)
        self._cache[name] = (val + inc, remaining - 1, inc)
        return val
