"""State storage: replicated registry of tablet leaders.

Mirror of the reference's StateStorage (core/base/statestorage.cpp,
statestorage_proxy.cpp; SURVEY.md §2.4): a quorum ring of replica actors
holding (tablet_id -> leader actor, generation) in memory only — the
truth about *who currently leads* a tablet lives here, while the truth
about the tablet's *state* lives in the blob store. Updates carry the
boot generation; a replica accepts only non-decreasing generations, so a
zombie leader can never overwrite its successor's registration. Lookups
read a majority and take the max-generation answer.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ydb_tpu.runtime.actors import Actor, ActorId


@dataclasses.dataclass
class SSUpdate:
    tablet_id: str
    leader: ActorId
    generation: int
    cookie: Any = None


@dataclasses.dataclass
class SSUpdateAck:
    tablet_id: str
    accepted: bool
    cookie: Any = None


@dataclasses.dataclass
class SSLookup:
    tablet_id: str
    cookie: Any = None


@dataclasses.dataclass
class SSLookupReply:
    tablet_id: str
    leader: ActorId | None
    generation: int
    cookie: Any = None


@dataclasses.dataclass
class SSDelete:
    tablet_id: str


class StateStorageReplica(Actor):
    def __init__(self):
        super().__init__()
        self.entries: dict[str, tuple[ActorId, int]] = {}

    def receive(self, message, sender):
        if isinstance(message, SSUpdate):
            cur = self.entries.get(message.tablet_id)
            accepted = cur is None or message.generation >= cur[1]
            if accepted:
                self.entries[message.tablet_id] = (
                    message.leader, message.generation)
            self.send(sender, SSUpdateAck(
                message.tablet_id, accepted, message.cookie))
        elif isinstance(message, SSLookup):
            cur = self.entries.get(message.tablet_id)
            leader, gen = (cur if cur else (None, 0))
            self.send(sender, SSLookupReply(
                message.tablet_id, leader, gen, message.cookie))
        elif isinstance(message, SSDelete):
            self.entries.pop(message.tablet_id, None)


class StateStorageProxy(Actor):
    """Per-node proxy: fans requests to all replicas, answers the caller
    once a majority agrees (statestorage_proxy.cpp shape).

    Client protocol: send SSUpdate/SSLookup to the proxy; it replies with
    SSUpdateAck / SSLookupReply (max-generation winner).
    """

    def __init__(self, replicas: list[ActorId]):
        super().__init__()
        self.replicas = list(replicas)
        self._pending: dict[int, dict] = {}
        self._next_req = 0

    def _majority(self) -> int:
        return len(self.replicas) // 2 + 1

    def receive(self, message, sender):
        if isinstance(message, (SSUpdate, SSLookup)):
            req_id = self._next_req
            self._next_req += 1
            self._pending[req_id] = {
                "caller": sender, "message": message, "replies": [],
                "done": False,
            }
            inner = dataclasses.replace(message, cookie=(req_id,
                                                         message.cookie))
            for rep in self.replicas:
                self.send(rep, inner)
        elif isinstance(message, SSDelete):
            for rep in self.replicas:
                self.send(rep, message)
        elif isinstance(message, (SSUpdateAck, SSLookupReply)):
            req_id, orig_cookie = message.cookie
            st = self._pending.get(req_id)
            if st is None or st["done"]:
                return
            st["replies"].append(message)
            if len(st["replies"]) >= self._majority():
                st["done"] = True
                if isinstance(message, SSUpdateAck):
                    ok = all(r.accepted for r in st["replies"])
                    self.send(st["caller"], SSUpdateAck(
                        message.tablet_id, ok, orig_cookie))
                else:
                    best = max(st["replies"], key=lambda r: r.generation)
                    self.send(st["caller"], SSLookupReply(
                        message.tablet_id, best.leader, best.generation,
                        orig_cookie))
