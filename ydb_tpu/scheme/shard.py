"""SchemeShard: the schema tablet.

Mirror of the reference's SchemeShard (TSchemeShard
tx/schemeshard/schemeshard_impl.h:75; one persisted operation per DDL in
schemeshard__operation_*.cpp; SURVEY.md §2.5): the single durable owner
of the path tree and every table description. All DDL runs as a tablet
transaction (ydb_tpu.tablet.executor), so the whole schema survives
reboot-anywhere; each mutation is also journaled to an operations table
(the persisted multi-phase-operation analog — ops here commit in one
phase since shard creation is delegated to the hosting layer).

Publication: every successful DDL invokes the registered listeners with
(path, description-or-None, version) — the populator edge of the scheme
board (populator.h), which fans descriptions out to replicas and on to
per-node scheme caches (ydb_tpu.scheme.board).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ydb_tpu import dtypes
from ydb_tpu.scheme.model import TableDescription, type_from_str as _type
from ydb_tpu.tablet.executor import TabletExecutor, Transaction, TxContext
from ydb_tpu.tablet.hive import TabletActor


class SchemeError(Exception):
    pass


def _split(path: str) -> list[str]:
    return [p for p in path.strip("/").split("/") if p]


def _parent(path: str) -> str:
    parts = _split(path)
    return "/" + "/".join(parts[:-1]) if len(parts) > 1 else "/"


def _norm(path: str) -> str:
    return "/" + "/".join(_split(path))


class _DdlTx(Transaction):
    def __init__(self, fn: Callable[[TxContext], None]):
        self.fn = fn

    def execute(self, txc, tablet):
        self.fn(txc)


class SchemeShardCore:
    """Synchronous schema engine over a tablet executor. The actor-facing
    SchemeShardTablet wraps this; in-process clusters call it directly."""

    def __init__(self, executor: TabletExecutor):
        self.executor = executor
        self.listeners: list[Callable[[str, dict | None, int], None]] = []
        db = executor.db
        if db.table("paths").get(("/",)) is None:
            self._run(lambda txc: txc.put(
                "paths", ("/",), {"type": "dir", "version": 1}))

    # ---- internals ----

    def _run(self, fn) -> None:
        self.executor.execute(_DdlTx(fn))

    def _publish(self, path: str, desc: dict | None, version: int) -> None:
        for fn in self.listeners:
            fn(path, desc, version)

    def _next_op_id(self) -> int:
        row = self.executor.db.table("meta").get(("next_op",))
        return row["v"] if row else 1

    def _journal(self, txc: TxContext, kind: str, path: str,
                 detail: dict | None = None) -> int:
        """Persist the op; the returned op id doubles as the scheme
        board publish version — globally monotonic across ALL ops, so a
        replayed stale update can never beat a later delete/re-create."""
        op_id = self._next_op_id()
        txc.put("ops", (op_id,), {
            "kind": kind, "path": path, "detail": detail or {},
        })
        txc.put("meta", ("next_op",), {"v": op_id + 1})
        return op_id

    # ---- reads ----

    def describe(self, path: str) -> TableDescription | None:
        row = self.executor.db.table("tables").get((_norm(path),))
        return TableDescription.from_json(row) if row else None

    def exists(self, path: str) -> bool:
        return self.executor.db.table("paths").get((_norm(path),)) is not None

    def kind(self, path: str) -> str | None:
        row = self.executor.db.table("paths").get((_norm(path),))
        return row["type"] if row else None

    def children(self, path: str) -> list[str]:
        base = _norm(path)
        prefix = base if base.endswith("/") else base + "/"
        out = []
        for (p,), _row in self.executor.db.table("paths").range():
            if p != base and p.startswith(prefix) and \
                    "/" not in p[len(prefix):]:
                out.append(p)
        return out

    def list_tables(self) -> list[TableDescription]:
        return [TableDescription.from_json(row)
                for _k, row in self.executor.db.table("tables").range()]

    def operations_log(self) -> list[dict]:
        return [dict(row, op_id=k[0])
                for k, row in self.executor.db.table("ops").range()]

    # ---- DDL ops (one schemeshard__operation_*.cpp analog each) ----

    def mkdir(self, path: str) -> None:
        path = _norm(path)
        if self.exists(path):
            raise SchemeError(f"path {path} already exists")
        self._ensure_parent(path)

        def fn(txc):
            txc.put("paths", (path,), {"type": "dir", "version": 1})
            self._journal(txc, "mkdir", path)

        self._run(fn)

    def _ensure_parent(self, path: str) -> None:
        parent = _parent(path)
        k = self.kind(parent)
        if k is None:
            raise SchemeError(f"parent {parent} does not exist")
        if k != "dir":
            raise SchemeError(f"parent {parent} is not a directory")

    # ---- path ACLs (library/aclib + schemeshard ACL analog) ----

    PERMS = frozenset({"read", "write", "ddl", "grant", "full"})

    def grant(self, path: str, subject: str, perms) -> None:
        """Grant permissions on ``path`` (inherited by the subtree) to
        ``subject`` (an auth token / principal name)."""
        path = _norm(path)
        perms = {perms} if isinstance(perms, str) else set(perms)
        bad = perms - self.PERMS
        if bad:
            raise SchemeError(f"unknown permission(s) {sorted(bad)}")
        if path != "/" and not self.exists(path):
            raise SchemeError(f"no path {path}")

        def fn(txc):
            cur = txc.get("acl", (path, subject))
            have = set(cur["perms"]) if cur else set()
            txc.put("acl", (path, subject),
                    {"perms": sorted(have | perms)})
            self._journal(txc, "grant", path)
        self._run(fn)

    def revoke(self, path: str, subject: str, perms=None) -> None:
        """Revoke (some or all) permissions of ``subject`` on ``path``."""
        path = _norm(path)
        if perms is not None:
            drop = {perms} if isinstance(perms, str) else set(perms)
            bad = drop - self.PERMS
            if bad:  # a typo'd revoke must not silently keep access
                raise SchemeError(
                    f"unknown permission(s) {sorted(bad)}")

        def fn(txc):
            cur = txc.get("acl", (path, subject))
            if cur is None:
                return
            if perms is None:
                txc.erase("acl", (path, subject))
            else:
                drop = {perms} if isinstance(perms, str) else set(perms)
                left = sorted(set(cur["perms"]) - drop)
                if left:
                    txc.put("acl", (path, subject), {"perms": left})
                else:
                    txc.erase("acl", (path, subject))
            self._journal(txc, "revoke", path)
        self._run(fn)

    def access_list(self, path: str) -> dict[str, list[str]]:
        path = _norm(path)
        return {subj: row["perms"] for (p, subj), row in
                self.executor.db.table("acl").range()
                if p == path}

    def acl_enabled(self) -> bool:
        """Enforcement is on once ANY ACE exists (bootstrap-friendly:
        a cluster without configured ACLs keeps token-only auth)."""
        for _k, _row in self.executor.db.table("acl").range():
            return True
        return False

    def check_access(self, subject: str, path: str, perm: str) -> bool:
        """True when an ACE on ``path`` or any ancestor grants
        ``subject`` the permission (or "full")."""
        path = _norm(path)
        acl = self.executor.db.table("acl")
        probe = path
        while True:
            row = acl.get((probe, subject))
            if row is not None and (
                    perm in row["perms"] or "full" in row["perms"]):
                return True
            if probe == "/":
                return False
            probe = _parent(probe)

    def create_table(self, desc: TableDescription) -> None:
        path = _norm(desc.path)
        if self.exists(path):
            raise SchemeError(f"path {path} already exists")
        self._ensure_parent(path)
        for pk in desc.primary_key:
            if pk not in desc.schema:
                raise SchemeError(f"primary key column {pk} not in schema")
        desc = dataclasses.replace(desc, path=path, schema_version=1)
        d = desc.to_json()
        pub = {}

        def fn(txc):
            txc.put("paths", (path,), {"type": "table", "version": 1})
            txc.put("tables", (path,), d)
            pub["v"] = self._journal(txc, "create_table", path)

        self._run(fn)
        self._publish(path, d, pub["v"])

    def drop_table(self, path: str,
                   trash_prefixes: list[str] = ()) -> None:
        """``trash_prefixes``: blob-store prefixes of the table's shard
        state, recorded durably IN the drop transaction; the hosting
        layer deletes them and calls clear_trash, and sweeps leftovers
        on boot — a crash between drop and delete can never resurrect
        rows under a recreated name."""
        path = _norm(path)
        if self.kind(path) != "table":
            raise SchemeError(f"{path} is not a table")
        pub = {}

        def fn(txc):
            txc.erase("paths", (path,))
            txc.erase("tables", (path,))
            pub["v"] = self._journal(txc, "drop_table", path)
            if trash_prefixes:
                txc.put("trash", (pub["v"],),
                        {"prefixes": list(trash_prefixes)})

        self._run(fn)
        self._publish(path, None, pub["v"])

    # ---- trash (deferred storage cleanup) ----

    def trash(self) -> list[tuple[int, list[str]]]:
        return [(k[0], row["prefixes"])
                for k, row in self.executor.db.table("trash").range()]

    def clear_trash(self, op_id: int) -> None:
        self._run(lambda txc: txc.erase("trash", (op_id,)))

    # ---- pending column strips (crash-safe row DROP COLUMN) ----

    def mark_strip(self, path: str) -> None:
        self._run(lambda txc: txc.put("strips", (_norm(path),), {}))

    def clear_strip(self, path: str) -> None:
        self._run(lambda txc: txc.erase("strips", (_norm(path),)))

    def pending_strips(self) -> set[str]:
        return {k[0] for k, _ in
                self.executor.db.table("strips").range()}

    def alter_table(
        self,
        path: str,
        add_columns: list[dtypes.Field] = (),
        drop_columns: list[str] = (),
        ttl_column: str | None | bool = False,  # False = unchanged
    ) -> TableDescription:
        path = _norm(path)
        desc = self.describe(path)
        if desc is None:
            raise SchemeError(f"{path} is not a table")
        fields = list(desc.schema.fields)
        new_version = desc.schema_version + 1
        column_added = dict(desc.column_added)
        for f in add_columns:
            if f.name in desc.schema:
                raise SchemeError(f"column {f.name} already exists")
            if not f.nullable:
                raise SchemeError(
                    "added columns must be nullable (existing rows have "
                    "no value)")
            fields.append(f)
            column_added[f.name] = new_version
        for name in drop_columns:
            if name in desc.primary_key:
                raise SchemeError(f"cannot drop key column {name}")
            if name not in desc.schema:
                raise SchemeError(f"no column {name}")
            fields = [f for f in fields if f.name != name]
            column_added.pop(name, None)
        desc = dataclasses.replace(
            desc,
            schema=dtypes.Schema(tuple(fields)),
            ttl_column=(desc.ttl_column if ttl_column is False
                        else ttl_column),
            schema_version=new_version,
            column_added=column_added,
        )
        d = desc.to_json()
        pub = {}

        def fn(txc):
            row = dict(txc.get("paths", (path,)))
            row["version"] = desc.schema_version
            txc.put("paths", (path,), row)
            txc.put("tables", (path,), d)
            pub["v"] = self._journal(txc, "alter_table", path, {
                "add": [f.name for f in add_columns],
                "drop": list(drop_columns),
            })

        self._run(fn)
        self._publish(path, d, pub["v"])
        return desc

    def reshard_table(self, path: str, n_shards: int,
                      shard_gen: int) -> TableDescription:
        """Record a completed split/merge: the new shard count +
        generation become THE durable truth in one journaled DDL tx
        (the datashard split/merge commit point,
        schemeshard__operation_split_merge.cpp)."""
        path = _norm(path)
        desc = self.describe(path)
        if desc is None:
            raise SchemeError(f"{path} is not a table")
        if n_shards < 1:
            raise SchemeError("n_shards must be >= 1")
        if shard_gen <= desc.shard_gen:
            raise SchemeError(
                f"shard_gen must advance ({shard_gen} <="
                f" {desc.shard_gen})")
        desc = dataclasses.replace(
            desc, n_shards=n_shards, shard_gen=shard_gen)
        d = desc.to_json()
        pub = {}

        def fn(txc):
            row = dict(txc.get("paths", (path,)))
            row["version"] = row.get("version", 1) + 1
            txc.put("paths", (path,), row)
            txc.put("tables", (path,), d)
            pub["v"] = self._journal(txc, "reshard_table", path, {
                "n_shards": n_shards, "shard_gen": shard_gen,
            })

        self._run(fn)
        self._publish(path, d, pub["v"])
        return desc


class SchemeShardTablet(TabletActor):
    """Actor wrapper: DDL over tablet pipes; replies ("ok", result_json)
    or ("error", text)."""

    def __init__(self, tablet_id: str, executor: TabletExecutor):
        super().__init__(tablet_id, executor)
        self.core = SchemeShardCore(executor)
        self.core.listeners.append(self._on_publish)
        self.board: "ActorId | None" = None  # set post-register

    def _on_publish(self, path, desc, version):
        if self.board is not None:
            from ydb_tpu.scheme.board import BoardPublish

            self.send(self.board, BoardPublish(path, desc, version))

    def handle(self, message, reply_to):
        op, args = message[0], message[1:]
        try:
            if op == "mkdir":
                self.core.mkdir(args[0])
                result = None
            elif op == "create_table":
                self.core.create_table(TableDescription.from_json(args[0]))
                result = None
            elif op == "drop_table":
                self.core.drop_table(args[0])
                result = None
            elif op == "alter_table":
                desc = self.core.alter_table(
                    args[0],
                    add_columns=[dtypes.Field(n, _type(ts), True)
                                 for n, ts in args[1]],
                    drop_columns=list(args[2]),
                )
                result = desc.to_json()
            elif op == "describe":
                desc = self.core.describe(args[0])
                result = desc.to_json() if desc else None
            elif op == "children":
                result = self.core.children(args[0])
            else:
                raise SchemeError(f"unknown op {op}")
            self.send(reply_to, ("ok", result))
        except SchemeError as e:
            self.send(reply_to, ("error", str(e)))
