"""Schema object model + JSON serialization.

The reference describes every scheme entity with protobuf path
descriptions flowing from SchemeShard through the scheme board to
per-node caches (TPathDescription; SURVEY.md §2.5). This is the
equivalent wire model: table descriptions as JSON-able dicts, so they
can live in tablet-executor state and cross the scheme board.
"""

from __future__ import annotations

import dataclasses

from ydb_tpu import dtypes


def type_to_str(t: dtypes.LogicalType) -> str:
    if t.is_decimal:
        return f"decimal({t.scale})"
    return t.kind.value


def type_from_str(s: str) -> dtypes.LogicalType:
    if s.startswith("decimal("):
        return dtypes.decimal(int(s[8:-1]))
    return dtypes.LogicalType(dtypes.Kind(s))


def schema_to_json(schema: dtypes.Schema) -> list:
    return [[f.name, type_to_str(f.type), f.nullable]
            for f in schema.fields]


def schema_from_json(data: list) -> dtypes.Schema:
    return dtypes.Schema(tuple(
        dtypes.Field(name, type_from_str(ts), nullable)
        for name, ts, nullable in data
    ))


@dataclasses.dataclass
class TableDescription:
    path: str
    schema: dtypes.Schema
    primary_key: tuple[str, ...]
    n_shards: int = 4
    store: str = "column"          # "column" (OLAP) | "row" (OLTP)
    ttl_column: str | None = None
    schema_version: int = 1
    # column name -> schema version that (re)introduced it; absent means
    # the column existed from version 1 (guards DROP+ADD resurrection)
    column_added: dict = dataclasses.field(default_factory=dict)
    # row tables: emit a CDC changefeed topic "<name>_changefeed"
    changefeed: bool = False
    # column tables: PK upsert semantics (a re-written key shadows the
    # old row; scans merge by PK newest-wins) — the reference's OLAP
    # REPLACE/BulkUpsert write model
    upsert: bool = False
    # shard generation: bumped by RESHARD (split/merge); generation g>0
    # stores shard state under <name>/g<g>/<i> so the cutover is an
    # atomic descriptor update (datashard split/merge analog)
    shard_gen: int = 0

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "schema": schema_to_json(self.schema),
            "primary_key": list(self.primary_key),
            "n_shards": self.n_shards,
            "store": self.store,
            "ttl_column": self.ttl_column,
            "schema_version": self.schema_version,
            "column_added": dict(self.column_added),
            "changefeed": self.changefeed,
            "upsert": self.upsert,
            "shard_gen": self.shard_gen,
        }

    @classmethod
    def from_json(cls, d: dict) -> "TableDescription":
        return cls(
            path=d["path"],
            schema=schema_from_json(d["schema"]),
            primary_key=tuple(d["primary_key"]),
            n_shards=d["n_shards"],
            store=d.get("store", "column"),
            ttl_column=d.get("ttl_column"),
            schema_version=d.get("schema_version", 1),
            column_added=dict(d.get("column_added", {})),
            changefeed=d.get("changefeed", False),
            upsert=d.get("upsert", False),
            shard_gen=d.get("shard_gen", 0),
        )
