from ydb_tpu.scheme.model import (
    TableDescription, schema_from_json, schema_to_json,
)
from ydb_tpu.scheme.shard import SchemeError, SchemeShardCore

__all__ = [
    "TableDescription", "schema_from_json", "schema_to_json",
    "SchemeError", "SchemeShardCore",
]
