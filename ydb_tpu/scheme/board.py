"""Scheme board: eventually-consistent pub/sub of path descriptions.

Mirror of the reference's scheme board (populator.h -> replica.h ->
subscriber.h, per-node cache tx/scheme_cache/; SURVEY.md §2.5): the
SchemeShard (populator) pushes every path description change to a set of
replica actors; per-node SchemeCache actors subscribe to a replica and
keep the latest-version description of each path, so query compilation
resolves tables without a round trip to the schema tablet. Versions make
the propagation idempotent and order-insensitive: a replica or cache
only applies a strictly newer version (or a deletion at version 0 that
outruns a stale update).
"""

from __future__ import annotations

import dataclasses

from ydb_tpu.runtime.actors import Actor, ActorId


@dataclasses.dataclass
class BoardPublish:
    path: str
    desc: dict | None     # None = deleted
    version: int


@dataclasses.dataclass
class BoardSubscribe:
    pass


@dataclasses.dataclass
class BoardSnapshot:
    entries: dict  # path -> (desc, version)


class SchemeBoardReplica(Actor):
    def __init__(self):
        super().__init__()
        self.entries: dict[str, tuple[dict | None, int]] = {}
        self.subscribers: list[ActorId] = []

    def _apply(self, message: BoardPublish) -> bool:
        # versions are globally monotonic scheme-op ids (deletes carry
        # one too), so plain newest-wins is order-insensitive even
        # across delete + re-create of the same path
        cur = self.entries.get(message.path)
        if cur is not None and message.version <= cur[1]:
            return False
        self.entries[message.path] = (message.desc, message.version)
        return True

    def receive(self, message, sender):
        if isinstance(message, BoardPublish):
            if self._apply(message):
                for sub in self.subscribers:
                    self.send(sub, message)
        elif isinstance(message, BoardSubscribe):
            self.subscribers.append(sender)
            self.send(sender, BoardSnapshot(dict(self.entries)))


class SchemeCache(Actor):
    """Per-node cache (tx/scheme_cache analog): resolve() is the sync
    read used by compilation on that node."""

    def __init__(self, replica: ActorId):
        super().__init__()
        self.replica = replica
        self.entries: dict[str, tuple[dict | None, int]] = {}

    def on_start(self):
        self.send(self.replica, BoardSubscribe())

    def receive(self, message, sender):
        if isinstance(message, BoardSnapshot):
            for path, (desc, ver) in message.entries.items():
                self._apply(BoardPublish(path, desc, ver))
        elif isinstance(message, BoardPublish):
            self._apply(message)

    def _apply(self, message: BoardPublish):
        cur = self.entries.get(message.path)
        if cur is not None and message.version <= cur[1]:
            return
        self.entries[message.path] = (message.desc, message.version)

    def resolve(self, path: str) -> dict | None:
        cur = self.entries.get(path)
        return cur[0] if cur else None
