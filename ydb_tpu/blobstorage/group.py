"""Blob groups: VDisks, topology, and the quorum DSProxy.

Mirror of the reference's group machinery (SURVEY.md §2.3): a group is a
set of disks across fail domains (TBlobStorageGroupInfo
groupinfo/blobstorage_groupinfo.h:65); clients talk to a per-group
DSProxy which erasure-encodes puts across the disks
(dsproxy_put.cpp:29), reads with reconstruction when disks are down
(restore-on-read, dsproxy_get.cpp:34), and the controller replaces
broken disks and rebuilds their parts (self-heal
mind/bscontroller/self_heal.cpp + vdisk repl).

VDisk here is the per-disk part store (the hull LSM collapsed to a KV
namespace on a host BlobStore); ``down`` simulates disk death for
tests/nemesis.
"""

from __future__ import annotations

import json

from ydb_tpu.blobstorage.erasure import ErasureCodec
from ydb_tpu.common import fnv1a_64
from ydb_tpu.engine.blobs import BlobStore, MemBlobStore


class DiskDown(Exception):
    pass


class VDisk:
    def __init__(self, disk_id: str, backing: BlobStore | None = None):
        self.disk_id = disk_id
        self.backing = backing if backing is not None else MemBlobStore()
        self.down = False

    def _key(self, blob_id: str, part: int) -> str:
        return f"vdisk/{self.disk_id}/{part}/{blob_id}"

    def put_part(self, blob_id: str, part: int, data: bytes) -> None:
        if self.down:
            raise DiskDown(self.disk_id)
        self.backing.put(self._key(blob_id, part), data)

    def get_part(self, blob_id: str, part: int) -> bytes:
        if self.down:
            raise DiskDown(self.disk_id)
        return self.backing.get(self._key(blob_id, part))

    def has_part(self, blob_id: str, part: int) -> bool:
        if self.down:
            raise DiskDown(self.disk_id)
        return self.backing.exists(self._key(blob_id, part))

    def delete_part(self, blob_id: str, part: int) -> None:
        if self.down:
            raise DiskDown(self.disk_id)
        self.backing.delete(self._key(blob_id, part))

    def list_parts(self, part: int, prefix: str = "") -> list[str]:
        if self.down:
            raise DiskDown(self.disk_id)
        full = f"vdisk/{self.disk_id}/{part}/{prefix}"
        skip = len(full) - len(prefix)
        return [k[skip:] for k in self.backing.list(full)]


class GroupInfo:
    """Topology: one disk per (fail domain, part slot). Part i of a blob
    lands on disk (i + rotation(blob)) % n — the reference's blob->disk
    mapper keeps load even the same way (groupinfo.h:274)."""

    def __init__(self, group_id: int, species: str = "block42",
                 disks: list[VDisk] | None = None):
        self.group_id = group_id
        self.codec = ErasureCodec(species)
        n = self.codec.total_parts
        self.disks = disks if disks is not None else [
            VDisk(f"g{group_id}-d{i}") for i in range(n)
        ]
        if len(self.disks) != n:
            raise ValueError(
                f"{species} needs exactly {n} disks per group")

    def disk_for(self, blob_id: str, part: int) -> VDisk:
        rot = hash_rotation(blob_id, len(self.disks))
        return self.disks[(part + rot) % len(self.disks)]


def hash_rotation(blob_id: str, n: int) -> int:
    return fnv1a_64(blob_id) % n


class DSProxy:
    """Per-group client: erasure put/get with quorum + restore-on-read.

    Blobs are stored under versioned ids (``blob_id@seq``, the TLogoBlobID
    analog: reference blobs are immutable and never overwritten in place),
    so an overwrite — or a failed overwrite during a disk outage — never
    touches the parts of the previous version. Parts that cannot land on
    their designated disk go to handoff slots on surviving disks (the
    reference's handoff placement, dsproxy_put.cpp); the write quorum
    demands every part written AND at least total-max_lost distinct
    disks, so the advertised loss tolerance is real for a healthy group
    and degrades only as far as the live topology forces it to.
    """

    META_PART = 255  # per-blob metadata (orig length) replicated broadly

    def __init__(self, group: GroupInfo):
        self.group = group
        self.codec = group.codec
        # synclog-lite (vdisk/syncer analog): an append-only log of
        # committed writes + a per-disk watermark of the log position
        # that disk has fully applied. A disk that was DOWN during
        # writes falls behind; resync() replays the gap so the rejoined
        # replica converges in the background instead of only via
        # read-repair/self-heal. (Process-local like VDisk.down itself:
        # the outage being simulated is a disk, not the proxy.)
        # entries: ("put", blob_id) | ("del", blob_id, upto_seq) —
        # deletes carry the highest version deleted so resync can drop
        # a rejoined disk's stale copy instead of resurrecting it
        self.sync_log: list[tuple] = []
        self.watermark: dict[str, int] = {
            d.disk_id: 0 for d in self.group.disks
        }
        # highest version ever deleted per blob: a re-created blob must
        # NOT reuse a tombstoned seq (resync would treat it as deleted)
        self._seq_floor: dict[str, int] = {}

    def _compact_synclog(self) -> None:
        """Drop log entries every replica has applied."""
        floor = min(self.watermark.values()) if self.watermark else 0
        if floor:
            self.sync_log = self.sync_log[floor:]
            for k in self.watermark:
                self.watermark[k] -= floor

    def _prune_meta(self, vid: str) -> None:
        """META stays only on disks still holding a data part of vid
        (shared by self-heal and resync repatriation)."""
        held = set()
        for d in self.group.disks:
            try:
                if any(d.has_part(vid, i)
                       for i in range(self.codec.total_parts)):
                    held.add(d.disk_id)
            except DiskDown:
                held.add(d.disk_id)  # unknown: keep its META
        for d in self.group.disks:
            if d.disk_id not in held:
                try:
                    d.delete_part(vid, self.META_PART)
                except DiskDown:
                    continue

    @staticmethod
    def _vid(blob_id: str, seq: int) -> str:
        return f"{blob_id}@{seq:016x}"

    def _seqs(self, blob_id: str) -> list[int]:
        """All stored versions of blob_id, newest first."""
        seqs = set()
        pref = blob_id + "@"
        for disk in self.group.disks:
            try:
                for vid in disk.list_parts(self.META_PART, prefix=pref):
                    seqs.add(int(vid[len(pref):], 16))
            except DiskDown:
                continue
        return sorted(seqs, reverse=True)

    # ---- put: encode, place parts (handoff), demand a write quorum ----

    def put(self, blob_id: str, data: bytes) -> None:
        parts = self.codec.encode(data)
        # next version = one past the highest stored version of THIS blob
        # (not a process counter: ordering must survive process restarts
        # over persistent backing)
        seq = max(max(self._seqs(blob_id), default=0),
                  self._seq_floor.get(blob_id, 0)) + 1
        vid = self._vid(blob_id, seq)
        meta = json.dumps({"len": len(data)}).encode()
        n = len(self.group.disks)
        rot = hash_rotation(blob_id, n)
        used: set[int] = set()
        placed: list[tuple[VDisk, int]] = []
        for i, part in enumerate(parts):
            # designated slot first, then handoff slots in rotation
            # order; prefer disks not already holding a part of this
            # blob, double up only when the live topology is smaller
            # than the part count
            slots = [(i + rot + off) % n for off in range(n)]
            for only_fresh in (True, False):
                done = False
                for slot in slots:
                    if only_fresh and slot in used:
                        continue
                    disk = self.group.disks[slot]
                    try:
                        disk.put_part(vid, i, part)
                        disk.put_part(vid, self.META_PART, meta)
                    except DiskDown:
                        continue
                    used.add(slot)
                    placed.append((disk, i))
                    done = True
                    break
                if done:
                    break
        # quorum needs (a) every part placed and (b) enough DISTINCT
        # disks that any two successful write quorums intersect — a
        # strict majority — so version numbering (seq = max seen + 1)
        # always observes the previous successful write even across
        # disjoint outages. For block42 the erasure bound (4) is already
        # a majority of 6; mirror3 gets majority 2-of-3.
        need_disks = max(self.codec.total_parts - self.codec.max_lost,
                         len(self.group.disks) // 2 + 1)
        if len(placed) < len(parts) or len(used) < need_disks:
            # roll back this version's parts only — the previous
            # version, living under its own vid, is untouched
            for disk, i in placed:
                try:
                    disk.delete_part(vid, i)
                    disk.delete_part(vid, self.META_PART)
                except DiskDown:
                    continue
            raise IOError(
                f"write quorum failed: {len(placed)}/{len(parts)} parts "
                f"on {len(used)} disks (need all parts on >= "
                f"{need_disks} disks)")
        # supersede older versions (best effort; down disks may keep
        # stale parts but get() always prefers the newest readable seq)
        for old in self._seqs(blob_id):
            if old != seq:
                self._delete_version(blob_id, old)
        # synclog: record the commit; disks that were fully caught up
        # AND took part in this write advance their watermark, anyone
        # down (or skipped) falls behind until resync()
        self.sync_log.append(("put", blob_id))
        pos = len(self.sync_log)
        took = {d.disk_id for d, _i in placed}
        for d in self.group.disks:
            if self.watermark.get(d.disk_id, 0) == pos - 1 \
                    and d.disk_id in took:
                self.watermark[d.disk_id] = pos
        self._compact_synclog()

    # ---- get: collect parts, reconstruct when disks are down ----

    def _gather(self, vid: str):
        parts: dict[int, bytes] = {}
        meta = None
        for disk in self.group.disks:
            try:
                if meta is None and disk.has_part(vid, self.META_PART):
                    meta = json.loads(
                        disk.get_part(vid, self.META_PART).decode())
                for i in range(self.codec.total_parts):
                    if i not in parts and disk.has_part(vid, i):
                        parts[i] = disk.get_part(vid, i)
            except DiskDown:
                continue
        return parts, meta

    def get(self, blob_id: str) -> bytes:
        seqs = self._seqs(blob_id)
        if not seqs:
            raise KeyError(blob_id)
        err: Exception | None = None
        for seq in seqs:
            parts, meta = self._gather(self._vid(blob_id, seq))
            if meta is None or not parts:
                continue
            try:
                return self.codec.decode(parts, meta["len"])
            except ValueError as e:
                err = e  # undecodable at this version; try older
        raise err if err is not None else KeyError(blob_id)

    def exists(self, blob_id: str) -> bool:
        return bool(self._seqs(blob_id))

    def _delete_version(self, blob_id: str, seq: int) -> None:
        vid = self._vid(blob_id, seq)
        for disk in self.group.disks:
            try:
                for i in range(self.codec.total_parts):
                    disk.delete_part(vid, i)
                disk.delete_part(vid, self.META_PART)
            except DiskDown:
                continue

    def delete(self, blob_id: str) -> None:
        seqs = self._seqs(blob_id)
        for seq in seqs:
            self._delete_version(blob_id, seq)
        # deletes are sync events too: a disk down during the delete
        # must drop its stale parts at resync (the tombstone carries
        # the highest deleted version so resync cannot resurrect)
        upto = max(seqs, default=0)
        if upto:
            self._seq_floor[blob_id] = max(
                self._seq_floor.get(blob_id, 0), upto)
        self.sync_log.append(("del", blob_id, upto))
        pos = len(self.sync_log)
        for d in self.group.disks:
            if self.watermark.get(d.disk_id, 0) == pos - 1 and not d.down:
                self.watermark[d.disk_id] = pos
        self._compact_synclog()

    # ---- background resync (vdisk/syncer + synclog analog) ----

    def resync(self) -> int:
        """Catch rejoined replicas up: replay the lagging UP disks'
        sync-log gap. For every blob touched while any of them was
        down, stale/superseded/deleted versions are dropped (delete
        tombstones carry the highest deleted seq so a stale replica
        cannot resurrect a blob) and the current version is fully
        REPATRIATED — every part restored to its designated disk,
        reconstructing where needed, handoff doubles removed — so the
        group's full loss tolerance returns, exactly as after
        self-heal. Advances watermarks; compacts the log when all
        replicas converge. Returns parts transferred.

        Reference: ydb/core/blobstorage/vdisk/syncer/ (synclog catch-up
        between group replicas), miniaturized to a per-commit log +
        per-replica watermark."""
        n = len(self.group.disks)
        moved = 0
        log_len = len(self.sync_log)
        lagging = [d for d in self.group.disks
                   if not d.down
                   and self.watermark.get(d.disk_id, 0) < log_len]
        if not lagging:
            return 0
        incomplete = False
        wm_floor = min(self.watermark.get(d.disk_id, 0)
                       for d in lagging)
        gap = self.sync_log[wm_floor:]
        max_del: dict[str, int] = {}
        for ent in gap:
            if ent[0] == "del":
                max_del[ent[1]] = max(max_del.get(ent[1], 0), ent[2])
        for blob_id in dict.fromkeys(e[1] for e in gap):
            rot = hash_rotation(blob_id, n)
            # versions at or below a tombstone are DELETED even if a
            # stale replica still advertises them
            seqs = [q for q in self._seqs(blob_id)
                    if q > max_del.get(blob_id, 0)]
            current = self._vid(blob_id, seqs[0]) if seqs else None
            pref = blob_id + "@"
            for disk in lagging:
                # drop anything this disk holds that is not current
                # (list_parts returns full vids, prefix included)
                for vid in disk.list_parts(self.META_PART, prefix=pref):
                    if vid != current:
                        for i in range(self.codec.total_parts):
                            disk.delete_part(vid, i)
                        disk.delete_part(vid, self.META_PART)
            if current is None:
                continue
            parts, meta = self._gather(current)
            if meta is None:
                incomplete = True
                continue
            meta_raw = json.dumps({"len": meta["len"]}).encode()
            # repatriate: every part onto its designated live disk,
            # handoff copies dropped — restores full loss tolerance
            for i in range(self.codec.total_parts):
                disk = self.group.disks[(i + rot) % n]
                try:
                    have = disk.has_part(current, i)
                except DiskDown:
                    continue
                if not have:
                    if i in parts:
                        part = parts[i]
                    else:
                        try:
                            part = self.codec.reconstruct_part(
                                parts, i, meta["len"])
                        except ValueError:
                            incomplete = True
                            continue  # unreconstructable right now
                    disk.put_part(current, i, part)
                    disk.put_part(current, self.META_PART, meta_raw)
                    moved += 1
                for other in self.group.disks:
                    if other is disk:
                        continue
                    try:
                        other.delete_part(current, i)
                    except DiskDown:
                        continue
            self._prune_meta(current)
        if incomplete:
            # something could not repatriate (peer disks down, meta
            # unreachable): leave watermarks so a later resync RETRIES
            # the gap — repatriation is idempotent
            return moved
        for d in self.group.disks:
            if not d.down:
                self.watermark[d.disk_id] = log_len
        self._compact_synclog()
        return moved

    def list(self, prefix: str = "") -> list[str]:
        seen = set()
        for disk in self.group.disks:
            try:
                for vid in disk.list_parts(self.META_PART, prefix=prefix):
                    seen.add(vid.rsplit("@", 1)[0])
            except DiskDown:
                continue
        return sorted(seen)

    # ---- self-heal: replace a dead disk, rebuild missing parts ----

    def self_heal(self, disk_index: int,
                  replacement: VDisk | None = None) -> int:
        """Swap in a fresh disk for group slot disk_index and rebuild
        every part the group is missing (BSC self-heal + vdisk repl).
        Returns the number of parts rebuilt."""
        old = self.group.disks[disk_index]
        new = replacement if replacement is not None else VDisk(
            old.disk_id + "'")
        self.group.disks[disk_index] = new
        # the dead disk's watermark must not pin log compaction
        self.watermark.pop(old.disk_id, None)
        n = len(self.group.disks)
        rebuilt = 0
        complete = True
        for blob_id in self.list():
            rot = hash_rotation(blob_id, n)
            for seq in self._seqs(blob_id):
                vid = self._vid(blob_id, seq)
                parts, meta = self._gather(vid)
                if meta is None:
                    continue
                # restore every part onto its designated live disk —
                # this both fills the replacement disk and repatriates
                # handoff copies written while disks were down, so the
                # group's full loss tolerance comes back after heal
                for i in range(self.codec.total_parts):
                    disk = self.group.disks[(i + rot) % n]
                    try:
                        on_designated = disk.has_part(vid, i)
                    except DiskDown:
                        continue
                    if not on_designated:
                        if i in parts:
                            part = parts[i]
                        else:
                            try:
                                part = self.codec.reconstruct_part(
                                    parts, i, meta["len"])
                            except ValueError:
                                complete = False
                                break  # unreconstructable: heal the rest
                        try:
                            disk.put_part(vid, i, part)
                            disk.put_part(
                                vid, self.META_PART,
                                json.dumps({"len": meta["len"]}).encode())
                        except DiskDown:
                            continue
                        rebuilt += 1
                    # drop now-redundant handoff copies of this part
                    for other in self.group.disks:
                        if other is disk:
                            continue
                        try:
                            other.delete_part(vid, i)
                        except DiskDown:
                            continue
                self._prune_meta(vid)
        # an INCOMPLETE heal (peers down made blobs unreconstructable)
        # leaves the replacement lagging so resync retries what it can;
        # rerun self_heal once the peers return for unlogged blobs
        self.watermark[new.disk_id] = (
            len(self.sync_log) if complete else 0)
        return rebuilt
