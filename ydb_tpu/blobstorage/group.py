"""Blob groups: VDisks, topology, and the quorum DSProxy.

Mirror of the reference's group machinery (SURVEY.md §2.3): a group is a
set of disks across fail domains (TBlobStorageGroupInfo
groupinfo/blobstorage_groupinfo.h:65); clients talk to a per-group
DSProxy which erasure-encodes puts across the disks
(dsproxy_put.cpp:29), reads with reconstruction when disks are down
(restore-on-read, dsproxy_get.cpp:34), and the controller replaces
broken disks and rebuilds their parts (self-heal
mind/bscontroller/self_heal.cpp + vdisk repl).

VDisk here is the per-disk part store (the hull LSM collapsed to a KV
namespace on a host BlobStore); ``down`` simulates disk death for
tests/nemesis.
"""

from __future__ import annotations

import json

from ydb_tpu.blobstorage.erasure import ErasureCodec
from ydb_tpu.common import fnv1a_64
from ydb_tpu.engine.blobs import BlobStore, MemBlobStore


class DiskDown(Exception):
    pass


class VDisk:
    def __init__(self, disk_id: str, backing: BlobStore | None = None):
        self.disk_id = disk_id
        self.backing = backing if backing is not None else MemBlobStore()
        self.down = False

    def _key(self, blob_id: str, part: int) -> str:
        return f"vdisk/{self.disk_id}/{part}/{blob_id}"

    def put_part(self, blob_id: str, part: int, data: bytes) -> None:
        if self.down:
            raise DiskDown(self.disk_id)
        self.backing.put(self._key(blob_id, part), data)

    def get_part(self, blob_id: str, part: int) -> bytes:
        if self.down:
            raise DiskDown(self.disk_id)
        return self.backing.get(self._key(blob_id, part))

    def has_part(self, blob_id: str, part: int) -> bool:
        if self.down:
            raise DiskDown(self.disk_id)
        return self.backing.exists(self._key(blob_id, part))

    def delete_part(self, blob_id: str, part: int) -> None:
        if self.down:
            raise DiskDown(self.disk_id)
        self.backing.delete(self._key(blob_id, part))

    def list_parts(self, part: int) -> list[str]:
        if self.down:
            raise DiskDown(self.disk_id)
        prefix = f"vdisk/{self.disk_id}/{part}/"
        return [k[len(prefix):] for k in self.backing.list(prefix)]


class GroupInfo:
    """Topology: one disk per (fail domain, part slot). Part i of a blob
    lands on disk (i + rotation(blob)) % n — the reference's blob->disk
    mapper keeps load even the same way (groupinfo.h:274)."""

    def __init__(self, group_id: int, species: str = "block42",
                 disks: list[VDisk] | None = None):
        self.group_id = group_id
        self.codec = ErasureCodec(species)
        n = self.codec.total_parts
        self.disks = disks if disks is not None else [
            VDisk(f"g{group_id}-d{i}") for i in range(n)
        ]
        if len(self.disks) != n:
            raise ValueError(
                f"{species} needs exactly {n} disks per group")

    def disk_for(self, blob_id: str, part: int) -> VDisk:
        rot = hash_rotation(blob_id, len(self.disks))
        return self.disks[(part + rot) % len(self.disks)]


def hash_rotation(blob_id: str, n: int) -> int:
    return fnv1a_64(blob_id) % n


class DSProxy:
    """Per-group client: erasure put/get with quorum + restore-on-read."""

    META_PART = 255  # per-blob metadata (orig length) replicated broadly

    def __init__(self, group: GroupInfo):
        self.group = group
        self.codec = group.codec

    # ---- put: encode, place parts, demand a write quorum ----

    def put(self, blob_id: str, data: bytes) -> None:
        parts = self.codec.encode(data)
        meta = json.dumps({"len": len(data)}).encode()
        written = 0
        for i, part in enumerate(parts):
            disk = self.group.disk_for(blob_id, i)
            try:
                disk.put_part(blob_id, i, part)
                disk.put_part(blob_id, self.META_PART, meta)
                written += 1
            except DiskDown:
                pass
        # quorum: enough surviving parts that max_lost MORE failures
        # still leave the blob readable
        need = len(parts) - self.codec.max_lost
        if written < need:
            # roll back the partial write: a sub-quorum blob would list
            # as existing but be unreconstructable, poisoning self-heal
            self.delete(blob_id)
            raise IOError(
                f"write quorum failed: {written}/{len(parts)} parts "
                f"(need {need})")

    # ---- get: collect parts, reconstruct when disks are down ----

    def get(self, blob_id: str) -> bytes:
        parts: dict[int, bytes] = {}
        meta = None
        for i in range(self.codec.total_parts):
            disk = self.group.disk_for(blob_id, i)
            try:
                if meta is None and disk.has_part(blob_id,
                                                  self.META_PART):
                    meta = json.loads(
                        disk.get_part(blob_id, self.META_PART).decode())
                if disk.has_part(blob_id, i):
                    parts[i] = disk.get_part(blob_id, i)
            except DiskDown:
                continue
        if meta is None:
            raise KeyError(blob_id)
        if not parts:
            raise KeyError(blob_id)
        return self.codec.decode(parts, meta["len"])

    def exists(self, blob_id: str) -> bool:
        for i in range(self.codec.total_parts):
            disk = self.group.disk_for(blob_id, i)
            try:
                if disk.has_part(blob_id, self.META_PART):
                    return True
            except DiskDown:
                continue
        return False

    def delete(self, blob_id: str) -> None:
        for i in range(self.codec.total_parts):
            disk = self.group.disk_for(blob_id, i)
            try:
                disk.delete_part(blob_id, i)
                disk.delete_part(blob_id, self.META_PART)
            except DiskDown:
                continue

    def list(self, prefix: str = "") -> list[str]:
        seen = set()
        for disk in self.group.disks:
            try:
                for blob_id in disk.list_parts(self.META_PART):
                    if blob_id.startswith(prefix):
                        seen.add(blob_id)
            except DiskDown:
                continue
        return sorted(seen)

    # ---- self-heal: replace a dead disk, rebuild its parts ----

    def self_heal(self, disk_index: int,
                  replacement: VDisk | None = None) -> int:
        """Swap in a fresh disk for group slot disk_index and rebuild
        every part the old disk held (BSC self-heal + vdisk repl).
        Returns the number of parts rebuilt."""
        old = self.group.disks[disk_index]
        new = replacement if replacement is not None else VDisk(
            old.disk_id + "'")
        self.group.disks[disk_index] = new
        rebuilt = 0
        # every known blob: if its part maps to this slot, reconstruct
        for blob_id in self.list():
            rot = hash_rotation(blob_id, len(self.group.disks))
            part_idx = (disk_index - rot) % len(self.group.disks)
            if part_idx >= self.codec.total_parts:
                continue
            parts: dict[int, bytes] = {}
            meta = None
            for i in range(self.codec.total_parts):
                disk = self.group.disk_for(blob_id, i)
                try:
                    if meta is None and disk.has_part(blob_id,
                                                      self.META_PART):
                        meta = json.loads(disk.get_part(
                            blob_id, self.META_PART).decode())
                    if disk.has_part(blob_id, i):
                        parts[i] = disk.get_part(blob_id, i)
                except DiskDown:
                    continue
            if meta is None:
                continue
            try:
                part = self.codec.reconstruct_part(parts, part_idx,
                                                   meta["len"])
            except ValueError:
                continue  # unreconstructable blob: skip, keep healing
            new.put_part(blob_id, part_idx, part)
            new.put_part(blob_id, self.META_PART,
                         json.dumps({"len": meta["len"]}).encode())
            rebuilt += 1
        return rebuilt
