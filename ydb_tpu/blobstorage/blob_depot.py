"""BlobDepot: blob virtualization tablet + transparent store adapter.

Mirror of the reference's blob-virtualization layer (ydb/core/
blob_depot: a tablet that owns the mapping from client blob names to
physically stored blobs, reference-counts shared payloads, garbage-
collects unreferenced data, and absorbs ("decommits") blobs from
groups being drained; SURVEY.md §2.3 row "BlobDepot / incrhuge /
keyvalue"). Built as an ordinary tablet over the executor, fronting
any BlobStore backend:

  * payloads dedup by content hash: N logical names for one payload
    store it once with refcount N (the incrhuge space-efficiency
    motivation);
  * deletes decrement; the physical blob is deleted only at zero
    references (with a durable trash mark first, so a crash between
    the index commit and the physical delete leaves garbage, never a
    dangling reference — collect_garbage() sweeps);
  * ``DepotBlobStore`` exposes the standard Put/Get/Delete/List
    surface, so any tablet (executor WAL, PQ partition, ColumnShard)
    runs over a depot transparently;
  * ``decommit(prefix)`` absorbs existing direct blobs of the backend
    into the depot index and rewrites them into depot-owned keys —
    the group-draining flow of the reference's decommission path.
"""

from __future__ import annotations

import hashlib

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.executor import TabletExecutor


def _digest(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


class BlobDepot:
    """Name -> payload indirection with dedup + refcounted GC."""

    def __init__(self, depot_id: str, backend: BlobStore):
        self.backend = backend
        self.depot_id = depot_id
        self.executor = TabletExecutor.boot(f"blobdepot/{depot_id}",
                                            backend)
        self._prefix = f"depot/{depot_id}/data/"
        # sweep trash a crash may have left between index commit and
        # physical delete (the crash-recovery half of the GC contract)
        self.collect_garbage()

    # -- write path --

    def put(self, name: str, data: bytes) -> None:
        digest = _digest(data)
        phys = self._prefix + digest
        # write payload BEFORE the index commit: a crash leaves
        # unreferenced garbage (swept later), never a broken reference
        if not self.backend.exists(phys):
            self.backend.put(phys, data)

        def fn(txc):
            old = txc.get("names", (name,))
            ref = txc.get("refs", (digest,))
            if old is not None and old["digest"] == digest:
                return False  # same content re-put: nothing changes
            txc.put("names", (name,), {"digest": digest,
                                       "size": len(data)})
            txc.put("refs", (digest,),
                    {"n": (ref["n"] if ref else 0) + 1,
                     "size": len(data)})
            if old is not None:
                self._dec_locked(txc, old["digest"])
                return True  # the displaced payload may now be trash
            return False
        if self.executor.run(fn):
            self.collect_garbage()

    def _dec_locked(self, txc, digest: str) -> None:
        ref = txc.get("refs", (digest,))
        n = (ref["n"] if ref else 1) - 1
        if n <= 0:
            txc.erase("refs", (digest,))
            # durable trash mark first; physical delete may crash
            txc.put("trash", (digest,), {})
        else:
            txc.put("refs", (digest,), dict(ref or {}, n=n))

    def delete(self, name: str) -> None:
        def fn(txc):
            row = txc.get("names", (name,))
            if row is None:
                return
            txc.erase("names", (name,))
            self._dec_locked(txc, row["digest"])
        self.executor.run(fn)
        self.collect_garbage()

    # -- read path --

    def get(self, name: str) -> bytes:
        row = self.executor.db.table("names").get((name,))
        if row is None:
            raise KeyError(name)
        return self.backend.get(self._prefix + row["digest"])

    def exists(self, name: str) -> bool:
        return self.executor.db.table("names").get((name,)) is not None

    def names(self, prefix: str = "") -> list[str]:
        # range-bounded like MemBlobStore.list: DepotBlobStore.list
        # sits on tablet boot/checkpoint hot paths
        lo = (prefix,) if prefix else None
        hi = (prefix + "￿",) if prefix else None
        return [n for (n,), _row in
                self.executor.db.table("names").range(lo=lo, hi=hi)]

    # -- maintenance --

    def collect_garbage(self) -> int:
        """Physically delete trash-marked payloads; returns count.
        Re-put of identical content between mark and sweep is handled:
        a digest with a live refcount is unmarked, not deleted."""
        swept = 0
        for (digest,), _row in list(
                self.executor.db.table("trash").range()):
            ref = self.executor.db.table("refs").get((digest,))
            if ref is not None:  # resurrected by a concurrent put
                self.executor.run(
                    lambda txc, d=digest: txc.erase("trash", (d,)))
                continue
            phys = self._prefix + digest
            if self.backend.exists(phys):
                self.backend.delete(phys)
            self.executor.run(
                lambda txc, d=digest: txc.erase("trash", (d,)))
            swept += 1
        return swept

    def stats(self) -> dict:
        names = logical = 0
        for (_n,), row in self.executor.db.table("names").range():
            names += 1
            logical += row["size"]
        payloads = physical = 0
        # sizes come from the refs index — a metadata query must not
        # fetch payload bytes from the backend
        for (_d,), row in self.executor.db.table("refs").range():
            payloads += 1
            physical += row.get("size", 0)
        return {"names": names, "payloads": payloads,
                "logical_bytes": logical, "physical_bytes": physical}

    def decommit(self, prefix: str) -> int:
        """Absorb direct backend blobs under ``prefix`` into the depot
        (decommission flow): each becomes a depot name; the original
        direct blob is removed once indexed. Returns blobs absorbed."""
        absorbed = 0
        for blob_id in list(self.backend.list(prefix)):
            # never absorb ANY depot's payloads or ANY tablet's state
            # (a shared backend hosts several depots + their tablets;
            # draining a sibling would dangle its references)
            if blob_id.startswith("depot/") or \
                    blob_id.startswith("tablet/"):
                continue
            data = self.backend.get(blob_id)
            self.put(blob_id, data)
            self.backend.delete(blob_id)
            absorbed += 1
        return absorbed


class DepotBlobStore(BlobStore):
    """Standard BlobStore surface over a BlobDepot (virtual group)."""

    def __init__(self, depot: BlobDepot):
        self.depot = depot

    def put(self, blob_id: str, data: bytes) -> None:
        self.depot.put(blob_id, data)

    def get(self, blob_id: str) -> bytes:
        return self.depot.get(blob_id)

    def delete(self, blob_id: str) -> None:
        self.depot.delete(blob_id)

    def exists(self, blob_id: str) -> bool:
        return self.depot.exists(blob_id)

    def list(self, prefix: str = "") -> list[str]:
        return self.depot.names(prefix)
