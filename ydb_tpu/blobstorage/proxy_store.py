"""GroupBlobStore: the narrow BlobStore interface over a DSProxy.

The integration seam: every durable consumer in the system — tablet
executors, ColumnShard portions/WAL, SchemeShard, the cluster dict
journal — talks BlobStore (SURVEY.md §2.3 header: tablets never see
disks, only blob ids). Pointing a Cluster at a GroupBlobStore puts the
ENTIRE database on erasure-coded storage: kill any max_lost disks of
the group and every table still reads and writes.
"""

from __future__ import annotations

from ydb_tpu.blobstorage.group import DSProxy
from ydb_tpu.engine.blobs import BlobStore


class GroupBlobStore(BlobStore):
    def __init__(self, proxy: DSProxy):
        self.proxy = proxy

    def put(self, blob_id: str, data: bytes) -> None:
        self.proxy.put(blob_id, bytes(data))

    def get(self, blob_id: str) -> bytes:
        return self.proxy.get(blob_id)

    def delete(self, blob_id: str) -> None:
        self.proxy.delete(blob_id)

    def exists(self, blob_id: str) -> bool:
        return self.proxy.exists(blob_id)

    def list(self, prefix: str = "") -> list[str]:
        return self.proxy.list(prefix)
