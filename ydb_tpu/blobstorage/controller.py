"""BlobStorage controller: node warden registry + automated self-heal.

Mirror of the reference's NodeWarden + BSController pair (SURVEY §2.3
NodeWarden/BSC row; ydb/core/blobstorage/nodewarden,
mind/bscontroller/self_heal.cpp): each node's warden registers the
PDisks it hosts; the controller owns the group map, watches disk
health, and when a group runs degraded it picks a spare from the warden
inventory, swaps it into the broken slot and drives the rebuild —
without operator involvement. The DSProxy's manual ``self_heal`` stays
the mechanism; the controller supplies the policy loop.
"""

from __future__ import annotations

import dataclasses

from ydb_tpu.blobstorage.group import DSProxy, VDisk


@dataclasses.dataclass
class HealRecord:
    group_id: int
    slot: int
    old_disk: str
    new_disk: str
    parts_rebuilt: int


class NodeWarden:
    """Per-node disk inventory (nodewarden analog): spares register
    here; the controller draws replacements from the pool."""

    def __init__(self, node_id: int):
        self.node_id = node_id
        self._spares: list[VDisk] = []

    def register_spare(self, disk: VDisk) -> None:
        self._spares.append(disk)

    def take_spare(self) -> VDisk | None:
        return self._spares.pop(0) if self._spares else None

    @property
    def spare_count(self) -> int:
        return len(self._spares)


class BSController:
    """Group map + the self-heal policy loop (bscontroller analog)."""

    def __init__(self):
        self.proxies: dict[int, DSProxy] = {}
        self.wardens: dict[int, NodeWarden] = {}
        self.heal_log: list[HealRecord] = []

    def register_group(self, proxy: DSProxy) -> None:
        self.proxies[proxy.group.group_id] = proxy

    def register_warden(self, warden: NodeWarden) -> None:
        self.wardens[warden.node_id] = warden

    def _next_spare(self) -> VDisk | None:
        wardens = sorted(self.wardens.values(),
                         key=lambda w: -w.spare_count)
        for w in wardens:
            d = w.take_spare()
            if d is not None:
                return d
        return None

    def degraded_groups(self) -> list[tuple[int, list[int]]]:
        """(group_id, [down slots]) for every group with dead disks."""
        out = []
        for gid, proxy in sorted(self.proxies.items()):
            down = [i for i, d in enumerate(proxy.group.disks) if d.down]
            if down:
                out.append((gid, down))
        return out

    def check_and_heal(self) -> list[HealRecord]:
        """One policy pass: every down slot heals onto a spare while
        spares last (worst-degraded groups first — a group past its
        loss tolerance is prioritized the way the reference orders its
        self-heal queue)."""
        degraded = sorted(self.degraded_groups(),
                          key=lambda g: -len(g[1]))
        healed: list[HealRecord] = []
        for gid, slots in degraded:
            proxy = self.proxies[gid]
            for slot in slots:
                spare = self._next_spare()
                if spare is None:
                    return healed  # out of spares: remaining stay down
                old = proxy.group.disks[slot]
                rebuilt = proxy.self_heal(slot, spare)
                rec = HealRecord(gid, slot, old.disk_id, spare.disk_id,
                                 rebuilt)
                self.heal_log.append(rec)
                healed.append(rec)
        return healed
