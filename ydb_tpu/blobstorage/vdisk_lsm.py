"""VDisk hull: an LSM-structured BlobStore over PDisk chunks.

Mirror of the reference VDisk's hull database (ydb/core/blobstorage/
vdisk/hulldb; SURVEY §2.3 VDisk row): writes land in a WAL (log chunks)
plus a memtable; flushes seal the memtable into an immutable sorted run
(SST) written append-only into reserved chunks; a MANIFEST (the PDisk
superblock metadata) lists live runs newest-first; size-tiered
compaction merges runs and releases their chunks. Recovery = manifest
+ WAL replay — the same two-structure design as the reference's
fresh-segment + levels with sync-log recovery.

Exposes the standard BlobStore surface, so a ``VDisk(backing=...)`` in
a blob group runs its part store on real chunked storage.
"""

from __future__ import annotations

import struct
import zlib

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.blobstorage.pdisk import PDisk

_REC = struct.Struct("!II")  # key_len, value_len (value 0xFFFFFFFF = del)
_TOMB = 0xFFFFFFFF


class LsmBlobStore(BlobStore):
    def __init__(self, pdisk: PDisk, memtable_bytes: int = 1 << 20,
                 max_runs: int = 6):
        self.pdisk = pdisk
        self.memtable_bytes = memtable_bytes
        self.max_runs = max_runs
        self.mem: dict[str, bytes | None] = {}
        self._mem_size = 0
        # manifest state
        self.runs: list[dict] = []   # newest first: {chunks, index}
        self._log_chunks: list[int] = []
        self._log_pos = 0
        self._boot()

    # ---- boot / manifest ----

    def _boot(self) -> None:
        meta = self.pdisk.meta
        self.runs = list(meta.get("runs", []))
        for cid in meta.get("log", []):
            self._replay_log_chunk(cid)
            self._log_chunks.append(cid)
        if not self._log_chunks:
            self._new_log_chunk(commit=True)

    def _commit_manifest(self) -> None:
        self.pdisk.commit_meta({
            "runs": self.runs,
            "log": self._log_chunks,
        })

    # ---- WAL ----

    def _new_log_chunk(self, commit: bool) -> None:
        cid = self.pdisk.alloc()
        # zero the header region so replay of a recycled chunk stops
        self.pdisk.write(cid, 0, b"\x00" * _REC.size)
        self._log_chunks.append(cid)
        self._log_pos = 0
        if commit:
            self._commit_manifest()

    def _log_append(self, key: str, value: bytes | None) -> None:
        kb = key.encode()
        vb = b"" if value is None else value
        rec = _REC.pack(len(kb), _TOMB if value is None else len(vb))
        frame = rec + kb + vb + struct.pack("!I", zlib.crc32(kb + vb))
        if self._log_pos + len(frame) + _REC.size > self.pdisk.chunk_size:
            self._new_log_chunk(commit=True)
        if len(frame) + _REC.size > self.pdisk.chunk_size:
            raise ValueError("record larger than a chunk")
        cid = self._log_chunks[-1]
        self.pdisk.write(cid, self._log_pos, frame)
        # pre-zero the NEXT header so replay terminates cleanly
        self.pdisk.write(cid, self._log_pos + len(frame),
                         b"\x00" * _REC.size)
        self.pdisk.sync()
        self._log_pos += len(frame)

    def _replay_log_chunk(self, cid: int) -> None:
        pos = 0
        while pos + _REC.size <= self.pdisk.chunk_size:
            klen, vlen = _REC.unpack(
                self.pdisk.read(cid, pos, _REC.size))
            if klen == 0:
                break
            is_del = vlen == _TOMB
            dlen = 0 if is_del else vlen
            body = self.pdisk.read(cid, pos + _REC.size, klen + dlen + 4)
            kb, vb = body[:klen], body[klen:klen + dlen]
            (crc,) = struct.unpack("!I", body[klen + dlen:])
            if zlib.crc32(kb + vb) != crc:
                break  # torn tail record: stop replay here
            self._mem_put(kb.decode(), None if is_del else vb)
            pos += _REC.size + klen + dlen + 4
        self._log_pos = pos

    # ---- memtable ----

    def _mem_put(self, key: str, value: bytes | None) -> None:
        old = self.mem.get(key)
        if old:
            self._mem_size -= len(old)
        self.mem[key] = value
        self._mem_size += len(value) if value else 0

    # ---- SST runs ----

    def _flush(self) -> None:
        if not self.mem:
            return
        entries = sorted(self.mem.items())
        chunks: list[int] = []
        index: list[tuple[str, int, int, int, bool]] = []
        cid = self.pdisk.alloc()
        chunks.append(cid)
        pos = 0
        for key, value in entries:
            vb = b"" if value is None else value
            if pos + len(vb) > self.pdisk.chunk_size:
                cid = self.pdisk.alloc()
                chunks.append(cid)
                pos = 0
            if len(vb) > self.pdisk.chunk_size:
                raise ValueError("value larger than a chunk")
            self.pdisk.write(cid, pos, vb)
            index.append((key, len(chunks) - 1, pos, len(vb),
                          value is None))
            pos += len(vb)
        self.pdisk.sync()
        self.runs.insert(0, {"chunks": chunks, "index": index})
        # the flush supersedes the WAL: recycle log chunks
        old_logs = self._log_chunks
        self._log_chunks = []
        self.mem = {}
        self._mem_size = 0
        self._new_log_chunk(commit=False)
        for cid in old_logs:
            self.pdisk.release(cid)
        if len(self.runs) > self.max_runs:
            self._compact()
        else:
            self._commit_manifest()

    def _compact(self) -> None:
        """Merge every run newest-wins into one; drop tombstones (full
        compaction = no older data can resurrect under them)."""
        merged: dict[str, tuple] = {}
        for run in self.runs:  # newest first: first occurrence wins
            for key, ci, off, ln, is_del in run["index"]:
                if key not in merged:
                    merged[key] = (run, ci, off, ln, is_del)
        entries = []
        for key in sorted(merged):
            run, ci, off, ln, is_del = merged[key]
            if is_del:
                continue
            entries.append(
                (key, self.pdisk.read(run["chunks"][ci], off, ln)))
        old_runs = self.runs
        chunks: list[int] = []
        index: list[tuple] = []
        cid = self.pdisk.alloc()
        chunks.append(cid)
        pos = 0
        for key, vb in entries:
            if pos + len(vb) > self.pdisk.chunk_size:
                cid = self.pdisk.alloc()
                chunks.append(cid)
                pos = 0
            self.pdisk.write(cid, pos, vb)
            index.append((key, len(chunks) - 1, pos, len(vb), False))
            pos += len(vb)
        self.pdisk.sync()
        self.runs = [{"chunks": chunks, "index": index}]
        self._commit_manifest()
        for run in old_runs:
            for c in run["chunks"]:
                self.pdisk.release(c)

    def _find(self, key: str):
        """(value bytes | None-as-tombstone | 'absent' sentinel)."""
        if key in self.mem:
            return self.mem[key]
        for run in self.runs:
            for k, ci, off, ln, is_del in run["index"]:
                if k == key:
                    if is_del:
                        return None
                    return self.pdisk.read(run["chunks"][ci], off, ln)
        return _ABSENT

    # ---- BlobStore surface ----

    def put(self, blob_id, data):
        data = bytes(data)
        self._log_append(blob_id, data)
        self._mem_put(blob_id, data)
        if self._mem_size >= self.memtable_bytes:
            self._flush()

    def get(self, blob_id):
        v = self._find(blob_id)
        if v is _ABSENT or v is None:
            raise KeyError(blob_id)
        return v

    def delete(self, blob_id):
        self._log_append(blob_id, None)
        self._mem_put(blob_id, None)

    def exists(self, blob_id):
        v = self._find(blob_id)
        return v is not _ABSENT and v is not None

    def list(self, prefix=""):
        seen: dict[str, bool] = {}
        for key, v in self.mem.items():
            if key.startswith(prefix):
                seen[key] = v is not None
        for run in self.runs:
            for k, ci, off, ln, is_del in run["index"]:
                if k.startswith(prefix) and k not in seen:
                    seen[k] = not is_del
        return sorted(k for k, live in seen.items() if live)

    def flush(self) -> None:
        """Public flush (tests / graceful shutdown)."""
        self._flush()


class _Absent:
    __slots__ = ()


_ABSENT = _Absent()
