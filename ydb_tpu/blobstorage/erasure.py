"""Erasure codecs for blob groups.

Mirror of the reference's erasure species (TErasureType
ydb/core/erasure/erasure.h:252-275; SURVEY.md §2.3): a blob is split
into parts placed on the disks of a group; reads reconstruct from any
quorum of surviving parts.

  * ``none``      — 1 part, no redundancy
  * ``mirror3``   — 3 full replicas (mirror-3dc shape without the DC
                    topology; any 1 of 3 parts restores)
  * ``block42``   — 4 data + 2 parity (the reference's default
                    block-4-2): parity P = XOR of data parts, parity Q =
                    GF(256) weighted sum (RAID-6 construction), so ANY
                    two lost parts are recoverable

Parts carry the original length so padding strips on decode. All the
math is vectorized numpy over uint8 — host-side storage plane, never
the device.
"""

from __future__ import annotations

import numpy as np

# ---- GF(256) tables (polynomial 0x11D, generator 2) ----

_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= 0x11D
_EXP[255:510] = _EXP[:255]


def _gf_mul_vec(a: np.ndarray, c: int) -> np.ndarray:
    """Multiply a uint8 vector by constant c in GF(256)."""
    if c == 0:
        return np.zeros_like(a)
    lc = int(_LOG[c])
    out = np.zeros_like(a)
    nz = a != 0
    out[nz] = _EXP[_LOG[a[nz]] + lc]
    return out


def _gf_div(a: int, b: int) -> int:
    if a == 0:
        return 0
    return int(_EXP[(int(_LOG[a]) - int(_LOG[b])) % 255])


class ErasureCodec:
    SPECIES = ("none", "mirror3", "block42")

    def __init__(self, species: str = "block42"):
        if species not in self.SPECIES:
            raise ValueError(f"unknown erasure species {species}")
        self.species = species

    @property
    def total_parts(self) -> int:
        return {"none": 1, "mirror3": 3, "block42": 6}[self.species]

    @property
    def data_parts(self) -> int:
        return {"none": 1, "mirror3": 1, "block42": 4}[self.species]

    @property
    def max_lost(self) -> int:
        """Parts that may be lost with full recovery still possible."""
        return {"none": 0, "mirror3": 2, "block42": 2}[self.species]

    # ---- encode ----

    def encode(self, data: bytes) -> list[bytes]:
        if self.species == "none":
            return [data]
        if self.species == "mirror3":
            return [data, data, data]
        # block42
        n = len(data)
        k = self.data_parts
        plen = (n + k - 1) // k if n else 1
        buf = np.zeros(k * plen, dtype=np.uint8)
        buf[:n] = np.frombuffer(data, dtype=np.uint8)
        d = buf.reshape(k, plen)
        p = d[0] ^ d[1] ^ d[2] ^ d[3]
        q = np.zeros(plen, dtype=np.uint8)
        for i in range(k):
            q ^= _gf_mul_vec(d[i], int(_EXP[i]))  # weights g^i
        return [d[i].tobytes() for i in range(k)] + [p.tobytes(),
                                                     q.tobytes()]

    # ---- decode ----

    def decode(self, parts: dict[int, bytes], orig_len: int) -> bytes:
        """parts: part index -> bytes for the SURVIVING parts."""
        if self.species == "none":
            return parts[0][:orig_len]
        if self.species == "mirror3":
            return next(iter(parts.values()))[:orig_len]
        return self._decode_block42(parts, orig_len)

    def _decode_block42(self, parts: dict[int, bytes],
                        orig_len: int) -> bytes:
        k = self.data_parts
        missing = [i for i in range(k) if i not in parts]
        if len([i for i in range(6) if i in parts]) < k:
            raise ValueError("too many parts lost to reconstruct")
        plen = len(next(iter(parts.values())))
        d = {i: np.frombuffer(parts[i], dtype=np.uint8).copy()
             for i in parts}
        if len(missing) == 1:
            m = missing[0]
            if 4 in d:  # rebuild from P (XOR)
                acc = d[4].copy()
                for i in range(k):
                    if i != m:
                        acc ^= d[i]
                d[m] = acc
            else:       # rebuild from Q
                acc = d[5].copy()
                for i in range(k):
                    if i != m:
                        acc ^= _gf_mul_vec(d[i], int(_EXP[i]))
                d[m] = _gf_mul_vec(acc, _gf_inv(int(_EXP[m])))
        elif len(missing) == 2:
            a, b = missing  # need both P and Q
            p_acc = d[4].copy()
            q_acc = d[5].copy()
            for i in range(k):
                if i not in missing:
                    p_acc ^= d[i]
                    q_acc ^= _gf_mul_vec(d[i], int(_EXP[i]))
            # p_acc = Da ^ Db ; q_acc = ga*Da ^ gb*Db  (RAID-6 solve)
            ga, gb = int(_EXP[a]), int(_EXP[b])
            denom = ga ^ gb
            da = _gf_mul_vec(q_acc ^ _gf_mul_vec(p_acc, gb),
                             _gf_inv(denom))
            d[a] = da
            d[b] = p_acc ^ da
        out = np.concatenate([d[i] for i in range(k)])
        return out.tobytes()[:orig_len]

    def reconstruct_part(self, parts: dict[int, bytes], idx: int,
                         orig_len: int) -> bytes:
        """Rebuild one part (self-heal/replication path)."""
        if self.species == "none":
            raise ValueError("no redundancy to rebuild from")
        if self.species == "mirror3":
            return next(iter(parts.values()))
        data = self.decode(parts, orig_len)
        return self.encode(data)[idx]


def _gf_inv(c: int) -> int:
    return _gf_div(1, c)
