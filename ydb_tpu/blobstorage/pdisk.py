"""PDisk: the raw-device chunk layer under VDisks.

Mirror of the reference's PDisk (ydb/core/blobstorage/pdisk/
blobstorage_pdisk_impl.h:46; SURVEY §2.3 PDisk row): one big device
(here: one file) divided into fixed-size CHUNKS, allocated/released to
owners, with a double-buffered superblock carrying the allocation state
and the owner's metadata — a crash between superblock commits falls
back to the previous consistent generation (the reference's format
record + sys log serve the same role).

Layout: chunks 0 and 1 are the superblock slots (alternating writes,
highest valid sequence wins); data chunks start at 2. Chunk writes are
in-place (the LSM above writes chunks append-only before committing
them to the manifest, so torn data chunks are unreachable garbage).
"""

from __future__ import annotations

import json
import os
import struct
import zlib

_SB_HDR = struct.Struct("!QII")  # seq, payload_len, crc32


class PDisk:
    DATA_START = 2

    def __init__(self, path: str, chunk_size: int = 256 << 10):
        self.path = path
        self.chunk_size = chunk_size
        exists = os.path.exists(path)
        self._f = open(path, "r+b" if exists else "w+b")
        self._seq = 0
        self._free: set[int] = set()
        self._next_chunk = self.DATA_START
        self.meta: dict = {}
        if exists:
            self._load_superblock()

    # ---- superblock (allocation state + owner metadata) ----

    def _sb_read(self, slot: int):
        self._f.seek(slot * self.chunk_size)
        hdr = self._f.read(_SB_HDR.size)
        if len(hdr) < _SB_HDR.size:
            return None
        seq, n, crc = _SB_HDR.unpack(hdr)
        if n == 0 or n > self.chunk_size - _SB_HDR.size:
            return None
        payload = self._f.read(n)
        if len(payload) < n or zlib.crc32(payload) != crc:
            return None  # torn superblock write: slot invalid
        return seq, json.loads(payload.decode())

    def _load_superblock(self) -> None:
        best = None
        for slot in (0, 1):
            got = self._sb_read(slot)
            if got and (best is None or got[0] > best[0]):
                best = got
        if best is None:
            return  # fresh/unformatted device
        self._seq, state = best
        self._free = set(state["free"])
        self._next_chunk = state["next_chunk"]
        self.meta = state["meta"]

    def commit_meta(self, meta: dict) -> None:
        """Atomically persist allocation state + owner metadata (the
        next boot sees exactly this generation or the previous one)."""
        self.meta = dict(meta)
        self._seq += 1
        payload = json.dumps({
            "free": sorted(self._free),
            "next_chunk": self._next_chunk,
            "meta": self.meta,
        }).encode()
        if len(payload) + _SB_HDR.size > self.chunk_size:
            raise ValueError("superblock payload exceeds chunk size")
        slot = self._seq % 2
        self._f.seek(slot * self.chunk_size)
        self._f.write(_SB_HDR.pack(self._seq, len(payload),
                                   zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        os.fsync(self._f.fileno())

    # ---- chunk allocation ----

    def alloc(self) -> int:
        """Reserve a chunk (volatile until commit_meta persists it as
        owned; an uncommitted allocation is reclaimed on reboot)."""
        if self._free:
            return self._free.pop()
        cid = self._next_chunk
        self._next_chunk += 1
        return cid

    def release(self, chunk_id: int) -> None:
        if chunk_id < self.DATA_START:
            raise ValueError("cannot release a superblock chunk")
        self._free.add(chunk_id)

    @property
    def allocated_chunks(self) -> int:
        return self._next_chunk - self.DATA_START - len(self._free)

    # ---- chunk IO ----

    def _off(self, chunk_id: int, offset: int, length: int) -> int:
        if offset + length > self.chunk_size:
            raise ValueError("IO crosses a chunk boundary")
        return chunk_id * self.chunk_size + offset

    def write(self, chunk_id: int, offset: int, data: bytes) -> None:
        self._f.seek(self._off(chunk_id, offset, len(data)))
        self._f.write(data)

    def read(self, chunk_id: int, offset: int, length: int) -> bytes:
        self._f.seek(self._off(chunk_id, offset, length))
        out = self._f.read(length)
        return out + b"\x00" * (length - len(out))

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()
