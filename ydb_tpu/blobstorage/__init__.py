from ydb_tpu.blobstorage.erasure import ErasureCodec
from ydb_tpu.blobstorage.group import DSProxy, GroupInfo, VDisk
from ydb_tpu.blobstorage.proxy_store import GroupBlobStore

__all__ = ["ErasureCodec", "DSProxy", "GroupInfo", "VDisk",
           "GroupBlobStore"]
