"""Column statistics subsystem: zone maps + mergeable sketches.

The reference keeps two statistics planes — per-portion column min/max
in TPortionInfo metadata consumed by scan planning, and a
StatisticsAggregator tablet merging count-min sketches across shards
for the cost-based optimizer (ydb/core/statistics; SURVEY.md §2.7).
This package is that layer for the TPU build:

  * ``zonemap``   — per-chunk and per-portion min/max/null-count zones
                    for every scan column, plus the predicate algebra
                    that turns a program's conjunctive filters into
                    skip / read / all-match decisions per chunk;
  * ``sketch``    — mergeable count-min sketch and an HLL-style NDV
                    estimator (pure numpy, associative ``merge``);
  * ``aggregator``— the StatisticsAggregator service: folds per-portion
                    sketches into per-shard then table-level stats,
                    snapshot/restore through the tablet WAL machinery;
  * ``cost``      — selectivity + cardinality estimation consumed by
                    scan planning, SSA group-by tier choice and DQ join
                    sizing.

Gating: ``YDB_TPU_STATS=0`` disables every stats CONSUMER (pruning,
planner hints) for A/B runs; zone maps are still written so the flag
can flip per scan. ``STATS_FORCE`` is the in-process test override.
Every pruned plan stays bit-identical to the unpruned one — pruning
only ever removes rows the program's own filters would discard.
"""

from __future__ import annotations

import os

#: test/bench override: True/False forces stats consumption regardless
#: of the environment (same contract as kernels.FUSED_FORCE).
STATS_FORCE: bool | None = None


def stats_enabled() -> bool:
    """Whether scan pruning / planner hints consume column statistics.
    Default on; ``YDB_TPU_STATS=0`` restores the stat-less paths."""
    if STATS_FORCE is not None:
        return STATS_FORCE
    return os.environ.get("YDB_TPU_STATS", "1") not in ("0", "", "off")


from ydb_tpu.stats.sketch import (  # noqa: E402
    ColumnSketch,
    CountMinSketch,
    HyperLogLog,
)
from ydb_tpu.stats.zonemap import (  # noqa: E402
    Pred,
    column_zones,
    extract_predicates,
    match_zone,
    zone_of,
)
from ydb_tpu.stats.cost import ColumnStats, TableStats  # noqa: E402
from ydb_tpu.stats.aggregator import StatisticsAggregator  # noqa: E402

__all__ = [
    "ColumnSketch",
    "ColumnStats",
    "CountMinSketch",
    "HyperLogLog",
    "Pred",
    "StatisticsAggregator",
    "TableStats",
    "column_zones",
    "extract_predicates",
    "match_zone",
    "stats_enabled",
    "zone_of",
    "STATS_FORCE",
]
