"""StatisticsAggregator: per-shard sketches folded into table stats.

Mirror of the reference's statistics aggregator tablet
(ydb/core/statistics/aggregator/aggregator_impl.h; SURVEY.md §2.7): a
service that periodically — and on demand after commit/compaction
events — pulls per-shard column sketches, merges them into table-level
``TableStats`` and serves them to the planner. Durability rides the
SAME tablet WAL machinery as every other coordination tablet
(ydb_tpu.tablet.executor): merged stats snapshot into the executor's
local DB, so a rebooted node plans with yesterday's statistics instead
of none while the first refresh runs.

Collection is incremental: per-(shard, portion) sketches cache in
memory keyed by the immutable portion id, so a refresh only reads
chunks of portions it has never seen; entries of GC'd portions prune.
Memory stays bounded by the live portion count, reads stay bounded by
churn, and the scan path is never touched (stats read blobs directly,
chunk at a time).
"""

from __future__ import annotations

import json
import threading

from ydb_tpu.analysis import sanitizer
from ydb_tpu.obs.probes import probe
from ydb_tpu.stats.cost import ColumnStats, TableStats
from ydb_tpu.stats.sketch import ColumnSketch

_P_REFRESH = probe("stats.aggregator.refresh")


class StatisticsAggregator:
    """Merges per-portion column sketches into table-level statistics.

    ``store`` (optional) enables snapshot/restore through a
    TabletExecutor on that blob store; without it the aggregator is a
    purely in-memory cache. ``start(period, fn)`` runs ``fn`` (the
    owner's refresh closure) on a background thread until ``stop()`` —
    the owner decides WHAT to refresh, the aggregator owns cadence and
    thread lifecycle.
    """

    def __init__(self, store=None, tablet_id: str = "statsaggr"):
        name = f"statsaggr.{id(self):x}"
        self._lock = sanitizer.make_lock(f"{name}.lock")
        # (shard_id, portion_id) -> {column: ColumnSketch}
        self._portions = sanitizer.share({}, f"{name}.portions")
        self._tables = sanitizer.share({}, f"{name}.tables")
        # table -> visible-portion-set fingerprint of the last refresh:
        # a steady-state maintenance tick (nothing committed/compacted)
        # must not re-merge every sketch nor rewrite the WAL snapshot
        self._table_keys = sanitizer.share({}, f"{name}.table_keys")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.executor = None
        if store is not None:
            from ydb_tpu.tablet.executor import TabletExecutor

            self.executor = TabletExecutor.boot(tablet_id, store)
            restored = {}
            for (tname,), row in self.executor.db.table(
                    "table_stats").range():
                restored[tname] = TableStats.from_json(
                    json.loads(row["json"]))
            with self._lock:
                self._tables.update(restored)

    # ---- collection ----

    def _portion_sketches(self, shard, meta) -> dict:
        """Sketches for ONE portion, chunk-streamed (bounded memory)."""
        from ydb_tpu.engine.portion import PortionChunkReader

        rd = PortionChunkReader(shard.store, meta.blob_id)
        out: dict[str, ColumnSketch] = {}
        for i in range(rd.n_chunks):
            cols, valid = rd.read_chunk(i)
            for col, arr in cols.items():
                sk = out.get(col)
                if sk is None:
                    sk = out[col] = ColumnSketch()
                sk.observe(arr, valid.get(col))
        return out

    def collect_shard(self, shard) -> dict:
        """Per-column merged sketches over a shard's visible portions;
        per-portion results cache by immutable portion id."""
        metas = shard.visible_portions()
        fresh: dict = {}
        todo = []
        with self._lock:
            for m in metas:
                key = (shard.shard_id, m.portion_id)
                hit = self._portions.get(key)
                if hit is None:
                    todo.append(m)
                else:
                    fresh[key] = hit
        # chunk reads happen OFF the lock (blob IO must not serialize
        # against concurrent stat lookups)
        computed = {(shard.shard_id, m.portion_id):
                    self._portion_sketches(shard, m) for m in todo}
        with self._lock:
            self._portions.update(computed)
            # prune entries of portions no longer in the shard's map
            live = {(shard.shard_id, m.portion_id) for m in metas}
            dead = [k for k in self._portions
                    if k[0] == shard.shard_id and k not in live]
            for k in dead:
                del self._portions[k]
        fresh.update(computed)
        merged: dict[str, ColumnSketch] = {}
        for sketches in fresh.values():
            for col, sk in sketches.items():
                merged[col] = sk if col not in merged \
                    else merged[col].merge(sk)
        return merged

    def refresh_table(self, name: str, shards) -> TableStats:
        """Pull + merge one table's shard sketches into TableStats and
        persist the snapshot. No-ops (serving the cached snapshot) when
        the table's visible portion set is unchanged since the last
        refresh — the steady-state maintenance tick costs one metadata
        walk, not a re-merge."""
        col_shards = [s for s in shards if hasattr(s, "visible_portions")]
        key = tuple(
            (s.shard_id, tuple(m.portion_id
                               for m in s.visible_portions()))
            for s in col_shards)
        with self._lock:
            cached = self._tables.get(name)
            if cached is not None and self._table_keys.get(name) == key:
                return cached
        merged: dict[str, ColumnSketch] = {}
        rows = 0
        for s in col_shards:
            rows += sum(m.num_rows for m in s.visible_portions())
            for col, sk in self.collect_shard(s).items():
                merged[col] = sk if col not in merged \
                    else merged[col].merge(sk)
        stats = TableStats(rows=rows, columns={
            col: ColumnStats(ndv=sk.ndv, nulls=sk.nulls, rows=sk.rows,
                             vmin=sk.vmin, vmax=sk.vmax,
                             heavy=sk.max_freq)
            for col, sk in merged.items()
        })
        with self._lock:
            self._tables[name] = stats
            self._table_keys[name] = key
        if self.executor is not None:
            payload = json.dumps(stats.to_json())
            self.executor.run(
                lambda txc: txc.put("table_stats", (name,),
                                    {"json": payload}))
        if _P_REFRESH:
            _P_REFRESH.fire(table=name, rows=rows,
                            columns=len(stats.columns))
        return stats

    def refresh_tables(self, tables: dict) -> dict:
        """tables: name -> shard list. Returns name -> TableStats."""
        return {name: self.refresh_table(name, shards)
                for name, shards in tables.items()}

    def refresh_cluster(self, cluster) -> dict:
        """Refresh every column-store table of a Cluster."""
        return self.refresh_tables({
            name: list(getattr(t, "shards", ()))
            for name, t in cluster.tables.items()
        })

    # ---- serving ----

    def table_stats(self, name: str) -> TableStats | None:
        with self._lock:
            return self._tables.get(name)

    def all_stats(self) -> dict:
        with self._lock:
            return dict(self._tables)

    def forget(self, name: str, shard_ids=()) -> None:
        """Drop a table's stats (DROP TABLE). ``shard_ids`` purges the
        per-portion sketch cache too: a re-created same-name table
        reuses shard ids AND restarts portion ids at 1 (the same hazard
        the cluster scan cache documents), so stale entries would serve
        the dropped table's sketches as the new table's statistics."""
        with self._lock:
            self._tables.pop(name, None)
            self._table_keys.pop(name, None)
            drop = set(shard_ids)
            if drop:
                for k in [k for k in self._portions if k[0] in drop]:
                    del self._portions[k]
        if self.executor is not None:
            self.executor.run(
                lambda txc: txc.erase("table_stats", (name,)))

    # ---- cadence ----

    def start(self, period_s: float, refresh_fn) -> None:
        """Background refresh every ``period_s`` seconds until stop().
        ``refresh_fn()`` is the owner's closure (e.g. bound
        ``refresh_cluster``); its errors are swallowed so a transient
        storage hiccup never kills the cadence thread."""
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(timeout=period_s):
                try:
                    refresh_fn()
                except Exception:  # noqa: BLE001 - cadence must survive
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name="stats-aggregator")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
        self._stop = threading.Event()
