"""Mergeable column sketches: count-min + HyperLogLog NDV, pure numpy.

Reference: the statistics service aggregates per-shard count-min
sketches into table-level statistics for the cost-based optimizer
(ydb/core/statistics/aggregator; SURVEY.md §2.7). Both sketches here
are linear structures — count-min merges by elementwise table addition,
HLL by elementwise register max — so per-portion sketches fold into
per-shard then table-level stats in any order (associative AND
commutative; tests/test_stats.py asserts the algebra).

Hashing is splitmix64 over the column's 64-bit physical image (ints
reinterpreted, floats via their IEEE bits), vectorized; no Python-level
per-row work anywhere.

Error contracts (fixed seeds make these deterministic):
  * count-min: estimate >= true always; estimate <= true + e/width * N
    with probability 1 - exp(-depth) per query;
  * HLL: relative NDV error ~ 1.04 / sqrt(2**p) (p=12 -> ~1.6%).
"""

from __future__ import annotations

import numpy as np

_U = np.uint64


def _to_u64(values: np.ndarray) -> np.ndarray:
    """Reinterpret any physical column as uint64 hash input."""
    arr = np.asarray(values)
    if arr.dtype.kind == "f":
        # IEEE bits; normalize -0.0 so it hashes like 0.0
        a = arr.astype(np.float64)
        a = np.where(a == 0.0, 0.0, a)
        return a.view(_U)
    if arr.dtype.kind == "b":
        return arr.astype(_U)
    return arr.astype(np.int64).view(_U)


def _splitmix64(x: np.ndarray, seed: int) -> np.ndarray:
    x = x + _U((seed * 0x9E3779B97F4A7C15 + 0x9E3779B97F4A7C15)
               & 0xFFFFFFFFFFFFFFFF)
    x = (x ^ (x >> _U(30))) * _U(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> _U(27))) * _U(0x94D049BB133111EB)
    return x ^ (x >> _U(31))


class CountMinSketch:
    """Conservative frequency sketch: ``depth`` hash rows of ``width``
    int64 counters; point estimate = min over rows."""

    def __init__(self, width: int = 2048, depth: int = 4, seed: int = 0):
        self.width = int(width)
        self.depth = int(depth)
        self.seed = int(seed)
        self.table = np.zeros((self.depth, self.width), dtype=np.int64)
        self.total = 0

    def _rows(self, values: np.ndarray) -> np.ndarray:
        u = _to_u64(values)
        return np.stack([
            (_splitmix64(u, self.seed + d) % _U(self.width)).astype(
                np.int64)
            for d in range(self.depth)
        ])

    def add_many(self, values: np.ndarray,
                 validity: np.ndarray | None = None) -> None:
        arr = np.asarray(values)
        if validity is not None:
            arr = arr[np.asarray(validity, dtype=bool)]
        if arr.size == 0:
            return
        idx = self._rows(arr)
        for d in range(self.depth):
            np.add.at(self.table[d], idx[d], 1)
        self.total += int(arr.size)

    def estimate(self, value) -> int:
        idx = self._rows(np.asarray([value]))
        return int(min(self.table[d][idx[d][0]]
                       for d in range(self.depth)))

    def max_freq(self) -> int:
        """Upper bound on the most frequent value's count, without
        knowing the value: freq(v) <= table[d, h_d(v)] <= max of row d,
        for every depth d — so min over depths of the per-row max cell
        bounds the heaviest hitter. Tight under adversarial skew (the
        hot key dominates its cells); loose but small (~collision load)
        on uniform data. Feeds shuffle bucket sizing
        (parallel/shuffle.size_buckets)."""
        if self.total == 0:
            return 0
        return int(self.table.max(axis=1).min())

    def merge(self, other: "CountMinSketch") -> "CountMinSketch":
        """Associative/commutative fold (elementwise counter addition).
        Returns a NEW sketch; operands stay untouched."""
        if (self.width, self.depth, self.seed) != (
                other.width, other.depth, other.seed):
            raise ValueError("count-min parameter mismatch")
        out = CountMinSketch(self.width, self.depth, self.seed)
        out.table = self.table + other.table
        out.total = self.total + other.total
        return out

    def to_json(self) -> dict:
        return {"width": self.width, "depth": self.depth,
                "seed": self.seed, "total": self.total,
                "table": self.table.ravel().tolist()}

    @staticmethod
    def from_json(d: dict) -> "CountMinSketch":
        s = CountMinSketch(d["width"], d["depth"], d["seed"])
        s.total = d["total"]
        s.table = np.asarray(d["table"], dtype=np.int64).reshape(
            s.depth, s.width)
        return s


class HyperLogLog:
    """NDV estimator: 2**p uint8 registers over splitmix64 hashes."""

    def __init__(self, p: int = 12, seed: int = 0):
        self.p = int(p)
        self.seed = int(seed)
        self.m = 1 << self.p
        self.registers = np.zeros(self.m, dtype=np.uint8)

    def add_many(self, values: np.ndarray,
                 validity: np.ndarray | None = None) -> None:
        arr = np.asarray(values)
        if validity is not None:
            arr = arr[np.asarray(validity, dtype=bool)]
        if arr.size == 0:
            return
        h = _splitmix64(_to_u64(arr), self.seed)
        reg = (h >> _U(64 - self.p)).astype(np.int64)
        w = (h & _U((1 << (64 - self.p)) - 1)).astype(np.uint64)
        # rank = leading-zero count of the (64-p)-bit suffix + 1; the
        # suffix fits float64's 53-bit mantissa for p >= 11, so frexp
        # gives the exact bit length without a Python loop
        _mant, expo = np.frexp(w.astype(np.float64))
        rank = ((64 - self.p) - expo + 1).astype(np.uint8)
        rank = np.where(w == 0, np.uint8(64 - self.p + 1), rank)
        np.maximum.at(self.registers, reg, rank)

    def estimate(self) -> float:
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        inv = np.ldexp(1.0, -self.registers.astype(np.int64))
        e = alpha * m * m / float(inv.sum())
        zeros = int(np.count_nonzero(self.registers == 0))
        if e <= 2.5 * m and zeros:
            return m * float(np.log(m / zeros))  # linear counting
        return float(e)

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        """Associative/commutative fold (elementwise register max)."""
        if (self.p, self.seed) != (other.p, other.seed):
            raise ValueError("hll parameter mismatch")
        out = HyperLogLog(self.p, self.seed)
        out.registers = np.maximum(self.registers, other.registers)
        return out

    def to_json(self) -> dict:
        return {"p": self.p, "seed": self.seed,
                "registers": self.registers.tolist()}

    @staticmethod
    def from_json(d: dict) -> "HyperLogLog":
        s = HyperLogLog(d["p"], d["seed"])
        s.registers = np.asarray(d["registers"], dtype=np.uint8)
        return s


class ColumnSketch:
    """One column's mergeable statistics bundle: NDV (HLL), frequency
    (count-min), row/null accounting and the physical value zone."""

    def __init__(self, p: int = 12, cm_width: int = 2048,
                 cm_depth: int = 4, seed: int = 0):
        self.hll = HyperLogLog(p, seed)
        self.cms = CountMinSketch(cm_width, cm_depth, seed)
        self.rows = 0
        self.nulls = 0
        self.vmin = None
        self.vmax = None

    def observe(self, values: np.ndarray,
                validity: np.ndarray | None = None) -> None:
        from ydb_tpu.stats.zonemap import zone_of

        arr = np.asarray(values)
        self.rows += int(arr.size)
        vmin, vmax, nulls = zone_of(arr, validity)
        self.nulls += nulls
        if vmin is not None:
            self.vmin = vmin if self.vmin is None else min(self.vmin, vmin)
            self.vmax = vmax if self.vmax is None else max(self.vmax, vmax)
        self.hll.add_many(arr, validity)
        self.cms.add_many(arr, validity)

    @property
    def ndv(self) -> int:
        return max(int(round(self.hll.estimate())), 1) \
            if self.rows > self.nulls else 0

    @property
    def null_fraction(self) -> float:
        return self.nulls / self.rows if self.rows else 0.0

    @property
    def max_freq(self) -> int:
        """Heaviest-hitter bound (CountMinSketch.max_freq)."""
        return self.cms.max_freq()

    def merge(self, other: "ColumnSketch") -> "ColumnSketch":
        out = ColumnSketch()
        out.hll = self.hll.merge(other.hll)
        out.cms = self.cms.merge(other.cms)
        out.rows = self.rows + other.rows
        out.nulls = self.nulls + other.nulls
        mins = [v for v in (self.vmin, other.vmin) if v is not None]
        maxs = [v for v in (self.vmax, other.vmax) if v is not None]
        out.vmin = min(mins) if mins else None
        out.vmax = max(maxs) if maxs else None
        return out

    def to_json(self) -> dict:
        return {"hll": self.hll.to_json(), "cms": self.cms.to_json(),
                "rows": self.rows, "nulls": self.nulls,
                "vmin": self.vmin, "vmax": self.vmax}

    @staticmethod
    def from_json(d: dict) -> "ColumnSketch":
        s = ColumnSketch()
        s.hll = HyperLogLog.from_json(d["hll"])
        s.cms = CountMinSketch.from_json(d["cms"])
        s.rows = d["rows"]
        s.nulls = d["nulls"]
        s.vmin = d["vmin"]
        s.vmax = d["vmax"]
        return s
