"""Selectivity + cardinality estimation over table statistics.

The CBO feed (ydb/library/yql/core/cbo shape): table-level row counts,
per-column NDV/null-fraction/value bounds (stats.aggregator) turned
into

  * conjunctive filter selectivity (equality via 1/NDV, ranges via
    value-span fractions, IN via k/NDV) — plan sizing for scans;
  * group-count estimates (capped NDV products) — the SSA compiler's
    group-by tier choice and group-capacity sizing;
  * plan-node row estimates — DQ join build-side selection and expand
    fanout sizing (kqp/dq_lower).

Estimates are advisory ONLY: every consumer treats them as performance
hints and keeps exactness through its own mechanisms (zone-derived
bounds are exact; estimated tiers all compute identical results).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ColumnStats:
    """Table-level statistics for one column (physical value domain)."""

    ndv: int = 0
    nulls: int = 0
    rows: int = 0
    vmin: object = None
    vmax: object = None
    # heaviest-hitter frequency bound (CountMinSketch.max_freq): the
    # most common value occurs at most this many times. Sizes shuffle
    # buckets under skew (parallel/shuffle.size_buckets); 0 = unknown.
    heavy: int = 0

    @property
    def null_fraction(self) -> float:
        return self.nulls / self.rows if self.rows else 0.0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "ColumnStats":
        return ColumnStats(**d)


@dataclasses.dataclass
class TableStats:
    rows: int = 0
    columns: dict = dataclasses.field(default_factory=dict)

    def column(self, name: str) -> ColumnStats | None:
        return self.columns.get(name)

    def to_json(self) -> dict:
        return {"rows": self.rows,
                "columns": {n: c.to_json()
                            for n, c in self.columns.items()}}

    @staticmethod
    def from_json(d: dict) -> "TableStats":
        return TableStats(
            rows=d["rows"],
            columns={n: ColumnStats.from_json(c)
                     for n, c in d["columns"].items()})


def _span_fraction(cs: ColumnStats, lo, hi) -> float:
    """Fraction of the value span [vmin, vmax] covered by [lo, hi],
    assuming uniformity (the classic System-R guess)."""
    if cs.vmin is None or cs.vmax is None:
        return 0.33
    try:
        span = float(cs.vmax) - float(cs.vmin)
        if span <= 0:
            return 1.0
        lo_c = max(float(lo), float(cs.vmin))
        hi_c = min(float(hi), float(cs.vmax))
        return max(0.0, min(1.0, (hi_c - lo_c) / span))
    except (TypeError, ValueError):
        return 0.33


def pred_selectivity(pred, stats: TableStats) -> float:
    """Selectivity of one zone-style conjunct (stats.zonemap.Pred)."""
    cs = stats.column(pred.column)
    if pred.op == "never":
        return 0.0
    if cs is None or cs.rows == 0:
        return 0.33
    notnull = 1.0 - cs.null_fraction
    ndv = max(cs.ndv, 1)
    if pred.op == "eq":
        return notnull / ndv
    if pred.op == "ne":
        return notnull * (1.0 - 1.0 / ndv)
    if pred.op == "in":
        return min(1.0, notnull * len(pred.value) / ndv)
    v = pred.value
    if pred.op in ("lt", "le"):
        return notnull * _span_fraction(cs, cs.vmin, v)
    if pred.op in ("gt", "ge"):
        return notnull * _span_fraction(cs, v, cs.vmax)
    return 0.33


def conj_selectivity(preds, stats: TableStats) -> float:
    """Independence-model selectivity of a conjunction, with per-column
    range conjuncts (lo <= c AND c < hi) intersected exactly instead of
    multiplied — the common band predicate would otherwise square its
    own selectivity."""
    sel = 1.0
    by_col: dict[str, list] = {}
    for p in preds:
        by_col.setdefault(p.column, []).append(p)
    for col, ps in by_col.items():
        cs = stats.column(col)
        ranged = [p for p in ps if p.op in ("lt", "le", "gt", "ge")]
        rest = [p for p in ps if p.op not in ("lt", "le", "gt", "ge")]
        if len(ranged) >= 2 and cs is not None and cs.rows:
            lo = max((p.value for p in ranged
                      if p.op in ("gt", "ge")), default=cs.vmin)
            hi = min((p.value for p in ranged
                      if p.op in ("lt", "le")), default=cs.vmax)
            sel *= (1.0 - cs.null_fraction) * _span_fraction(cs, lo, hi)
        else:
            for p in ranged:
                sel *= pred_selectivity(p, stats)
        for p in rest:
            sel *= pred_selectivity(p, stats)
    return max(0.0, min(1.0, sel))


def estimate_filter_rows(program, schema, stats: TableStats) -> float:
    """Estimated output rows of a program's leading filters over a
    table with ``stats``."""
    from ydb_tpu.stats.zonemap import extract_predicates

    preds, _full = extract_predicates(program, schema)
    return stats.rows * conj_selectivity(preds, stats)


def estimate_group_count(keys, stats: TableStats) -> float | None:
    """Estimated distinct group count of GROUP BY ``keys``: NDV product
    capped by the row count. None when no key has statistics."""
    est = 1.0
    known = False
    for k in keys:
        cs = stats.column(k)
        if cs is None or cs.ndv <= 0:
            continue
        known = True
        est *= cs.ndv + (1 if cs.nulls else 0)  # NULL forms its own group
    if not known:
        return None
    return min(est, float(max(stats.rows, 1)))


def choose_group_tier(n_groups: float) -> str:
    """The group-by execution tier a given group count lands in (the
    acceptance oracle: the tier chosen from the NDV estimate must match
    the tier the TRUE group count picks)."""
    from ydb_tpu.ssa import kernels

    if n_groups <= kernels.ONEHOT_GROUP_LIMIT:
        return "onehot"
    return "large"


def estimate_plan_rows(node, stats_by_table: dict,
                       schemas: dict | None = None) -> float | None:
    """Row estimate for a logical plan subtree (plan.nodes shapes).
    None = unknown (consumers keep their defaults). ``schemas`` (table
    -> dtypes.Schema) types predicate literals correctly — without the
    real schema a decimal column's scaled physical bounds would be
    compared against a descaled literal, skewing band selectivities by
    orders of magnitude; stat-known columns then fall back to INT64."""
    from ydb_tpu.plan.nodes import ExpandJoin, LookupJoin, TableScan, Transform

    if isinstance(node, TableScan):
        st = stats_by_table.get(node.table)
        if st is None:
            return None
        if node.program is None:
            return float(st.rows)
        from ydb_tpu.ssa.program import GroupByStep

        gb = next((s for s in node.program.steps
                   if isinstance(s, GroupByStep)), None)
        schema = (schemas or {}).get(node.table) or _scan_schema(st)
        try:
            rows = estimate_filter_rows(node.program, schema, st)
        except KeyError:
            rows = float(st.rows)
        if gb is not None:
            g = estimate_group_count(gb.keys, st)
            rows = min(rows, g) if g is not None else rows
        return rows
    if isinstance(node, LookupJoin):
        return estimate_plan_rows(node.probe, stats_by_table, schemas)
    if isinstance(node, ExpandJoin):
        p = estimate_plan_rows(node.probe, stats_by_table, schemas)
        b = estimate_plan_rows(node.build, stats_by_table, schemas)
        if p is None or b is None:
            return None
        # equi-join: |P||B| / max(ndv) with unknown key NDV -> assume
        # a modest 4x fanout bound
        return p * min(4.0, max(b, 1.0) ** 0.5)
    if isinstance(node, Transform):
        return estimate_plan_rows(node.input, stats_by_table, schemas)
    return None


def _scan_schema(stats: TableStats):
    """Fallback synthetic schema naming the stat-known columns as
    INT64 (selectivity needs names + a numeric type); callers with the
    real catalog pass ``schemas`` instead."""
    from ydb_tpu import dtypes

    return dtypes.Schema(tuple(
        dtypes.Field(n, dtypes.INT64) for n in stats.columns))
