"""Zone maps: per-chunk / per-portion column min-max-null statistics
and the predicate algebra that consumes them.

Reference shape: TPortionInfo column metadata (min/max per column blob,
engines/portion_info.h) consumed by the scan planner's range
intersection (SURVEY.md §2.7). Here a *zone* is ``[vmin, vmax,
null_count]`` per column per row-group chunk, serialized into the
portion blob header (v1 headers, engine/portion.py) and — at portion
granularity — into ``PortionMeta.zones`` so planning never touches blob
storage.

Value domain: zones hold PHYSICAL column values (scaled-decimal int64s,
dict-encoded string ids, float64s), and predicates are converted into
the same domain before matching (``physical_const``). Matching is a
trichotomy — ``none`` (no row can satisfy the predicate: skip the
chunk), ``some`` (must read), ``all`` (every row provably satisfies it:
the filter kernel can be skipped for this data). NULL rows never match
a comparison predicate, so ``none`` ignores nulls while ``all``
additionally requires ``null_count == 0``.

All decisions are conservative: an unknown zone, an undecomposable
expression or a dtype surprise degrades to "read the chunk", never to a
wrong skip — pruned scans stay bit-identical to unpruned ones.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.ssa.ops import Op
from ydb_tpu.ssa.program import (
    AssignStep,
    Call,
    Col,
    Const,
    DictPredicate,
    FilterStep,
    Program,
    ProjectStep,
)

# ---------------- zone construction (write path) ----------------


def zone_of(arr: np.ndarray, validity: np.ndarray | None = None):
    """(vmin, vmax, null_count) of one column slice, dtype-aware.

    Typed values: ints (incl. dict ids, scaled decimals, dates) stay
    ints; floats stay floats (NaN bounds are legal and match nothing,
    which is conservative both ways); bools report 0/1. ``(None, None,
    nulls)`` when no valid value exists.
    """
    n = int(arr.size)
    if validity is not None:
        nulls = n - int(np.count_nonzero(validity))
        vals = arr[validity] if nulls else arr
    else:
        nulls = 0
        vals = arr
    if vals.size == 0:
        return None, None, nulls
    vmin, vmax = vals.min(), vals.max()
    if arr.dtype.kind in ("i", "u", "b"):
        return int(vmin), int(vmax), nulls
    if arr.dtype.kind == "f":
        return float(vmin), float(vmax), nulls
    return None, None, nulls  # unknown physical dtype: no stats


def column_zones(
    columns: dict[str, np.ndarray],
    validity: dict[str, np.ndarray] | None = None,
    lo: int = 0,
    hi: int | None = None,
) -> dict[str, list]:
    """JSON-ready zones for every column of a row slice ``[lo, hi)``.

    This is the vectorized write-path entry: one min/max/count pass per
    column slice, no python-per-row work."""
    out: dict[str, list] = {}
    for name, arr in columns.items():
        end = len(arr) if hi is None else hi
        v = None
        if validity and name in validity:
            v = validity[name][lo:end]
        vmin, vmax, nulls = zone_of(arr[lo:end], v)
        if vmin is None and nulls == 0 and end > lo:
            continue  # unstatable dtype: omit rather than lie
        out[name] = [vmin, vmax, nulls]
    return out


# ---------------- predicates (read path) ----------------

#: comparison flip for ``const OP col`` spellings
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
         "eq": "eq", "ne": "ne"}

_CMP_OPS = {Op.EQ: "eq", Op.NE: "ne", Op.LT: "lt", Op.LE: "le",
            Op.GT: "gt", Op.GE: "ge"}


@dataclasses.dataclass(frozen=True)
class Pred:
    """One zone-checkable conjunct: ``column OP value`` in the column's
    physical domain. ``op`` is eq|ne|lt|le|gt|ge|in|never ("never" =
    provably constant-false, e.g. equality with an absent dictionary
    value: the whole scan may be emptied)."""

    column: str
    op: str
    value: object = None  # scalar, or sorted tuple for "in"
    step: int = -1        # FilterStep index this conjunct came from

    def fingerprint(self) -> tuple:
        """Hashable identity for cache keys (a pruned block stream is
        only reusable under the same predicate set)."""
        return (self.column, self.op, self.value)


def physical_const(col_type: dtypes.LogicalType, value, value_type):
    """Convert a literal into the column's physical value domain.
    Returns an int/float, or None when not convertible (skip the
    conjunct)."""
    if value is None or isinstance(value, (bytes, str)):
        return None
    if isinstance(value, bool):
        value = int(value)
    if not isinstance(value, (int, float)):
        return None
    vscale = value_type.scale if value_type is not None and \
        value_type.is_decimal else 0
    if col_type.is_decimal:
        shift = col_type.scale - vscale
        if isinstance(value, int) and shift >= 0:
            return value * 10 ** shift
        return float(value) * 10.0 ** shift
    # non-decimal column: descale a decimal literal into plain value
    if vscale:
        return float(value) / 10.0 ** vscale
    if col_type.is_floating:
        return float(value)
    return value


def _decompose(expr, step_idx: int, schema: dtypes.Schema,
               shadowed: set, dicts) -> tuple[list, bool]:
    """(preds, full): conjuncts extracted from one filter expression and
    whether the WHOLE tree decomposed (required for the filter-skip
    fast path; partial extraction still prunes)."""
    if isinstance(expr, Call) and expr.op is Op.AND and len(expr.args) == 2:
        pa, fa = _decompose(expr.args[0], step_idx, schema, shadowed, dicts)
        pb, fb = _decompose(expr.args[1], step_idx, schema, shadowed, dicts)
        return pa + pb, fa and fb

    def col_of(e):
        if isinstance(e, Col) and e.name not in shadowed \
                and e.name in schema:
            return e.name
        return None

    if isinstance(expr, Call) and expr.op in _CMP_OPS \
            and len(expr.args) == 2:
        a, b = expr.args
        name, const, op = None, None, _CMP_OPS[expr.op]
        if col_of(a) is not None and isinstance(b, Const):
            name, const = col_of(a), b
        elif col_of(b) is not None and isinstance(a, Const):
            name, const, op = col_of(b), a, _FLIP[_CMP_OPS[expr.op]]
        if name is not None:
            t = schema.field(name).type
            v = physical_const(t, const.value, const.type)
            if v is not None:
                if op == "eq" and t.is_integer and \
                        isinstance(v, float) and not v.is_integer():
                    return [Pred(name, "never", step=step_idx)], True
                return [Pred(name, op, v, step_idx)], True
        return [], False
    if isinstance(expr, Call) and expr.op is Op.IN_SET and expr.args:
        name = col_of(expr.args[0])
        if name is not None and all(
                isinstance(a, Const) for a in expr.args[1:]):
            t = schema.field(name).type
            vals = []
            for a in expr.args[1:]:
                v = physical_const(t, a.value, a.type)
                if v is None:
                    return [], False
                vals.append(v)
            if not vals:
                return [Pred(name, "never", step=step_idx)], True
            return [Pred(name, "in", tuple(sorted(set(vals))),
                         step_idx)], True
        return [], False
    if isinstance(expr, DictPredicate) and dicts is not None \
            and expr.column in schema and expr.column not in shadowed:
        d = dicts[expr.column] if expr.column in dicts else None
        if d is None:
            return [], False
        if expr.kind == "eq":
            i = d.eq_id(expr.pattern)
            if i < 0:
                return [Pred(expr.column, "never", step=step_idx)], True
            return [Pred(expr.column, "eq", int(i), step_idx)], True
        if expr.kind == "in_set":
            ids = sorted({int(d.eq_id(v)) for v in expr.pattern
                          if d.eq_id(v) >= 0})
            if not ids:
                return [Pred(expr.column, "never", step=step_idx)], True
            return [Pred(expr.column, "in", tuple(ids), step_idx)], True
        return [], False
    return [], False


def extract_predicates(
    program: Program, schema: dtypes.Schema, dicts=None,
) -> tuple[list[Pred], set[int]]:
    """Zone-checkable conjuncts of a program's leading filters.

    Walks steps in order and stops at the first step that changes row
    identity (group-by/sort/window): a filter after such a step gates
    groups or post-limit rows, not source rows, and must never prune
    chunks. Columns shadowed by a prior AssignStep are skipped — their
    values are no longer the stored bytes the zones describe.

    Returns ``(preds, full_steps)``: ``full_steps`` are the FilterStep
    indices whose entire expression decomposed — candidates for the
    skip-the-filter-kernel fast path when every surviving zone reports
    "all".
    """
    preds: list[Pred] = []
    full: set[int] = set()
    shadowed: set = set()
    for i, step in enumerate(program.steps):
        if isinstance(step, AssignStep):
            shadowed.add(step.name)
        elif isinstance(step, FilterStep):
            got, whole = _decompose(step.expr, i, schema, shadowed, dicts)
            preds.extend(got)
            if whole and got:
                full.add(i)
        elif isinstance(step, ProjectStep):
            continue
        else:
            break  # group-by / sort / window: later filters don't prune
    return preds, full


# ---------------- zone matching ----------------


def match_zone(zone, pred: Pred, rows: int | None = None) -> str:
    """Trichotomy of one predicate against one zone: 'none' | 'some' |
    'all'. ``zone`` is ``[vmin, vmax, null_count]`` (or None for
    stat-less data)."""
    if pred.op == "never":
        return "none"
    if zone is None:
        return "some"
    vmin, vmax, nulls = zone[0], zone[1], zone[2]
    if vmin is None:
        # zero valid values: NULL rows match no comparison predicate
        return "none"
    try:
        if isinstance(vmin, float) and (math.isnan(vmin)
                                        or math.isnan(vmax)):
            return "some"  # NaN bounds prove nothing either way
        no_nulls = nulls == 0
        v = pred.value
        if pred.op == "eq":
            if v < vmin or v > vmax:
                return "none"
            return "all" if (vmin == vmax == v and no_nulls) else "some"
        if pred.op == "ne":
            if vmin == vmax == v:
                return "none"
            return "all" if no_nulls and (v < vmin or v > vmax) \
                else "some"
        if pred.op == "lt":
            if vmin >= v:
                return "none"
            return "all" if no_nulls and vmax < v else "some"
        if pred.op == "le":
            if vmin > v:
                return "none"
            return "all" if no_nulls and vmax <= v else "some"
        if pred.op == "gt":
            if vmax <= v:
                return "none"
            return "all" if no_nulls and vmin > v else "some"
        if pred.op == "ge":
            if vmax < v:
                return "none"
            return "all" if no_nulls and vmin >= v else "some"
        if pred.op == "in":
            inside = [s for s in v if vmin <= s <= vmax]
            if not inside:
                return "none"
            return "all" if (no_nulls and vmin == vmax
                             and vmin in v) else "some"
    except TypeError:
        return "some"  # incomparable domains: never skip on a surprise
    return "some"


def zones_decide(zones: dict | None, preds: list[Pred]) -> tuple[bool, set]:
    """Evaluate conjuncts against one zone dict (a chunk's or a
    portion's). Returns ``(skip, all_steps)``: skip is True when ANY
    conjunct proves no row matches; ``all_steps`` is the set of step
    indices whose every conjunct (on zone-known columns) reported
    'all' **for this zone dict** — callers intersect across data units
    before dropping a filter."""
    all_by_step: dict[int, bool] = {}
    for p in preds:
        zone = None if zones is None else zones.get(p.column)
        m = match_zone(zone, p)
        if m == "none":
            return True, set()
        all_by_step[p.step] = all_by_step.get(p.step, True) and m == "all"
    return False, {s for s, ok in all_by_step.items() if ok}


def drop_filter_steps(program: Program, steps: set[int]) -> Program:
    """Program with the given FilterStep indices removed (the fast path
    for zone-proven all-match filters — every row passes them, so the
    compiled program need not evaluate them)."""
    if not steps:
        return program
    kept = tuple(s for i, s in enumerate(program.steps) if i not in steps)
    return Program(kept)


def preds_fingerprint(preds: list[Pred]) -> tuple:
    """Canonical hashable identity of a predicate set — block-cache keys
    must include it: a pruned block stream only equals another stream
    pruned under the SAME predicates."""
    return tuple(sorted(p.fingerprint() for p in preds))
