"""Deterministic distributed commit: the coordinator/mediator plane.

Reference (SURVEY.md §2.5, §3.2-commit): a Coordinator tablet assigns
monotonically increasing *plan steps* to proposed transactions, batches
them, and Mediators fan the planned tx ids to participant tablets, which
execute planned txs in step order; MVCC snapshots read at (step, tx) time.
Volatile txs skip the coordinator round for single-step commits.

TPU build: transactions are host-side metadata operations (the device
never participates in commit). This module keeps the same contract in one
process — the coordinator is the single source of global time:

  * ``propose(participants)`` assigns the next plan step
  * every participant shard commits *at that step* (ColumnShard.commit
    with an explicit snapshot), all-or-nothing per the prepare checks
  * a read snapshot is just a plan step: readers at step S see exactly
    the transactions planned <= S on every shard — the same guarantee
    the reference's mediator time barrier provides

The multi-node version replaces direct calls with the runtime actor shim
(ydb_tpu.runtime) carrying the same messages.

Durability: the reference coordinator persists planned steps before
handing them out (tx/coordinator/coordinator__plan_step.cpp); here a
``Coordinator(store)`` write-ahead-reserves step ranges in the blob store
(hi-lo allocation: one put per ``reserve`` steps, not per tx), so a
rebooted coordinator resumes strictly after every step it might ever have
assigned — shard snapshots stay monotonic across coordinator crashes.
"""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class TxResult:
    txid: int
    step: int
    committed: bool
    error: str | None = None


class Coordinator:
    """Global plan-step clock + two-phase commit driver.

    Commits serialize on a commit lock (the reference coordinator also
    plans steps through one tablet), which keeps per-shard steps monotonic
    under concurrency. ``read_snapshot`` returns the last *fully
    committed* step — the mediator-time barrier: a step becomes readable
    only after every participant of every tx planned at or before it has
    committed, so readers never see a torn cross-shard transaction.
    """

    STEP_KEY = "coordinator/plan_step"

    def __init__(self, store=None, start_step: int = 0, reserve: int = 64):
        self._lock = threading.Lock()
        self._commit_lock = threading.Lock()
        self._store = store
        self._reserve = max(1, int(reserve))
        if store is not None and store.exists(self.STEP_KEY):
            start_step = max(start_step,
                             int(store.get(self.STEP_KEY).decode()))
        self._step = start_step
        self._completed = start_step
        # persisted ceiling: every handed-out step is <= _reserved before
        # it leaves plan(), so recovery never re-assigns a used step
        self._reserved = start_step
        self._next_txid = start_step + 1
        # mediator fan-out: callbacks invoked (outside locks) whenever
        # the completed-step barrier advances (tx/mediator.py)
        self._on_complete: list = []
        # volatile steps planned but not yet decided: the completed
        # barrier may never pass an undecided step, or a snapshot read
        # repeated after the late decision would change result
        # (non-monotonic reads)
        self._outstanding: set[int] = set()
        # high-water of steps whose effects are applied
        self._applied = start_step

    @property
    def last_step(self) -> int:
        return self._step

    def read_snapshot(self) -> int:
        """Last fully-committed plan step (mediator time barrier)."""
        with self._lock:
            return self._completed

    def _plan_locked(self, register: bool) -> tuple[int, int]:
        """Step allocation body (callers hold no lock). ``register``
        adds the step to the outstanding set: the completed barrier
        cannot pass it until ``_resolve`` — EVERY multi-effect commit
        path registers its step so no path's barrier advance can
        expose another path's mid-apply step (torn read)."""
        with self._lock:
            self._step += 1
            if self._store is not None and self._step > self._reserved:
                self._reserved = self._step + self._reserve - 1
                self._store.put(self.STEP_KEY,
                                str(self._reserved).encode())
            txid = self._next_txid
            self._next_txid += 1
            if register:
                self._outstanding.add(self._step)
            return txid, self._step

    def _resolve(self, step: int) -> None:
        with self._lock:
            self._outstanding.discard(step)

    def plan(self) -> tuple[int, int]:
        """Assign (txid, step) for a new transaction."""
        return self._plan_locked(register=False)

    def subscribe_completed(self, fn) -> None:
        """Register a mediator callback: fn(step) fires on every barrier
        advance (after the step is fully applied)."""
        self._on_complete.append(fn)

    def _mark_completed(self, step: int) -> None:
        with self._lock:
            self._applied = max(self._applied, step)
            bound = (min(self._outstanding) - 1 if self._outstanding
                     else self._applied)
            new = min(self._applied, bound)
            advanced = new > self._completed
            if advanced:
                self._completed = new
            completed = self._completed
        if advanced:
            for fn in self._on_complete:
                fn(completed)

    def background_plan(self) -> int:
        """Plan step for a single-shard background op (compaction/TTL).

        Marked completed immediately: shard-local metadata swaps cannot
        tear a cross-shard read, and background results should become
        visible without waiting for the next distributed commit. Takes the
        commit lock so it cannot interleave with an in-flight distributed
        commit and advance the barrier past its not-yet-applied step."""
        with self._commit_lock:
            _, step = self.plan()
            self._mark_completed(step)
            return step

    def commit(self, participants: list, prepare_args: list) -> TxResult:
        """Two-phase commit: prepare on every participant, then commit all
        at one plan step.

        Prepare failure aborts EVERY participant (prepared or not) and
        returns committed=False. Once all prepares succeed the decision is
        commit: commit_at is applied to every participant even if one
        errors (textbook 2PC — post-decision failures need repair/retry,
        not rollback), and any such error surfaces as RuntimeError after
        all attempts.

        Single-participant commits take the VOLATILE fast path
        (datashard volatile_tx.h analog): no cross-shard atomicity is at
        stake, so the decision collapses to one prepare+apply and the
        read barrier advances immediately — the common single-shard
        write skips the 2PC decision bookkeeping.
        """
        if len(participants) == 1:
            with self._commit_lock:
                txid, step = self._plan_locked(register=True)
                try:
                    p, args = participants[0], prepare_args[0]
                    try:
                        token = p.prepare(args)
                    except Exception as e:
                        try:
                            p.abort(args)
                        except Exception:
                            pass
                        return TxResult(txid, step, False,
                                        f"prepare: {e}")
                    p.commit_at(token, step)
                finally:
                    self._resolve(step)
                self._mark_completed(step)
                return TxResult(txid, step, True)
        with self._commit_lock:
            txid, step = self._plan_locked(register=True)
            try:
                tokens = []
                failed = None
                for p, args in zip(participants, prepare_args):
                    try:
                        tokens.append(p.prepare(args))
                    except Exception as e:
                        failed = e
                        break
                if failed is not None:
                    # abort cleanup, bounded by participant SHARDS
                    # ydb-lint: disable=H006
                    for p, args, i in zip(participants, prepare_args,
                                          range(len(participants))):
                        try:
                            p.abort(tokens[i] if i < len(tokens)
                                    else args)
                        except Exception:
                            pass
                    return TxResult(txid, step, False,
                                    f"prepare: {failed}")
                errors = []
                for p, t in zip(participants, tokens):
                    try:
                        p.commit_at(t, step)
                    except Exception as e:  # post-decision: keep going
                        errors.append((p, e))
            finally:
                self._resolve(step)
            self._mark_completed(step)
            if errors:
                raise RuntimeError(
                    f"commit decided at step {step} but participants "
                    f"failed to apply: {errors}; shard repair required"
                )
            return TxResult(txid, step, True)

    def commit_volatile(self, participants: list,
                        prepare_args: list) -> TxResult:
        """Volatile distributed commit (volatile_tx.h:91 +
        datashard_outreadset.h): NO prepare round-trip under the commit
        lock — the step is planned and registered outstanding, each
        participant validates + optimistically accepts independently,
        and outcomes propagate as readsets; every participant finalizes
        (or rolls back) on its own once its expected readsets arrive.
        The completed barrier cannot pass the step until the decision,
        so snapshot reads stay monotonic; concurrent classic commits at
        later steps proceed without waiting (no _commit_lock hold
        across the apply phase — the serialization VERDICT weak #7
        called out).
        """
        if len(participants) == 1:
            return self.commit(participants, prepare_args)
        txid, step = self._plan_locked(register=True)
        ids = list(range(len(participants)))
        outcomes = []
        try:
            for p, args, pid in zip(participants, prepare_args, ids):
                peers = [q for q in ids if q != pid]
                outcomes.append(
                    p.apply_volatile(args, txid, step, peers))
            # readset exchange: every outcome reaches every peer;
            # participants decide locally (commit on all-ok, rollback
            # on the first negative readset)
            for qid, q in zip(ids, participants):
                for pid in ids:
                    if pid != qid:
                        q.deliver_readset(txid, pid, outcomes[pid])
        except Exception:
            # an escaped error (storage failure mid-exchange, ...)
            # must not leave accepted participants wedged undecided:
            # roll their volatile state back before surfacing
            for p in participants:
                try:
                    p.abort_volatile(txid)
                except Exception:
                    pass
            raise
        finally:
            self._resolve(step)
        if all(outcomes):
            self._mark_completed(step)
            return TxResult(txid, step, True)
        # unblock the barrier for later steps: the aborted step holds
        # no effects, so completing it is safe
        self._mark_completed(step)
        bad = [i for i, ok in zip(ids, outcomes) if not ok]
        return TxResult(txid, step, False,
                        f"volatile abort: participants {bad} rejected")
