"""Deterministic distributed commit: the coordinator/mediator plane.

Reference (SURVEY.md §2.5, §3.2-commit): a Coordinator tablet assigns
monotonically increasing *plan steps* to proposed transactions, batches
them, and Mediators fan the planned tx ids to participant tablets, which
execute planned txs in step order; MVCC snapshots read at (step, tx) time.
Volatile txs skip the coordinator round for single-step commits.

TPU build: transactions are host-side metadata operations (the device
never participates in commit). This module keeps the same contract in one
process — the coordinator is the single source of global time:

  * ``propose(participants)`` assigns the next plan step
  * every participant shard commits *at that step* (ColumnShard.commit
    with an explicit snapshot), all-or-nothing per the prepare checks
  * a read snapshot is just a plan step: readers at step S see exactly
    the transactions planned <= S on every shard — the same guarantee
    the reference's mediator time barrier provides

The multi-node version replaces direct calls with the runtime actor shim
(ydb_tpu.runtime) carrying the same messages.
"""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class TxResult:
    txid: int
    step: int
    committed: bool
    error: str | None = None


class Coordinator:
    """Global plan-step clock + two-phase commit driver."""

    def __init__(self, start_step: int = 0):
        self._lock = threading.Lock()
        self._step = start_step
        self._next_txid = 1

    @property
    def last_step(self) -> int:
        return self._step

    def read_snapshot(self) -> int:
        """Current consistent read point (mediator-time analog)."""
        with self._lock:
            return self._step

    def plan(self) -> tuple[int, int]:
        """Assign (txid, step) for a new transaction."""
        with self._lock:
            self._step += 1
            txid = self._next_txid
            self._next_txid += 1
            return txid, self._step

    def commit(self, participants: list, prepare_args: list) -> TxResult:
        """Two-phase commit: prepare on every participant, then commit all
        at one plan step; abort (release) everywhere on any failure.

        ``participants`` expose prepare(args) -> token, commit_at(token,
        step), abort(token).
        """
        txid, step = self.plan()
        tokens = []
        try:
            for p, args in zip(participants, prepare_args):
                tokens.append(p.prepare(args))
        except Exception as e:  # prepare failed somewhere: abort prepared
            for p, t in zip(participants, tokens):
                p.abort(t)
            return TxResult(txid, step, False, f"prepare: {e}")
        for p, t in zip(participants, tokens):
            p.commit_at(t, step)
        return TxResult(txid, step, True)
