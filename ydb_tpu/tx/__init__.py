from ydb_tpu.tx.coordinator import Coordinator, TxResult  # noqa: F401
from ydb_tpu.tx.sharded import ShardedTable  # noqa: F401
