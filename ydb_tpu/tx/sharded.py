"""Sharded tables: partition parallelism over ColumnShards with
coordinated commits and consistent cross-shard snapshots.

Reference shape (SURVEY.md §2.11 row 1): a table splits into tablets by PK
range (row) or hash sharding function (OLAP, tx/sharding/); writes route
by the sharding function, distributed commits ride coordinator plan steps,
and scans fan out per shard and merge. Here:

  * ``insert`` routes rows by hash(pk) % n_shards, writes each shard's
    slice, and commits everything at ONE coordinator plan step — readers
    at any step see all-or-nothing across shards
  * ``scan`` runs the partial program per shard (one compiled executable
    shared across shards — same schema, same block capacity) and merges
    partials with the final program, exactly the MeshScan dataflow with
    host-side shards standing in for mesh devices
  * dictionaries are table-level, shared by all shards, so ids agree in
    cross-shard merges
"""

from __future__ import annotations

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import concat_blocks
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa.program import Program
from ydb_tpu.tx.coordinator import Coordinator, TxResult


def _fnv_route(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Deterministic row -> shard routing (tx/sharding hash analog)."""
    h = keys.astype(np.uint64)
    h ^= h >> 33
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> 33
    return (h % np.uint64(n_shards)).astype(np.int64)


class ShardedTable:
    def __init__(
        self,
        name: str,
        schema: dtypes.Schema,
        store: BlobStore,
        coordinator: Coordinator,
        n_shards: int = 4,
        pk_column: str | None = None,
        ttl_column: str | None = None,
        config: ShardConfig | None = None,
        dicts: DictionarySet | None = None,
        boot: bool = False,
        upsert: bool = False,
    ):
        self.name = name
        self.schema = schema
        self.coordinator = coordinator
        self.pk_column = pk_column or schema.names[0]
        # upsert: PK rewrite shadows the old row. Rows route by PK hash,
        # so one key always lands on one shard and per-shard newest-wins
        # dedup (engine.reader) is globally correct.
        self.upsert = upsert
        self.dicts = dicts if dicts is not None else DictionarySet()
        if boot:
            # reboot from the blob store (snapshot + WAL per shard); the
            # shared dict set must already be recovered by the caller
            self.shards = [
                ColumnShard.boot(
                    f"{name}/{i}", schema, store,
                    pk_column=self.pk_column, ttl_column=ttl_column,
                    config=config, dicts=self.dicts,
                )
                for i in range(n_shards)
            ]
            for s in self.shards:
                s.upsert = upsert
        else:
            self.shards = [
                ColumnShard(
                    f"{name}/{i}", schema, store,
                    pk_column=self.pk_column, ttl_column=ttl_column,
                    config=config, dicts=self.dicts, upsert=upsert,
                )
                for i in range(n_shards)
            ]
        for s in self.shards:
            s.snap_source = coordinator.background_plan
        # called after string encode but before any shard write: the
        # cluster journals dictionary growth here so no durable shard
        # state ever references a dict id that is not itself durable
        self.pre_commit = None

    def storage_prefixes(self) -> list[str]:
        """Blob-store prefixes owning this table's durable state (DROP
        TABLE deletes them so a same-name CREATE starts empty)."""
        return [f"{s.shard_id}/" for s in self.shards]

    def alter_schema(
        self,
        schema: dtypes.Schema,
        schema_version: int = 1,
        column_added: dict[str, int] | None = None,
    ) -> None:
        """Apply an ALTER'd schema. ``column_added`` maps column name ->
        schema version that (re)introduced it; portions older than that
        version read the column as NULL, so DROP+ADD of one name cannot
        resurrect dropped bytes."""
        self.schema = schema
        for s in self.shards:
            s.schema = schema
            s.schema_version = schema_version
            s.column_added = dict(column_added or {})

    # ---------------- writes ----------------

    def insert(
        self,
        columns: dict[str, np.ndarray | list],
        validity: dict[str, np.ndarray] | None = None,
    ) -> TxResult:
        """Route rows by PK hash, write every shard, commit at one step."""
        enc = self.shards[0].encode_strings(columns)
        if self.pre_commit is not None:
            self.pre_commit()
        n = len(next(iter(enc.values())))
        route = _fnv_route(
            np.asarray(enc[self.pk_column], dtype=np.int64),
            len(self.shards),
        )
        participants, prepare_args = [], []
        for i, shard in enumerate(self.shards):
            mask = route == i
            if not mask.any():
                continue
            cols_i = {k: np.asarray(v)[mask] for k, v in enc.items()}
            val_i = (
                {k: np.asarray(v)[mask] for k, v in validity.items()}
                if validity else None
            )
            wid = shard.write(cols_i, val_i)
            participants.append(shard)
            prepare_args.append([wid])
        return self.coordinator.commit(participants, prepare_args)

    # ---------------- reads ----------------

    def scan(
        self,
        program: Program,
        snap: int | None = None,
        key_spaces: dict[str, int] | None = None,
        block_rows: int = 1 << 20,
    ) -> OracleTable:
        """Fan out per shard, merge partials (the DQ scan fan-out shape)."""
        snap = self.coordinator.read_snapshot() if snap is None else snap
        from ydb_tpu.engine.reader import PortionStreamSource
        from ydb_tpu.engine.scan import required_columns

        cols = required_columns(program, self.schema)
        sources = [
            PortionStreamSource(s, s.visible_portions(snap), columns=cols)
            for s in self.shards
        ]
        ex = ScanExecutor(program, sources[0], block_rows, key_spaces)
        partials = []
        for src in sources:
            if src.num_rows == 0:
                continue
            for b in src.blocks(block_rows, ex.read_cols):
                partials.append(ex.run_block(b))
        if not partials:
            # all shards empty at this snapshot: one empty padded block
            # through the already-compiled executor
            return ex.execute()
        if ex.final is None:
            return OracleTable.from_block(concat_blocks(partials))
        return OracleTable.from_block(ex.finalize(partials))

    # ---------------- background ----------------

    def run_background(self, ttl_cutoff: int | None = None,
                       conveyor=None) -> dict | list:
        """One background maintenance pass over all shards.

        Without a conveyor the pass runs inline (tests, small tables).
        With one, per-shard compaction/TTL jobs submit to the worker pool
        under broker quotas and run OFF the commit path — foreground
        scans/commits proceed concurrently (the conveyor/resource-broker
        plane, tx/conveyor/service/service.h:73; VERDICT r4 item 8);
        returns the task handles."""
        if conveyor is not None:
            handles = [
                conveyor.submit("compaction", s.maybe_compact)
                for s in self.shards
            ]
            if ttl_cutoff is not None:
                handles += [
                    conveyor.submit("ttl", s.evict_ttl, ttl_cutoff)
                    for s in self.shards
                ]
            return handles
        stats = {"compacted": 0, "evicted": 0}
        for s in self.shards:
            if s.maybe_compact():
                stats["compacted"] += 1
            if ttl_cutoff is not None:
                stats["evicted"] += s.evict_ttl(ttl_cutoff)
        return stats
