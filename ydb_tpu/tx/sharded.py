"""Sharded tables: partition parallelism over ColumnShards with
coordinated commits and consistent cross-shard snapshots.

Reference shape (SURVEY.md §2.11 row 1): a table splits into tablets by PK
range (row) or hash sharding function (OLAP, tx/sharding/); writes route
by the sharding function, distributed commits ride coordinator plan steps,
and scans fan out per shard and merge. Here:

  * ``insert`` routes rows by hash(pk) % n_shards, writes each shard's
    slice, and commits everything at ONE coordinator plan step — readers
    at any step see all-or-nothing across shards
  * ``scan`` runs the partial program per shard (one compiled executable
    shared across shards — same schema, same block capacity) and merges
    partials with the final program, exactly the MeshScan dataflow with
    host-side shards standing in for mesh devices
  * dictionaries are table-level, shared by all shards, so ids agree in
    cross-shard merges
"""

from __future__ import annotations

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import concat_blocks
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa.program import Program
from ydb_tpu.tx.coordinator import Coordinator, TxResult


def _fnv_route(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Deterministic row -> shard routing (tx/sharding hash analog)."""
    h = keys.astype(np.uint64)
    h ^= h >> 33
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> 33
    return (h % np.uint64(n_shards)).astype(np.int64)


class ShardedTable:
    def __init__(
        self,
        name: str,
        schema: dtypes.Schema,
        store: BlobStore,
        coordinator: Coordinator,
        n_shards: int = 4,
        pk_column: str | None = None,
        ttl_column: str | None = None,
        config: ShardConfig | None = None,
        dicts: DictionarySet | None = None,
        boot: bool = False,
        upsert: bool = False,
        gen: int = 0,
    ):
        self.name = name
        self.schema = schema
        self.store = store
        self.coordinator = coordinator
        self.pk_column = pk_column or schema.names[0]
        self.ttl_column = ttl_column
        self.config = config
        # upsert: PK rewrite shadows the old row. Rows route by PK hash,
        # so one key always lands on one shard and per-shard newest-wins
        # dedup (engine.reader) is globally correct.
        self.upsert = upsert
        # shard generation: RESHARD builds generation g+1 under
        # <name>/g<g+1>/<i> and cuts over atomically (scheme descriptor)
        self.gen = gen
        self.dicts = dicts if dicts is not None else DictionarySet()
        ids = [self._shard_id(gen, i) for i in range(n_shards)]
        if boot:
            # reboot from the blob store (snapshot + WAL per shard); the
            # shared dict set must already be recovered by the caller
            self.shards = [
                ColumnShard.boot(
                    sid, schema, store,
                    pk_column=self.pk_column, ttl_column=ttl_column,
                    config=config, dicts=self.dicts,
                )
                for sid in ids
            ]
            for s in self.shards:
                s.upsert = upsert
        else:
            self.shards = [
                ColumnShard(
                    sid, schema, store,
                    pk_column=self.pk_column, ttl_column=ttl_column,
                    config=config, dicts=self.dicts, upsert=upsert,
                )
                for sid in ids
            ]
        for s in self.shards:
            s.snap_source = coordinator.background_plan
        # called after string encode but before any shard write: the
        # cluster journals dictionary growth here so no durable shard
        # state ever references a dict id that is not itself durable
        self.pre_commit = None

    def _shard_id(self, gen: int, i: int) -> str:
        return (f"{self.name}/g{gen}/{i}" if gen else f"{self.name}/{i}")

    def storage_prefixes(self) -> list[str]:
        """Blob-store prefixes owning this table's durable state (DROP
        TABLE deletes them so a same-name CREATE starts empty)."""
        return [f"{s.shard_id}/" for s in self.shards]

    # ---------------- split / merge (resharding) ----------------

    def reshard(self, n_new: int, batch_rows: int = 1 << 18) -> int:
        """SPLIT/MERGE: rebuild the table as generation gen+1 with
        ``n_new`` shards — stream every row (at one snapshot, deduped)
        out of the old shards and hash-route it into the new ones, then
        swap. Returns the new generation; the CALLER must durably record
        (n_new, gen) in the scheme (Cluster.reshard_table does) — until
        then a reboot sees the old generation, and the new one's blobs
        are swept as orphans. The datashard split/merge analog
        (schemeshard__operation_split_merge.cpp) collapsed to an offline
        copy: hash sharding moves most keys on a count change, so a
        range-style incremental split does not apply."""
        from ydb_tpu.engine.reader import PortionStreamSource

        if n_new < 1:
            raise ValueError("reshard needs n_new >= 1")
        new_gen = self.gen + 1
        old_shards = self.shards
        snap = self.coordinator.read_snapshot()
        new_shards = [
            ColumnShard(
                self._shard_id(new_gen, i), self.schema, self.store,
                pk_column=self.pk_column, ttl_column=self.ttl_column,
                config=self.config, dicts=self.dicts, upsert=self.upsert,
            )
            for i in range(n_new)
        ]
        for s in new_shards:
            s.schema_version = old_shards[0].schema_version
            s.column_added = dict(old_shards[0].column_added)
        names = self.schema.names
        for old in old_shards:
            src = PortionStreamSource(old, old.visible_portions(snap))
            from ydb_tpu.engine.reader import plan_clusters, rechunk

            payloads = src.payload_stream(
                plan_clusters(src.metas, src.dedup), names)
            for cols, valid in rechunk(payloads, names, batch_rows):
                route = _fnv_route(
                    np.asarray(cols[self.pk_column], dtype=np.int64),
                    n_new)
                for i in range(n_new):
                    mask = route == i
                    if not mask.any():
                        continue
                    wid = new_shards[i].write(
                        {k: v[mask] for k, v in cols.items()},
                        {k: v[mask] for k, v in valid.items()},
                    )
                    # commit at a coordinator background step: local
                    # snaps could run AHEAD of the plan clock, making
                    # copied rows invisible at the read barrier
                    new_shards[i].commit_at(
                        [wid], self.coordinator.background_plan())
        # cutover: swap in-memory; scheme records the new generation
        self.shards = new_shards
        self.gen = new_gen
        for s in new_shards:
            s.snap_source = self.coordinator.background_plan
        return new_gen

    def drop_generation_storage(self, gen: int, n_shards: int) -> None:
        """Delete a superseded generation's blobs (post-cutover GC)."""
        for i in range(n_shards):
            prefix = f"{self._shard_id(gen, i)}/"
            for bid in self.store.list(prefix):
                self.store.delete(bid)

    def sweep_stale_generations(self) -> int:
        """Boot-time sweep: delete blobs of any generation other than
        the current one (a crash mid-reshard leaves either the unborn
        new generation or the superseded old one as orphans)."""
        keep = tuple(f"{s.shard_id}/" for s in self.shards)
        swept = 0
        for bid in self.store.list(f"{self.name}/"):
            if not bid.startswith(keep):
                self.store.delete(bid)
                swept += 1
        return swept

    def alter_schema(
        self,
        schema: dtypes.Schema,
        schema_version: int = 1,
        column_added: dict[str, int] | None = None,
    ) -> None:
        """Apply an ALTER'd schema. ``column_added`` maps column name ->
        schema version that (re)introduced it; portions older than that
        version read the column as NULL, so DROP+ADD of one name cannot
        resurrect dropped bytes."""
        self.schema = schema
        for s in self.shards:
            s.schema = schema
            s.schema_version = schema_version
            s.column_added = dict(column_added or {})

    # ---------------- writes ----------------

    def insert(
        self,
        columns: dict[str, np.ndarray | list],
        validity: dict[str, np.ndarray] | None = None,
    ) -> TxResult:
        """Route rows by PK hash, write every shard, commit at one step."""
        enc = self.shards[0].encode_strings(columns)
        if self.pre_commit is not None:
            self.pre_commit()
        n = len(next(iter(enc.values())))
        route = _fnv_route(
            np.asarray(enc[self.pk_column], dtype=np.int64),
            len(self.shards),
        )
        participants, prepare_args = [], []
        for i, shard in enumerate(self.shards):
            mask = route == i
            if not mask.any():
                continue
            cols_i = {k: np.asarray(v)[mask] for k, v in enc.items()}
            val_i = (
                {k: np.asarray(v)[mask] for k, v in validity.items()}
                if validity else None
            )
            wid = shard.write(cols_i, val_i)
            participants.append(shard)
            prepare_args.append([wid])
        return self.coordinator.commit(participants, prepare_args)

    # ---------------- reads ----------------

    def scan(
        self,
        program: Program,
        snap: int | None = None,
        key_spaces: dict[str, int] | None = None,
        block_rows: int = 1 << 20,
    ) -> OracleTable:
        """Fan out per shard, merge partials (the DQ scan fan-out shape)."""
        snap = self.coordinator.read_snapshot() if snap is None else snap
        from ydb_tpu.engine.reader import PortionStreamSource
        from ydb_tpu.engine.scan import required_columns

        cols = required_columns(program, self.schema)
        sources = [
            PortionStreamSource(s, s.visible_portions(snap), columns=cols)
            for s in self.shards
        ]
        ex = ScanExecutor(program, sources[0], block_rows, key_spaces)
        partials = []
        for src in sources:
            if src.num_rows == 0:
                continue
            for b in src.blocks(block_rows, ex.read_cols):
                partials.append(ex.run_block(b))
        if not partials:
            # all shards empty at this snapshot: one empty padded block
            # through the already-compiled executor
            return ex.execute()
        if ex.final is None:
            return OracleTable.from_block(concat_blocks(partials))
        return OracleTable.from_block(ex.finalize(partials))

    # ---------------- background ----------------

    def run_background(self, ttl_cutoff: int | None = None,
                       conveyor=None) -> dict | list:
        """One background maintenance pass over all shards.

        Without a conveyor the pass runs inline (tests, small tables).
        With one, per-shard compaction/TTL jobs submit to the worker pool
        under broker quotas and run OFF the commit path — foreground
        scans/commits proceed concurrently (the conveyor/resource-broker
        plane, tx/conveyor/service/service.h:73; VERDICT r4 item 8);
        returns the task handles."""
        if conveyor is not None:
            handles = [
                conveyor.submit("compaction", s.maybe_compact)
                for s in self.shards
            ]
            if ttl_cutoff is not None:
                handles += [
                    conveyor.submit("ttl", s.evict_ttl, ttl_cutoff)
                    for s in self.shards
                ]
            return handles
        stats = {"compacted": 0, "evicted": 0}
        for s in self.shards:
            if s.maybe_compact():
                stats["compacted"] += 1
            if ttl_cutoff is not None:
                stats["evicted"] += s.evict_ttl(ttl_cutoff)
        return stats
