"""Mediator: plan-step fan-out to per-node time caches.

Mirror of the reference's mediator + time-cast pair (SURVEY §2.5
mediator row; ydb/core/tx/mediator, time_cast.cpp): the coordinator
plans steps, the MEDIATOR fans completed steps out to subscribers, and
each node keeps a local TIME CACHE so readers learn the current
consistent snapshot without a coordinator round trip. Cross-process,
the subscription rides the interconnect (a callback that sends a step
message); in-process it is a direct call.
"""

from __future__ import annotations

import threading


class NodeTimeCache:
    """Per-node view of mediator time (TMediatorTimecastEntry analog):
    ``read_snapshot`` is a local read; ``wait_for`` blocks until the
    barrier passes a step (the 'wait until my tx is visible' path)."""

    def __init__(self):
        self._step = 0
        self._cv = threading.Condition()

    def advance(self, step: int) -> None:
        with self._cv:
            if step > self._step:
                self._step = step
                self._cv.notify_all()

    def read_snapshot(self) -> int:
        with self._cv:
            return self._step

    def wait_for(self, step: int, timeout: float = 10.0) -> int:
        with self._cv:
            if not self._cv.wait_for(lambda: self._step >= step,
                                     timeout=timeout):
                raise TimeoutError(
                    f"mediator time stuck below step {step}")
            return self._step


class Mediator:
    """Fans coordinator barrier advances to registered time caches."""

    def __init__(self, coordinator):
        self.coordinator = coordinator
        self._caches: list[NodeTimeCache] = []
        coordinator.subscribe_completed(self._fan_out)

    def register(self) -> NodeTimeCache:
        cache = NodeTimeCache()
        # append FIRST, then seed: a barrier advance in between reaches
        # the cache via fan-out, and advance() is monotonic either way —
        # the reverse order could strand a late joiner one step behind
        self._caches.append(cache)
        cache.advance(self.coordinator.read_snapshot())
        return cache

    def _fan_out(self, step: int) -> None:
        for cache in self._caches:
            cache.advance(step)
