"""Async table replication over CDC changefeeds.

The reference's async replication service tails a source table's
changefeed and applies the change stream to a target table, tracking
progress durably so a restarted worker resumes where it left off
(ydb/core/tx/replication/; SURVEY §2.14 async-replication row).

TPU-era shape: the changefeed already lands in a PersQueue topic
(datashard change exchange -> topic, exactly-once via producer seqnos).
The ``Replicator`` is a topic consumer per partition:

    read batch -> apply upsert/erase to the target row table
               -> commit the consumer offset

Apply is idempotent (upsert-by-key / delete-by-key), so the
at-least-once redelivery window between apply and offset commit is
harmless — the same guarantee the reference's replication worker gives.
The target stays a consistent-prefix replica: changes apply in source
commit order per key (per-shard queues are ordered; one key always maps
to one shard and one topic partition).
"""

from __future__ import annotations

import json


class Replicator:
    """Tails one changefeed topic into a target RowTable."""

    def __init__(self, topic, target, consumer: str = "replicator",
                 batch: int = 256):
        self.topic = topic
        self.target = target
        self.consumer = consumer
        self.batch = batch

    def poll(self) -> int:
        """One replication pass: apply every new change. Returns the
        number of changes applied."""
        applied = 0
        for pid, part in enumerate(self.topic.partitions):
            while True:
                offset = part.committed(self.consumer)
                msgs = part.read(offset, limit=self.batch)
                if not msgs:
                    break
                # apply in order: a delete after an upsert of the same
                # key must win, so apply in message order, batched by
                # consecutive runs of the same kind
                self._apply_in_order(msgs)
                applied += len(msgs)
                part.commit(self.consumer, offset + len(msgs))
        return applied

    def _apply_in_order(self, msgs) -> None:
        run_kind = None
        run: list = []

        def flush():
            nonlocal run
            if not run:
                return
            if run_kind == "del":
                self.target.delete_keys(run)
            else:
                self.target.upsert_rows(run)
            run = []

        for m in msgs:
            ch = json.loads(m["data"])
            kind = "del" if ch["new"] is None else "up"
            if kind != run_kind:
                flush()
                run_kind = kind
            if kind == "del":
                run.append(tuple(ch["key"]))
            else:
                run.append(dict(ch["new"]))
        flush()


def replicate_once(source_table, topic, target_table,
                   consumer: str = "replicator") -> int:
    """Drain the source's pending changes into the topic, then apply
    them to the target (one synchronous replication cycle)."""
    source_table.drain_changes_to(topic)
    return Replicator(topic, target_table, consumer).poll()
