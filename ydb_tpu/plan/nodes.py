"""Logical query plan: the multi-table dataflow above SSA programs.

The reference splits a query into stages connected by channels
(dq_tasks.proto:190); each stage hosts a MiniKQL program, and joins are
stage operators (GraceJoin/MapJoin). Here the plan is a small node tree:
table scans carry pushed-down SSA programs (the kqp_olap pushdown shape,
kqp_opt_phy_olap_filter.cpp), joins pick the N:1 lookup or N:M expand
kernel, and Transform nodes run post-join SSA (aggregation/sort/having).
The executor (plan/executor.py) walks it bottom-up; the distributed
executor maps the same tree onto the mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Union

from ydb_tpu.ssa.program import Program


@dataclasses.dataclass(frozen=True)
class TableScan:
    table: str
    program: Program | None = None  # pushed-down filter/project/partial-agg
    columns: tuple[str, ...] | None = None  # projection when no program


@dataclasses.dataclass(frozen=True)
class LookupJoin:
    """N:1 equi-join (build keys unique): every TPC-H FK->PK join."""

    probe: "PlanNode"
    build: "PlanNode"
    probe_keys: tuple[str, ...]
    build_keys: tuple[str, ...]
    payload: tuple[str, ...] = ()  # build columns carried to output
    kind: str = "inner"  # inner | left | semi | anti
    suffix: str = ""


@dataclasses.dataclass(frozen=True)
class ExpandJoin:
    """N:M equi-join via static-capacity expansion (inner | left)."""

    probe: "PlanNode"
    build: "PlanNode"
    probe_keys: tuple[str, ...]
    build_keys: tuple[str, ...]
    probe_payload: tuple[str, ...]
    build_payload: tuple[str, ...]
    fanout_hint: float = 4.0
    build_suffix: str = ""
    kind: str = "inner"


@dataclasses.dataclass(frozen=True)
class Transform:
    input: "PlanNode"
    program: Program
    # (renamed_column -> source column) pairs: string columns renamed by
    # join suffixing / derived-table aliasing still resolve their
    # dictionaries at compile time
    dict_aliases: tuple[tuple[str, str], ...] = ()


PlanNode = Union[TableScan, LookupJoin, ExpandJoin, Transform]
