"""Logical query plan: the multi-table dataflow above SSA programs.

The reference splits a query into stages connected by channels
(dq_tasks.proto:190); each stage hosts a MiniKQL program, and joins are
stage operators (GraceJoin/MapJoin). Here the plan is a small node tree:
table scans carry pushed-down SSA programs (the kqp_olap pushdown shape,
kqp_opt_phy_olap_filter.cpp), joins pick the N:1 lookup or N:M expand
kernel, and Transform nodes run post-join SSA (aggregation/sort/having).
The executor (plan/executor.py) walks it bottom-up; the distributed
executor maps the same tree onto the mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Union

from ydb_tpu.ssa.program import Program


@dataclasses.dataclass(frozen=True)
class TableScan:
    table: str
    program: Program | None = None  # pushed-down filter/project/partial-agg
    columns: tuple[str, ...] | None = None  # projection when no program


@dataclasses.dataclass(frozen=True)
class LookupJoin:
    """N:1 equi-join (build keys unique): every TPC-H FK->PK join."""

    probe: "PlanNode"
    build: "PlanNode"
    probe_keys: tuple[str, ...]
    build_keys: tuple[str, ...]
    payload: tuple[str, ...] = ()  # build columns carried to output
    kind: str = "inner"  # inner | left | semi | anti
    suffix: str = ""


@dataclasses.dataclass(frozen=True)
class ExpandJoin:
    """N:M equi-join via static-capacity expansion (inner | left)."""

    probe: "PlanNode"
    build: "PlanNode"
    probe_keys: tuple[str, ...]
    build_keys: tuple[str, ...]
    probe_payload: tuple[str, ...]
    build_payload: tuple[str, ...]
    fanout_hint: float = 4.0
    build_suffix: str = ""
    kind: str = "inner"


@dataclasses.dataclass(frozen=True)
class Transform:
    input: "PlanNode"
    program: Program
    # (renamed_column -> source column) pairs: string columns renamed by
    # join suffixing / derived-table aliasing still resolve their
    # dictionaries at compile time
    dict_aliases: tuple[tuple[str, str], ...] = ()


@dataclasses.dataclass(frozen=True)
class Concat:
    """UNION ALL: inputs produce identical column sets; rows append.

    The reference's Extend/UnionAll expression node
    (yql/essentials/core/type_ann/type_ann_list.cpp); here each input
    executes independently and the blocks concatenate."""

    inputs: tuple["PlanNode", ...]


PlanNode = Union[TableScan, LookupJoin, ExpandJoin, Transform, Concat]


def format_plan(plan: PlanNode, indent: int = 0) -> str:
    """Human-readable physical plan (EXPLAIN output; the reference
    renders its plans via kqp query plan JSON — this is the compact
    text form)."""
    pad = "  " * indent

    def prog_summary(program) -> str:
        if program is None:
            return ""
        from ydb_tpu.ssa.program import (
            AssignStep, FilterStep, GroupByStep, ProjectStep, SortStep,
        )

        bits = []
        n_filters = sum(
            1 for s in program.steps if isinstance(s, FilterStep))
        n_assigns = sum(
            1 for s in program.steps if isinstance(s, AssignStep))
        if n_filters:
            bits.append(f"filters={n_filters}")
        if n_assigns:
            bits.append(f"assigns={n_assigns}")
        for s in program.steps:
            if isinstance(s, GroupByStep):
                bits.append(
                    f"group_by[keys={list(s.keys)}, "
                    f"aggs={len(s.aggs)}]")
            elif isinstance(s, SortStep) and (s.keys or s.limit):
                lim = f", limit={s.limit}" if s.limit is not None else ""
                bits.append(f"sort[{list(s.keys)}{lim}]")
            elif isinstance(s, ProjectStep):
                bits.append(f"project={list(s.names)}")
        return ", ".join(bits)

    if isinstance(plan, TableScan):
        return (f"{pad}TableScan {plan.table}"
                + (f" ({prog_summary(plan.program)})"
                   if plan.program is not None else ""))
    if isinstance(plan, LookupJoin):
        head = (f"{pad}LookupJoin[{plan.kind}] "
                f"{list(plan.probe_keys)} = {list(plan.build_keys)}"
                + (f" payload={list(plan.payload)}" if plan.payload
                   else ""))
        return "\n".join([
            head,
            format_plan(plan.probe, indent + 1),
            format_plan(plan.build, indent + 1),
        ])
    if isinstance(plan, ExpandJoin):
        head = (f"{pad}ExpandJoin[{plan.kind}] "
                f"{list(plan.probe_keys)} = {list(plan.build_keys)}")
        return "\n".join([
            head,
            format_plan(plan.probe, indent + 1),
            format_plan(plan.build, indent + 1),
        ])
    if isinstance(plan, Transform):
        return "\n".join([
            f"{pad}Transform ({prog_summary(plan.program)})",
            format_plan(plan.input, indent + 1),
        ])
    if isinstance(plan, Concat):
        return "\n".join(
            [f"{pad}Concat[{len(plan.inputs)}]"]
            + [format_plan(i, indent + 1) for i in plan.inputs])
    return f"{pad}{plan!r}"
