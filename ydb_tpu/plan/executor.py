"""Plan executor: DQ stage graph for join-bearing plans, single-chip
walk for single-stage plans.

The host-side analog of the KQP executer (kqp_executer_impl.h:120):
every plan containing a join lowers to the DQ task graph — scan stages
feeding hash-partitioned channels into grace-bucket join stages and a
final aggregate — executed by credit-flow compute actors
(kqp/dq_lower.py + dq/compute.py), exactly as the reference routes every
query through executer → tasks → compute actors (kqp_tasks_graph.cpp:448).
Single-stage plans (scan → transform, no join) keep the direct
streaming walk below — the one-task collapse of the same graph: scans
stream blocks through compiled SSA (ydb_tpu.engine.scan), transforms
compile against the inferred intermediate schema. The recursive walk
also remains the fallback for plan shapes that do not lower (a
CTE-shared subtree feeding two consumers).
"""

from __future__ import annotations

import dataclasses
import os

import jax
import numpy as np

from ydb_tpu import chaos, dtypes
from ydb_tpu.analysis import host_ok
from ydb_tpu.analysis.verify import check_program
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.blocks.block import TableBlock, concat_blocks, device_aux
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
from ydb_tpu.obs import tracing
from ydb_tpu.obs.probes import probe as _probe
from ydb_tpu.ssa import join as join_kernels
from ydb_tpu.ssa import kernels
from ydb_tpu.ssa.compiler import compile_program
from ydb_tpu.plan.nodes import (
    Concat,
    ExpandJoin,
    LookupJoin,
    PlanNode,
    TableScan,
    Transform,
)

# the SQL scan path fires the SAME probe points the direct
# ColumnShard.scan fires (shard=-1 marks the statement-level aggregate
# over all shards), so EXPLAIN ANALYZE actuals and probe sessions see
# one consistent accounting
_P_SCAN_STAGES = _probe("columnshard.scan.stages")
_P_SCAN_PRUNING = _probe("columnshard.scan.pruning")


@dataclasses.dataclass
class Database:
    """Named host tables + shared dictionaries (one 'shard' worth).

    ``_compile_cache`` memoizes compiled Transform programs per
    (program, schema) — the XLA-era computation-pattern cache
    (mkql_computation_pattern_cache.h). Ingest that extends dictionaries
    must call ``invalidate_compile_cache()`` (plan-time dictionary tables
    bake into the cached aux)."""

    sources: dict[str, ColumnSource]
    dicts: DictionarySet | None = None
    key_spaces: dict[str, int] | None = None
    _compile_cache: dict = dataclasses.field(default_factory=dict)
    # when set (Cluster.enable_mesh), eligible plans execute SPMD over
    # the device mesh (parallel/mesh_exec.py) instead of DQ/recursive
    mesh_executor: object = None
    # cluster-owned DeviceBlockCache: table scans over portion-backed
    # sources reuse HBM-resident decoded blocks across statements (the
    # SQL path's share of the shared-page-cache analog). Databases are
    # per-statement; the cache outlives them.
    block_cache: object = None
    # aggregator table statistics (stats.cost.TableStats by table name):
    # feeds DQ join sizing estimates; advisory only
    table_stats: dict | None = None

    def invalidate_compile_cache(self):
        self._compile_cache.clear()


def _materialize(source: ColumnSource, columns) -> TableBlock:
    names = columns if columns is not None else source.schema.names
    blocks = list(source.blocks(block_rows=1 << 40, columns=names))
    return blocks[0] if len(blocks) == 1 else concat_blocks(blocks)


def _pruned_source(src, program, db: Database):
    """Predicate-pruned view of a scan source, when statistics are on
    and the source supports it (MultiShardStreamSource.with_predicates).
    Falls through to the original source otherwise — host-resident
    ColumnSources have no chunk plane to prune."""
    from ydb_tpu import stats as stats_mod

    with_preds = getattr(src, "with_predicates", None)
    if with_preds is None or not stats_mod.stats_enabled():
        return src
    from ydb_tpu.stats.zonemap import extract_predicates

    preds, _full = extract_predicates(program, src.schema, db.dicts)
    if not preds:
        return src
    return with_preds(preds)


# DQ is the default executor for join-bearing plans (VERDICT r4 item 2);
# YDB_TPU_DQ=0 restores the recursive walk for A/B debugging
_DQ_ON = os.environ.get("YDB_TPU_DQ", "1") not in ("0", "", "off")
_DQ_TASKS = int(os.environ.get("YDB_TPU_DQ_TASKS", "2"))
_DQ_BLOCK_ROWS = int(os.environ.get("YDB_TPU_DQ_BLOCK_ROWS",
                                    str(1 << 20)))


def _plan_nodes(plan: PlanNode):
    stack = [plan]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (LookupJoin, ExpandJoin)):
            stack += [n.probe, n.build]
        elif isinstance(n, Transform):
            stack.append(n.input)
        elif isinstance(n, Concat):
            stack += list(n.inputs)


def _partition_for_dq(src) -> list:
    """A table's scan partitions for DQ task feeding: per-shard portion
    streams for sharded tables (their natural partitioning), round-robin
    row slices for host-resident sources."""
    subs = getattr(src, "subs", None)
    if subs:
        return list(subs)
    if isinstance(src, ColumnSource) and src.num_rows > 0:
        from ydb_tpu.kqp.dq_lower import partition_source

        return partition_source(src, _DQ_TASKS)
    return [src]


def _execute_plan_mesh(plan: PlanNode, db: Database):
    """SPMD mesh execution for eligible plans (scan+agg and join trees
    whose tables the mesh database carries). Returns the host-resident
    OracleTable (to_host passes it through — no device round-trip for a
    result already gathered), or None when the shape doesn't map
    (non-root aggregating Transform, missing table) so the caller falls
    through to DQ/recursive. Real execution defects (shape errors etc.)
    propagate — only the explicit doesn't-lower signal falls back."""
    mex = db.mesh_executor
    for node in _plan_nodes(plan):
        if isinstance(node, TableScan) and \
                node.table not in mex.db.sources:
            return None
    # sharded whole-plan fusion first (parallel/mesh_fuse): one jitted
    # donated-buffer dispatch over the mesh; the per-node walk remains
    # the fallback for shapes that don't mesh-fuse
    try:
        fused = getattr(mex, "execute_fused", None)
        if fused is not None:
            out = fused(plan)
            if out is not None:
                return out
        return mex.execute(plan)
    except NotImplementedError:
        return None
    except chaos.DeviceLostError:
        # graceful degradation: a lost device fails THIS dispatch, not
        # the statement — single-chip fused execution (then the walk)
        # picks the plan up, bit-identical
        chaos.note_fallback("mesh.dispatch")
        tracing.annotate(mesh_fallback=1)
        return None


def _execute_plan_dq(plan: PlanNode, db: Database) -> TableBlock | None:
    """Lower to DQ stages and run on an in-process actor system. Returns
    None when the plan does not lower (the caller falls back to the
    recursive walk)."""
    from ydb_tpu.dq.compute import build_stage_graph
    from ydb_tpu.kqp.dq_lower import plan_to_stages
    from ydb_tpu.runtime.actors import ActorSystem

    seen: set[int] = set()
    parts: dict[str, list] = {}
    for node in _plan_nodes(plan):
        if id(node) in seen:
            # a shared subtree (CTE referenced twice) would re-lower —
            # and re-execute — once per consumer; the recursive walk's
            # _memo executes it once, so fall back
            return None
        seen.add(id(node))
        if isinstance(node, TableScan) and node.table not in parts:
            # dict.get never triggers lazy sys-view materialization
            src = db.sources.get(node.table)
            if src is None:
                return None
            parts[node.table] = _partition_for_dq(src)
    estimator = None
    if db.table_stats:
        from ydb_tpu import stats as stats_mod
        from ydb_tpu.stats import cost

        if stats_mod.stats_enabled():
            table_stats = db.table_stats
            # real schemas type predicate literals (decimal scaling)
            schemas = {
                name: db.sources[name].schema for name in parts
                if hasattr(db.sources.get(name), "schema")
            }

            def estimator(node):
                return cost.estimate_plan_rows(node, table_stats,
                                               schemas)
    rt = ActorSystem(node=1)
    try:
        stages = plan_to_stages(plan, n_tasks=_DQ_TASKS,
                                estimator=estimator)
        handle = build_stage_graph(
            stages, parts, rt, db.dicts, db.key_spaces,
            block_rows=_DQ_BLOCK_ROWS, compile_cache=db._compile_cache)
    except (ValueError, NotImplementedError):
        # plan shapes that do not lower (e.g. a join-rooted plan with no
        # result Transform) keep working through the recursive walk
        return None
    try:
        with tracing.span("dq") as sp:
            sp.set(stages=len(stages), tasks=_DQ_TASKS)
            handle.start()
            rt.run()
        err = handle.collector.error
        if err is not None and "deadline" in err:
            # the graph aborted on statement-deadline expiry: surface
            # the typed cancellation, not a generic incompletion
            raise statement_deadline.StatementCancelled(err)
        if not handle.collector.done:
            raise RuntimeError("DQ stage graph did not complete")
        return handle.collector.result_block()
    finally:
        # a cancelled/aborted graph still holds spilled blobs for any
        # parked or accumulated block ids; drop them with the graph
        handle.close()


def execute_plan(plan: PlanNode, db: Database,
                 _memo: dict | None = None,
                 use_dq: bool | None = None) -> TableBlock:
    """Execute a logical plan: join-bearing plans route through the DQ
    stage graph (the production executer path); single-stage plans and
    non-lowerable shapes use the bottom-up walk. ``_memo`` dedupes
    shared subtrees (a CTE referenced from several places executes once
    per statement)."""
    if _memo is None:
        if db.mesh_executor is not None:
            out = _execute_plan_mesh(plan, db)
            if out is not None:
                return out
        if (use_dq if use_dq is not None else _DQ_ON) and any(
                isinstance(n, (LookupJoin, ExpandJoin))
                for n in _plan_nodes(plan)):
            out = _execute_plan_dq(plan, db)
            if out is not None:
                return out
        # whole-plan fusion (ssa.plan_fuse): replace the per-node memo
        # walk with ONE jitted dispatch when the whole tree is fusible.
        # A bare TableScan is already a single fragment — _scan_node's
        # streaming path stays.
        from ydb_tpu.ssa import plan_fuse

        if plan_fuse.fusion_enabled() and not isinstance(plan, TableScan):
            out = _execute_plan_fused(plan, db)
            if out is not None:
                return out
        _memo = {}
    hit = _memo.get(id(plan))
    if hit is not None:
        return hit
    out = _execute_node(plan, db, _memo)
    _memo[id(plan)] = out
    return out


def _scan_node(plan: TableScan, db: Database, sp) -> TableBlock:
    from ydb_tpu.obs.probes import StageTimer

    src = db.sources[plan.table]
    key = (plan.table, plan.program)
    ex = db._compile_cache.get(key)
    fresh = ex is None
    if fresh:
        ex = ScanExecutor(
            plan.program, src, block_rows=1 << 22,
            key_spaces=db.key_spaces,
        ).detach()  # cache compiled state, not the source arrays
        db._compile_cache[key] = ex
    # stage accounting while a query trace records OR a probe session
    # listens (probe observability must not degrade when profiling is
    # off — the shard-level probes fire unconditionally too). The timer
    # itself is cheap, but attaching it threads per-chunk charging
    # through the whole staging pipeline; attached to the base source
    # for this run only — a Database reused across statements (bench)
    # shares its sources, and a stale timer would keep charging later
    # unprofiled scans — so it detaches after the stream drains.
    want_stats = (sp.recording or bool(_P_SCAN_STAGES)
                  or bool(_P_SCAN_PRUNING))
    timer = None
    base_src = src
    if want_stats:
        timer = StageTimer()
        if hasattr(base_src, "attach_timer"):
            base_src.attach_timer(timer)
    try:
        # zone-map scan pruning (stats.zonemap): the pushdown program's
        # conjunctive filters skip portions/chunks before any blob
        # read. The pruned view carries its predicate fingerprint into
        # the device cache key, so pruned streams never alias unpruned
        # ones.
        src = _pruned_source(src, plan.program, db)
        # chunk counters are cumulative on the source object; shared
        # unpruned sources accumulate across statements, so the span
        # reports this run's DELTA (pruned views are fresh per run)
        chunks0 = {k: int(getattr(src, k, 0))
                   for k in ("chunks_read", "chunks_skipped",
                             "resident_hits", "resident_rows")}
        raw_stream = src.blocks(1 << 22, ex.read_cols)
        stream = raw_stream
        bc = db.block_cache
        key_of = getattr(src, "device_cache_key", None)
        # the resident tier subsumes the whole-stream device cache (see
        # ColumnShard scan: double-caching holds the bytes twice)
        res_on = any(
            getattr(s.shard, "resident", None) is not None
            and s.shard.resident.enabled()
            for s in getattr(src, "subs", ()))
        if bc is not None and key_of is not None and bc.budget() > 0 \
                and not res_on:
            # bind the RAW source stream, not `stream` itself: the
            # single-flight cache calls make_blocks lazily (on first
            # next()), after `stream` has been rebound to the cache
            # generator — a late-bound `stream` would hand the
            # generator back to itself
            stream = bc.stream(
                key_of(ex.read_cols, 1 << 22), lambda: raw_stream)
        out = ex.run_stream(stream, timer=timer)
    finally:
        if timer is not None and hasattr(base_src, "attach_timer"):
            base_src.attach_timer(None)
    if want_stats:
        stages = timer.snapshot()
        pruning = {k: int(getattr(src, k, 0)) - v0
                   for k, v0 in chunks0.items()}
        # resident-hit attribution: EXPLAIN ANALYZE shows how much of
        # the scan the HBM tier served without touching host bytes
        pruning["resident_portions"] = pruning.pop("resident_hits")
        pruning["portions_skipped"] = int(
            getattr(src, "portions_skipped", 0))
        pruning["portions_total"] = pruning["portions_skipped"] + sum(
            len(s.metas) for s in getattr(src, "subs", ()))
        if sp.recording:
            sp.set(table=plan.table, rows=int(out.length),
                   compile_cache=("miss" if fresh else "hit"),
                   **{f"stage_{k}": v for k, v in stages.items()},
                   **pruning)
            if fresh and ex.first_trace_seconds:
                sp.set(first_trace_seconds=round(
                    ex.first_trace_seconds, 6))
        if _P_SCAN_STAGES:
            _P_SCAN_STAGES.fire(shard=-1, **stages)
        if _P_SCAN_PRUNING:
            _P_SCAN_PRUNING.fire(shard=-1, **pruning)
    return out


@host_ok("scan staging boundary: host source arrays cross to the"
         " device here by design (block cache / resident tier absorb"
         " repeat crossings; donate-safety copies are part of it)")
def _stage_fused_site(site, db: Database, timer, donate: bool):
    """Stage one fused scan site to its shape-class capacity.

    Mirrors _scan_node's staging side exactly — pruned view, chunk-delta
    pruning accounting, block cache / resident tier routing, StageTimer
    attachment — but ends at a single padded device block instead of a
    streamed program run (the program runs inside the fused trace).
    Returns (block, pruning dict). The staged block's buffers are always
    fresh (from_numpy copies / a jitted merge), so the fused dispatch
    may donate them."""
    import contextlib

    from ydb_tpu.ssa import plan_fuse

    src = db.sources[site.table]
    base_src = src
    if timer is not None and hasattr(base_src, "attach_timer"):
        base_src.attach_timer(timer)
    try:
        if site.node.program is not None:
            src = _pruned_source(src, site.node.program, db)
        chunks0 = {k: int(getattr(src, k, 0))
                   for k in ("chunks_read", "chunks_skipped",
                             "resident_hits", "resident_rows")}
        staging = (timer.stage("stage") if timer is not None
                   else contextlib.nullcontext())
        if isinstance(src, ColumnSource):
            n = src.num_rows
            arrays = {m: src.columns[m] for m in site.read_cols}
            validity = None
            if src.validity:
                validity = {m: src.validity[m]
                            for m in site.read_cols
                            if m in src.validity}
            if donate and site.capacity == n:
                # exact-fit capacity: from_numpy pads nothing, and
                # jnp.asarray may alias an aligned host array on CPU —
                # donating the alias would let XLA scribble over the
                # source table. Copy this (power-of-two row count) case;
                # every other path stages through fresh buffers already.
                arrays = {k: np.array(v) for k, v in arrays.items()}
                if validity:
                    validity = {k: np.array(v)
                                for k, v in validity.items()}
            with staging:
                blk = TableBlock.from_numpy(
                    arrays, site.in_schema, validity,
                    capacity=site.capacity)
        else:
            raw_stream = src.blocks(1 << 22, site.read_cols)
            stream = raw_stream
            bc = db.block_cache
            key_of = getattr(src, "device_cache_key", None)
            res_on = any(
                getattr(s.shard, "resident", None) is not None
                and s.shard.resident.enabled()
                for s in getattr(src, "subs", ()))
            if bc is not None and key_of is not None \
                    and bc.budget() > 0 and not res_on:
                stream = bc.stream(
                    key_of(site.read_cols, 1 << 22), lambda: raw_stream)
            blocks = tuple(stream)
            with staging:
                blk = plan_fuse.fit_blocks(blocks, site.capacity)
    finally:
        if timer is not None and hasattr(base_src, "attach_timer"):
            base_src.attach_timer(None)
    pruning = {k: int(getattr(src, k, 0)) - v0
               for k, v0 in chunks0.items()}
    pruning["resident_portions"] = pruning.pop("resident_hits")
    pruning["portions_skipped"] = int(
        getattr(src, "portions_skipped", 0))
    pruning["portions_total"] = pruning["portions_skipped"] + sum(
        len(s.metas) for s in getattr(src, "subs", ()))
    return blk, pruning


def _run_fused(fused, db: Database, fsp) -> TableBlock:
    """Stage every scan site, dispatch the fused computation once, and
    handle expand-join overflow retries.

    Observability mirrors the walk: each staged table gets a "scan" span
    with stage/pruning attrs firing the shard=-1 probes; the PRIMARY
    (largest) table's span stays open around the fused dispatch so
    device time lands in its "compute" stage — EXPLAIN ANALYZE actuals
    and probe sessions stay consistent whichever executor ran."""
    import contextlib

    from ydb_tpu.obs.probes import StageTimer

    want_stats = (fsp.recording or bool(_P_SCAN_STAGES)
                  or bool(_P_SCAN_PRUNING))
    sites = fused.sites
    primary = max(range(len(sites)), key=lambda i: sites[i].capacity)
    inputs: dict = {}

    def emit_obs(sp, site, timer, rows, pruning):
        stages = timer.snapshot()
        if sp.recording:
            sp.set(table=site.table, rows=rows,
                   **{f"stage_{k}": v for k, v in stages.items()},
                   **pruning)
        if _P_SCAN_STAGES:
            _P_SCAN_STAGES.fire(shard=-1, **stages)
        if _P_SCAN_PRUNING:
            _P_SCAN_PRUNING.fire(shard=-1, **pruning)

    for i, other in enumerate(sites):
        if i == primary:
            continue
        with tracing.span("scan") as sp:
            timer = StageTimer() if want_stats else None
            blk, pruning = _stage_fused_site(other, db, timer,
                                             fused.donate)
            inputs[other.key] = blk
            if want_stats:
                emit_obs(sp, other, timer, int(blk.length), pruning)

    site = sites[primary]
    with tracing.span("scan") as sp:
        timer = StageTimer() if want_stats else None
        blk, pruning = _stage_fused_site(site, db, timer, fused.donate)
        inputs[site.key] = blk
        # rows read before the dispatch: donated inputs are dead after
        rows = int(blk.length) if want_stats else 0
        while True:
            # cooperative cancellation between (uninterruptible) fused
            # dispatches: a statement past its deadline stops here
            statement_deadline.check_current("fused dispatch")
            computing = (timer.stage("compute") if timer is not None
                         else contextlib.nullcontext())
            with computing:
                out, totals = fused.run(inputs)
            over = fused.overflowed(totals)
            if not over:
                break
            # an expand join outgrew its static capacity: widen it (the
            # cached plan keeps the exact size for later statements),
            # re-stage — donation consumed the inputs — and re-dispatch
            for j in over:
                fused.grow(j, totals[j])
            inputs = {
                s.key: _stage_fused_site(s, db, None, fused.donate)[0]
                for s in sites
            }
        if want_stats:
            emit_obs(sp, site, timer, rows, pruning)
    return out


def _execute_plan_fused(plan: PlanNode, db: Database) -> TableBlock | None:
    """Whole-plan fused fast path (ssa.plan_fuse): one donated-buffer
    jitted dispatch per (plan fingerprint, shape class), cached in the
    cluster compile cache. Returns None when the plan is not fusible
    (the caller falls back to the per-node walk)."""
    from ydb_tpu.ssa import plan_fuse

    sig = plan_fuse.plan_signature_cached(plan, db)
    if sig is None or not sig.sites:
        return None
    if chaos.hit("fuse.trace") is not None:
        # injected trace failure: the fused path declines the plan and
        # the per-node walk answers, bit-identical
        chaos.note_fallback("fuse.trace")
        return None
    key = sig.cache_key(db)
    fused = db._compile_cache.get(key)
    fresh = fused is None
    with tracing.span("plan.fuse") as fsp:
        if fresh:
            try:
                fused = plan_fuse.build(sig, db)
            except plan_fuse.Unfusible:
                return None
            db._compile_cache[key] = fused
        ft0 = fused.first_trace_seconds or 0.0
        out = _run_fused(fused, db, fsp)
        if fsp.recording:
            fsp.set(fused_stages=fused.fused_stages,
                    fragments_elided=fused.fused_stages - 1,
                    compile_cache=("miss" if fresh else "hit"))
            # growth retraces on a cached plan count too: report THIS
            # run's trace time, not the lifetime accumulation
            ft = (fused.first_trace_seconds or 0.0) - ft0
            if ft:
                fsp.set(first_trace_seconds=round(ft, 6))
    return out


@host_ok("compile-cache miss path: compiles the Transform once; the"
         " (run, aux) pair is cached by (program, aliases, schema)")
def _compiled_transform(plan: Transform, schema, db: Database):
    """Compile a Transform program (jit + device aux); split out so the
    executor walk stays free of trace-time constructs."""
    cp = compile_program(
        plan.program, schema, db.dicts, db.key_spaces,
        dict_aliases=dict(plan.dict_aliases),
    )
    return jax.jit(cp.run), device_aux(cp.aux)


def _execute_node(plan: PlanNode, db: Database, _memo: dict) -> TableBlock:
    if isinstance(plan, TableScan):
        src = db.sources[plan.table]
        if plan.program is None:
            return _materialize(src, plan.columns)
        with tracing.span("scan") as sp:
            return _scan_node(plan, db, sp)
    if isinstance(plan, LookupJoin):
        probe = execute_plan(plan.probe, db, _memo)
        build = execute_plan(plan.build, db, _memo)
        return join_kernels.run_equi_join(
            probe, build, plan.probe_keys, plan.build_keys,
            kind=plan.kind, suffix=plan.suffix, payload=plan.payload,
        )
    if isinstance(plan, ExpandJoin):
        probe = execute_plan(plan.probe, db, _memo)
        build = execute_plan(plan.build, db, _memo)
        return join_kernels.run_equi_join(
            probe, build, plan.probe_keys, plan.build_keys,
            kind=plan.kind, suffix=plan.build_suffix, expand=True,
            probe_payload=plan.probe_payload,
            build_payload=plan.build_payload,
            fanout_hint=plan.fanout_hint,
        )
    if isinstance(plan, Transform):
        block = execute_plan(plan.input, db, _memo)
        key = (plan.program, plan.dict_aliases, block.schema)
        hit = db._compile_cache.get(key)
        with tracing.span("transform") as sp:
            if hit is None:
                sp.set(compile_cache="miss")
                # mandatory precondition (ydb_tpu.analysis): surface
                # step-indexed diagnostics for malformed programs
                # before any trace work; compile_program re-checks, but
                # this keeps the executor the choke point even if
                # lowering changes
                check_program(plan.program, block.schema)
                hit = _compiled_transform(plan, block.schema, db)
                db._compile_cache[key] = hit
            else:
                sp.set(compile_cache="hit")
            run, aux = hit
            return run(block, aux)
    if isinstance(plan, Concat):
        # branches execute independently (planner guarantees identical
        # column names/types); live rows append in branch order
        return concat_blocks(
            [execute_plan(i, db, _memo) for i in plan.inputs])
    raise NotImplementedError(plan)


@host_ok("lazy result fetch: the ONE deliberate device->host boundary"
         " per statement (under the session's 'fetch' span)")
def to_host(block) -> OracleTable:
    if isinstance(block, OracleTable):  # mesh results are already host
        return block
    return OracleTable.from_block(block)
