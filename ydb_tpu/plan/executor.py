"""Single-chip plan executor: walks the logical plan bottom-up.

The host-side analog of the KQP executer driving stage tasks
(kqp_executer_impl.h:120) collapsed to one device: scans stream blocks
through compiled SSA (ydb_tpu.engine.scan), joins run the device kernels
(ydb_tpu.ssa.join), transforms compile against the inferred intermediate
schema. Intermediate results materialize as single blocks — streaming
stage pipelining arrives with the DQ layer.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import TableBlock, concat_blocks
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
from ydb_tpu.ssa import join as join_kernels
from ydb_tpu.ssa import kernels
from ydb_tpu.ssa.compiler import compile_program
from ydb_tpu.plan.nodes import (
    ExpandJoin,
    LookupJoin,
    PlanNode,
    TableScan,
    Transform,
)


@dataclasses.dataclass
class Database:
    """Named host tables + shared dictionaries (one 'shard' worth).

    ``_compile_cache`` memoizes compiled Transform programs per
    (program, schema) — the XLA-era computation-pattern cache
    (mkql_computation_pattern_cache.h). Ingest that extends dictionaries
    must call ``invalidate_compile_cache()`` (plan-time dictionary tables
    bake into the cached aux)."""

    sources: dict[str, ColumnSource]
    dicts: DictionarySet | None = None
    key_spaces: dict[str, int] | None = None
    _compile_cache: dict = dataclasses.field(default_factory=dict)

    def invalidate_compile_cache(self):
        self._compile_cache.clear()


def _materialize(source: ColumnSource, columns) -> TableBlock:
    names = columns if columns is not None else source.schema.names
    blocks = list(source.blocks(block_rows=1 << 40, columns=names))
    return blocks[0] if len(blocks) == 1 else concat_blocks(blocks)


def execute_plan(plan: PlanNode, db: Database,
                 _memo: dict | None = None) -> TableBlock:
    """Bottom-up plan walk. ``_memo`` dedupes shared subtrees (a CTE
    referenced from several places executes once per statement)."""
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(plan))
    if hit is not None:
        return hit
    out = _execute_node(plan, db, _memo)
    _memo[id(plan)] = out
    return out


def _execute_node(plan: PlanNode, db: Database, _memo: dict) -> TableBlock:
    if isinstance(plan, TableScan):
        src = db.sources[plan.table]
        if plan.program is None:
            return _materialize(src, plan.columns)
        key = (plan.table, plan.program)
        ex = db._compile_cache.get(key)
        if ex is None:
            ex = ScanExecutor(
                plan.program, src, block_rows=1 << 22,
                key_spaces=db.key_spaces,
            ).detach()  # cache compiled state, not the source arrays
            db._compile_cache[key] = ex
        return ex.run_stream(src.blocks(1 << 22, ex.read_cols))
    if isinstance(plan, LookupJoin):
        probe = execute_plan(plan.probe, db, _memo)
        build = execute_plan(plan.build, db, _memo)
        return join_kernels.run_equi_join(
            probe, build, plan.probe_keys, plan.build_keys,
            kind=plan.kind, suffix=plan.suffix, payload=plan.payload,
        )
    if isinstance(plan, ExpandJoin):
        probe = execute_plan(plan.probe, db, _memo)
        build = execute_plan(plan.build, db, _memo)
        return join_kernels.run_equi_join(
            probe, build, plan.probe_keys, plan.build_keys,
            kind=plan.kind, suffix=plan.build_suffix, expand=True,
            probe_payload=plan.probe_payload,
            build_payload=plan.build_payload,
            fanout_hint=plan.fanout_hint,
        )
    if isinstance(plan, Transform):
        block = execute_plan(plan.input, db, _memo)
        key = (plan.program, plan.dict_aliases, block.schema)
        hit = db._compile_cache.get(key)
        if hit is None:
            cp = compile_program(
                plan.program, block.schema, db.dicts, db.key_spaces,
                dict_aliases=dict(plan.dict_aliases),
            )
            hit = (jax.jit(cp.run),
                   {k: jnp.asarray(v) for k, v in cp.aux.items()})
            db._compile_cache[key] = hit
        run, aux = hit
        return run(block, aux)
    raise NotImplementedError(plan)


def to_host(block: TableBlock) -> OracleTable:
    return OracleTable.from_block(block)
