from ydb_tpu.plan.nodes import (  # noqa: F401
    ExpandJoin,
    LookupJoin,
    PlanNode,
    TableScan,
    Transform,
)
from ydb_tpu.plan.executor import Database, execute_plan, to_host  # noqa: F401
