"""DQ compute actors: task execution with credit-based channel flow.

Mirror of the reference's compute-actor framework (SURVEY.md §2.10):
a generic actor hosts one task's program, drives its input/output
channels with a credit protocol (TEvChannelData / TEvChannelDataAck,
dq_compute_actor_channels.h:15), spills backlog beyond the memory quota
(spilling service), and streams the result channel to the executer.

Device work happens inside the task: each arriving block lifts to a
TableBlock, runs the stage's compiled SSA program on the accelerator, and
the (much smaller) result travels the channels host-side.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.analysis import host_ok
from ydb_tpu.blocks.block import TableBlock, concat_blocks, device_aux
from ydb_tpu.dq.graph import (
    Broadcast,
    ChannelSpec,
    HashPartition,
    ResultOutput,
    SourceInput,
    StageSpec,
    TaskSpec,
    UnionAll,
    build_tasks,
)
from ydb_tpu.dq.spilling import Spiller
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, merge_blocks_device
from ydb_tpu.runtime.actors import Actor, ActorId
from ydb_tpu.ssa.compiler import compile_program

DEFAULT_WINDOW = 4  # unacked blocks per channel before spilling


# ---- channel protocol messages ----


@dataclasses.dataclass
class ChannelData:
    channel_id: int
    seq: int
    payload: dict | None
    finished: bool


@dataclasses.dataclass
class ChannelAck:
    channel_id: int
    seq: int


@dataclasses.dataclass
class StartTask:
    pass


@dataclasses.dataclass
class WireTask:
    """Late channel wiring: consumer ActorIds for this task's output
    channels (possibly on other NODES — the targets ride the
    interconnect transparently), plus where results and aborts go.
    Sent by the executer after every task everywhere has registered
    (the two-phase start the reference's executer does when it wires
    TEvChannelData routes across compute nodes)."""

    channel_targets: dict[int, ActorId]
    result_target: ActorId | None = None
    abort_target: ActorId | None = None


@dataclasses.dataclass
class QueryAborted:
    """Fatal query error: propagated to the collector so a dead peer
    (Undelivered channel data) fails the query cleanly instead of
    hanging it (TEvAbortExecution shape, dq_compute_actor.h:41)."""

    reason: str


@dataclasses.dataclass
class _PumpSource:
    """Self-message: consume ONE source block, then re-arm. Keeps the
    mailbox responsive between blocks so checkpoint barriers (and any
    control traffic) interleave with streaming reads."""


@dataclasses.dataclass
class ResultData:
    payload: dict | None
    finished: bool


# ---- payload <-> block ----


def block_to_payload(block: TableBlock) -> dict:
    data = block.to_numpy()
    valid = block.validity_numpy()
    out = {}
    for k, v in data.items():
        out[k] = v
        out[f"__v_{k}"] = valid[k]
    return out


def payload_to_block(payload: dict, schema: dtypes.Schema) -> TableBlock:
    cols = {f.name: payload[f.name] for f in schema.fields}
    validity = {f.name: payload[f"__v_{f.name}"] for f in schema.fields}
    return TableBlock.from_numpy(cols, schema, validity)


def _hash_rows(payload: dict, schema, keys) -> np.ndarray:
    """Row hash for partition routing (the vectorized block hash
    partitioner, dq_output_consumer.cpp:338); computed once per block and
    reduced mod the channel count per consumer group. Runs in the native
    host library when built (ydb_tpu.native, bit-identical fallback)."""
    from ydb_tpu import native

    return native.hash_rows(
        [payload[k].astype(np.int64) for k in keys],
        [payload[f"__v_{k}"] for k in keys],
    )


def _split_by_hash(payload: dict, h: np.ndarray, n: int) -> list[dict]:
    if n == 1:
        return [payload]
    dest = (h % np.uint64(n)).astype(np.int64)
    out = []
    for d in range(n):
        m = dest == d
        out.append({k: v[m] for k, v in payload.items()})
    return out


class _CompiledStage:
    """Per-stage compiled programs + schemas (shared by its tasks).

    ``in_schemas`` has one schema per stage input; join stages have two
    (probe, build) and every other stage exactly one shared schema."""

    def __init__(self, spec: StageSpec, in_schemas, dicts, key_spaces):
        self.in_schemas = list(in_schemas)
        in_schema = in_schemas[0]
        self.in_schema = in_schema
        if spec.join is not None:
            self.per_block = None
            self.final = None
            self.join = spec.join
            self.out_schema = _join_out_schema(
                spec.join, in_schemas[0], in_schemas[1])
            self.mid_schema = self.out_schema
            return
        self.join = None
        if spec.program is not None:
            self.per_block = compile_program(
                spec.program, in_schema, dicts, key_spaces,
                dict_aliases=dict(spec.dict_aliases),
            )
            mid = self.per_block.out_schema
            self._pb_jit = jax.jit(self.per_block.run)
            self._pb_aux = device_aux(self.per_block.aux)
        else:
            self.per_block = None
            mid = in_schema
        self.mid_schema = mid
        if spec.final_program is not None:
            from ydb_tpu.ssa import twophase

            aliases = dict(spec.dict_aliases)
            if spec.program is not None:
                aliases.update(twophase.dict_aliases(spec.program))
            self.final = compile_program(
                spec.final_program, mid, dicts, key_spaces,
                dict_aliases=aliases,
            )
            self._f_aux = device_aux(self.final.aux)
            self.out_schema = self.final.out_schema
            final_run = self.final.run

            # the stage's whole final phase — merge accumulated partials
            # + final program — is ONE traced computation (the fused
            # finalize the single-chip ScanExecutor uses): partials never
            # round-trip through the host between merge and final
            @jax.jit
            def _finalize(parts, aux):
                return final_run(merge_blocks_device(list(parts)), aux)

            self._finalize_jit = _finalize
        else:
            self.final = None
            self.out_schema = mid
            self._f_aux = {}
            self._finalize_jit = jax.jit(
                lambda parts, aux: merge_blocks_device(list(parts)))

    def run_block(self, block: TableBlock) -> TableBlock:
        if self.per_block is None:
            return block
        return self._pb_jit(block, self._pb_aux)

    def run_join(self, probe: TableBlock, build: TableBlock) -> TableBlock:
        """Device-local join of this task's hash bucket (grace bucket
        join, mkql_grace_join_imp.cpp bucket processing). Shares the
        exact dispatch with the single-chip executor (run_equi_join)."""
        from ydb_tpu.ssa import join as join_kernels

        j = self.join
        return join_kernels.run_equi_join(
            probe, build, j.probe_keys, j.build_keys, kind=j.kind,
            suffix=j.suffix, expand=j.expand, payload=j.payload,
            probe_payload=j.probe_payload, build_payload=j.build_payload,
            fanout_hint=j.fanout_hint,
        )

    def run_final(self, blocks: list[TableBlock]) -> TableBlock:
        if self.final is None and len(blocks) == 1:
            return blocks[0]
        return self._finalize_jit(tuple(blocks), self._f_aux)


class ComputeActor(Actor):
    """Hosts one task (sync compute actor variant,
    dq_compute_actor_impl.h:95)."""

    def __init__(
        self,
        task: TaskSpec,
        compiled: _CompiledStage,
        channel_targets: dict[int, ActorId],  # my out channel -> consumer
        channel_specs: dict[int, ChannelSpec],
        sources: list[ColumnSource],
        result_target: ActorId | None,
        spiller: Spiller | None = None,
        window: int = DEFAULT_WINDOW,
        block_rows: int = 1 << 16,
        checkpoint_storage=None,
        restore_checkpoint: int | None = None,
    ):
        super().__init__()
        self.task = task
        self.compiled = compiled
        self.channel_targets = channel_targets
        self.channel_specs = channel_specs
        self.sources = sources
        self.result_target = result_target
        self.window = window
        self.block_rows = block_rows
        self.spiller = spiller or Spiller()
        self.abort_target: ActorId | None = None
        self._aborted = False
        # profile span for this task (opened at StartTask when a query
        # trace is active on the executer thread; finished with the
        # task's accumulated device-compute seconds)
        self._span = None
        self._compute_s = 0.0

        self._in_finished: set[int] = set()
        # agg stages accumulate partial states THROUGH the spiller
        # (operator spilling: beyond the memory quota the partials live
        # in blobs, not RAM — dq_spilling + combiner spill analog)
        self._acc_ids: list[int] = []
        # join stages accumulate their hash bucket per side (payloads
        # stay host-side until the single device-local bucket join)
        self._join_acc: dict[int, list] = {0: [], 1: []}
        self._unacked: dict[int, int] = {c: 0 for c in task.output_channels}
        self._parked: dict[int, collections.deque] = {
            c: collections.deque() for c in task.output_channels
        }
        self._next_seq: dict[int, int] = {c: 0 for c in task.output_channels}
        self._fin_pending: set[int] = set()
        self._done = False
        groups: dict[tuple[int, int], list[int]] = {}
        for c in task.output_channels:
            spec = channel_specs[c]
            groups.setdefault((spec.dst_stage, spec.input_index),
                              []).append(c)
        # hash slot p must land on the consumer task with dst_index p
        self._consumer_groups: list[list[int]] = [
            sorted(chs, key=lambda c: channel_specs[c].dst_index)
            for chs in groups.values()
        ]

        # ---- checkpoint state (IDqTaskRunner Save/Load analog) ----
        self.checkpoint_storage = checkpoint_storage
        self.coordinator_target: ActorId | None = None
        self._source_iter = None
        self._source_pos = 0          # blocks consumed from sources
        self._source_done = not sources
        self._aligned: dict[int, set] = {}   # ckpt id -> aligned channels
        self._barrier_of: dict[int, int] = {}  # channel -> pending ckpt
        # channel -> post-barrier msgs (FIFO; drained with popleft)
        self._held: dict[int, collections.deque] = {}
        if restore_checkpoint is not None and checkpoint_storage:
            state = checkpoint_storage.load_task(
                restore_checkpoint, task.task_id)
            if state is not None:
                self._acc_ids = [
                    self.spiller.put(p) for p in state["acc"]
                ]
                self._join_acc = {
                    int(k): list(v)
                    for k, v in state.get("join_acc", {}).items()
                } or {0: [], 1: []}
                self._source_pos = state["source_pos"]
                self.block_rows = state["block_rows"]
                self._in_finished = set(state["in_finished"])

    # ---- input side ----

    def receive(self, message, sender):
        from ydb_tpu.dq.checkpoint import InjectCheckpoint
        from ydb_tpu.runtime.interconnect import Undelivered

        if isinstance(message, StartTask):
            from ydb_tpu.obs import tracing

            parent = tracing.current_span()
            if parent is not None and self._span is None:
                self._span = parent.child("dq.task").set(
                    stage=self.task.stage, task=self.task.task_id,
                    thread=threading.get_ident())
            self._start_source()
        elif isinstance(message, _PumpSource):
            if not self._aborted:
                self._pump_source()
        elif isinstance(message, WireTask):
            self.channel_targets.update(message.channel_targets)
            if message.result_target is not None:
                self.result_target = message.result_target
            if message.abort_target is not None:
                self.abort_target = message.abort_target
        elif isinstance(message, InjectCheckpoint):
            # source-side barrier injection: snapshot between blocks
            self._take_checkpoint(message.checkpoint_id)
        elif isinstance(message, ChannelData):
            self.send(sender, ChannelAck(message.channel_id, message.seq))
            if not self._aborted:
                self._on_channel_data(message)
        elif isinstance(message, ChannelAck):
            self._on_ack(message)
        elif isinstance(message, Undelivered):
            # a peer died with our channel data in flight: the query
            # cannot complete — abort it at the collector and stop
            # feeding the graph from this task
            self._aborted = True
            if self.abort_target is not None:
                self.send(self.abort_target, QueryAborted(
                    f"task {self.task.task_id}: channel data undelivered "
                    f"({message.reason})"))
        elif isinstance(message, QueryAborted):
            self._aborted = True
        else:
            raise TypeError(message)

    def _on_channel_data(self, message: ChannelData):
        from ydb_tpu.dq.checkpoint import BARRIER_KEY

        ch = message.channel_id
        # anything arriving on a channel that already delivered a
        # barrier for a pending checkpoint belongs to a later epoch:
        # hold it, in arrival order, until that checkpoint is taken.
        # Per-channel FIFO keeps multiple in-flight checkpoints
        # consistent — each release stops at the channel's next barrier.
        if ch in self._barrier_of:
            self._held.setdefault(ch, collections.deque()).append(message)
            return
        payload = message.payload
        if payload is not None and BARRIER_KEY in payload:
            self._register_barrier(int(payload[BARRIER_KEY]), ch)
            return
        self._apply_channel_data(message)

    def _apply_channel_data(self, message: ChannelData):
        if message.payload is not None:
            if self.compiled.join is not None:
                idx = self.channel_specs[message.channel_id].input_index
                self._join_acc[idx].append(message.payload)
            else:
                blk = payload_to_block(message.payload,
                                       self.compiled.in_schema)
                self._ingest(blk)
        if message.finished:
            self._in_finished.add(message.channel_id)
            self._check_alignment()  # finished counts as aligned
            if self._in_finished >= set(self.task.input_channels):
                self._finish_input()

    # ---- checkpoint protocol ----

    def _register_barrier(self, checkpoint_id: int, channel_id: int):
        self._barrier_of[channel_id] = checkpoint_id
        self._aligned.setdefault(checkpoint_id, set()).add(channel_id)
        self._check_alignment()

    def _check_alignment(self):
        need = set(self.task.input_channels)
        while self._aligned:
            # checkpoints must be taken in id order; per-channel FIFO
            # guarantees the smallest pending id aligns first
            cid = min(self._aligned)
            if not (self._aligned[cid] | self._in_finished) >= need:
                return
            self._take_checkpoint(cid)

    def _take_checkpoint(self, checkpoint_id: int):
        from ydb_tpu.dq.checkpoint import BARRIER_KEY, TaskCheckpointed

        if self.checkpoint_storage is not None:
            self.checkpoint_storage.save_task(checkpoint_id,
                                              self.task.task_id, {
                "acc": [self.spiller.peek(sid)
                        for sid in self._acc_ids],
                # join stages: both sides' accumulated bucket payloads
                "join_acc": {k: list(v)
                             for k, v in self._join_acc.items()},
                # position is counted in BLOCKS of this block size; the
                # restore pins block_rows so the count stays meaningful
                "source_pos": self._source_pos,
                "block_rows": self.block_rows,
                "in_finished": sorted(self._in_finished),
            })
        # forward the barrier in band on EVERY output channel (parks
        # behind pending data, so it cannot overtake blocks)
        if not isinstance(self.task.stage_spec.output, ResultOutput):
            # numpy value so the credit queue/spiller treat the barrier
            # exactly like a (tiny) data payload
            barrier = {BARRIER_KEY: np.asarray(checkpoint_id)}
            for ch in self.task.output_channels:
                self._send_channel(ch, barrier)
        if self.coordinator_target is not None:
            self.send(self.coordinator_target,
                      TaskCheckpointed(self.task.task_id, checkpoint_id))
        # release each aligned channel's held messages up to (and
        # registering) that channel's next barrier, in arrival order
        chans = self._aligned.pop(checkpoint_id, set())
        for ch in sorted(chans):
            if self._barrier_of.get(ch) == checkpoint_id:
                del self._barrier_of[ch]
            q = self._held.get(ch, collections.deque())
            while q:
                msg = q.popleft()
                payload = msg.payload
                if payload is not None and BARRIER_KEY in payload:
                    self._register_barrier(int(payload[BARRIER_KEY]), ch)
                    break
                self._apply_channel_data(msg)
            if not q:
                self._held.pop(ch, None)

    # ---- source streaming ----

    def _start_source(self):
        # scan stages stream only the program's required columns (the
        # scan-executor projection, ScanExecutor.read_cols): stream
        # sources then skip unread chunks entirely
        names = None
        if self.compiled.per_block is not None:
            names = self.compiled.in_schema.names

        def blocks(skip: int):
            # checkpoint resume: seek in O(1) per source rather than
            # materializing and discarding consumed blocks (n_blocks is
            # only required of sources that actually resume)
            for source in self.sources:
                if skip:
                    nb = source.n_blocks(self.block_rows)
                    if skip >= nb:
                        skip -= nb
                        continue
                yield from source.blocks(self.block_rows, columns=names,
                                         start_block=skip)
                skip = 0

        self._source_iter = blocks(self._source_pos)
        if self.sources:
            self.send(self.self_id, _PumpSource())
        elif not self.task.input_channels:
            self._finish_input()

    def _pump_source(self):
        # block-boundary cancellation: a statement past its deadline
        # stops pumping and aborts the whole graph (the collector turns
        # this into a typed StatementCancelled at the executor)
        from ydb_tpu.chaos import deadline as statement_deadline

        dl = statement_deadline.current()
        if dl is not None and dl.expired():
            self._aborted = True
            if self.abort_target is not None:
                self.send(self.abort_target, QueryAborted(
                    f"task {self.task.task_id}: statement deadline "
                    "exceeded"))
            return
        blk = next(self._source_iter, None)
        if blk is None:
            if not self.task.input_channels:
                self._finish_input()
            return
        self._source_pos += 1
        self._ingest(blk)
        self.send(self.self_id, _PumpSource())

    def _timed(self, fn, *args):
        """Charge a stage-program dispatch to the task's profile span
        (pass-through when no trace is active)."""
        if self._span is None:
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        self._compute_s += time.perf_counter() - t0
        return out

    def _ingest(self, block: TableBlock):
        spec = self.task.stage_spec
        if spec.final_program is not None:
            # aggregate stage: per-block partial, accumulated via the
            # spiller (blocks beyond the quota go to blobs)
            self._acc_ids.append(self.spiller.put(
                block_to_payload(
                    self._timed(self.compiled.run_block, block))))
        else:
            out = self._timed(self.compiled.run_block, block)
            self._emit(out)

    def _finish_input(self):
        spec = self.task.stage_spec
        if self.compiled.join is not None:
            probe = _assemble(self._join_acc[0],
                              self.compiled.in_schemas[0])
            build = _assemble(self._join_acc[1],
                              self.compiled.in_schemas[1])
            self._join_acc = {0: [], 1: []}
            self._emit(self._timed(self.compiled.run_join, probe, build))
            self._finish_output()
            return
        if spec.final_program is not None:
            if self._acc_ids:
                blocks = [
                    payload_to_block(self.spiller.get(sid),
                                     self.compiled.mid_schema)
                    for sid in self._acc_ids
                ]
                self._emit(self._timed(self.compiled.run_final, blocks))
            else:
                # empty input still finalizes (COUNT over nothing etc.)
                empty = _empty_block(self.compiled.mid_schema)
                self._emit(self._timed(self.compiled.run_final, [empty]))
            self._acc_ids = []
        self._finish_output()

    # ---- output side ----

    def _emit(self, block: TableBlock):
        if int(block.capacity) == 0:
            return
        payload = block_to_payload(block)
        out = self.task.stage_spec.output
        if isinstance(out, ResultOutput):
            self.send(self.result_target, ResultData(payload, False))
            return
        # each consumer edge gets the full routed stream independently;
        # the row hash is only needed when some edge actually fans out
        h = None
        if isinstance(out, HashPartition) and any(
                len(chans) > 1 for chans in self._consumer_groups):
            h = _hash_rows(payload, self.compiled.out_schema, out.keys)
        for chans in self._consumer_groups:
            if isinstance(out, HashPartition) and len(chans) > 1:
                for ch, part in zip(chans,
                                    _split_by_hash(payload, h, len(chans))):
                    if len(next(iter(part.values()))) == 0:
                        continue
                    self._send_channel(ch, part)
            else:  # Broadcast/UnionAll, or a single-task hash consumer
                for ch in chans:
                    self._send_channel(ch, payload)

    def _send_channel(self, ch: int, payload: dict):
        if self._unacked[ch] >= self.window:
            self._parked[ch].append(self.spiller.put(payload))
            return
        self._dispatch(ch, payload, finished=False)

    def _dispatch(self, ch: int, payload: dict | None, finished: bool):
        seq = self._next_seq[ch]
        self._next_seq[ch] += 1
        if payload is not None:
            self._unacked[ch] += 1
        self.send(self.channel_targets[ch],
                  ChannelData(ch, seq, payload, finished))

    def _finish_output(self):
        self._done = True
        if self._span is not None:
            self._span.set(compute_seconds=round(self._compute_s, 6))
            self._span.finish()
            self._span = None
        if isinstance(self.task.stage_spec.output, ResultOutput):
            self.send(self.result_target, ResultData(None, True))
            return
        for ch in self.task.output_channels:
            if self._parked[ch] or self._unacked[ch] > 0:
                self._fin_pending.add(ch)
            else:
                self._dispatch(ch, None, finished=True)

    def _on_ack(self, ack: ChannelAck):
        ch = ack.channel_id
        self._unacked[ch] -= 1
        while self._parked[ch] and self._unacked[ch] < self.window:
            sid = self._parked[ch].popleft()
            self._dispatch(ch, self.spiller.get(sid), finished=False)
        if (
            ch in self._fin_pending
            and not self._parked[ch]
            and self._unacked[ch] == 0
        ):
            self._fin_pending.discard(ch)
            self._dispatch(ch, None, finished=True)


def _assemble(payloads: list[dict], schema: dtypes.Schema) -> TableBlock:
    """Concat channel payloads into one block (capacity >= 1 so the join
    kernels' searchsorted shapes stay valid on empty sides)."""
    cols = {}
    validity = {}
    for f in schema.fields:
        parts = [p[f.name] for p in payloads]
        vparts = [p[f"__v_{f.name}"] for p in payloads]
        cols[f.name] = (np.concatenate(parts) if parts
                        else np.empty(0, dtype=f.type.physical))
        validity[f.name] = (np.concatenate(vparts) if vparts
                            else np.empty(0, dtype=bool))
    n = len(next(iter(cols.values()))) if cols else 0
    return TableBlock.from_numpy(cols, schema, validity,
                                 capacity=max(n, 1))


def _join_out_schema(j, probe_schema: dtypes.Schema,
                     build_schema: dtypes.Schema) -> dtypes.Schema:
    """Static output schema of a join stage."""
    left = j.kind == "left"  # NULL-extended build payload is nullable
    if not j.expand:
        if j.kind in ("semi", "anti"):
            return probe_schema
        fields = list(probe_schema.fields)
        for n in j.payload:
            f = build_schema.field(n)
            fields.append(dtypes.Field(n + j.suffix, f.type,
                                       f.nullable or left))
        return dtypes.Schema(tuple(fields))
    fields = [probe_schema.field(n) for n in j.probe_payload]
    for n in j.build_payload:
        f = build_schema.field(n)
        fields.append(dtypes.Field(n + j.suffix, f.type,
                                   f.nullable or left))
    return dtypes.Schema(tuple(fields))


@host_ok("zero-row result block: one bounded 0-byte alloc per column,"
         " only when a stage produced no rows")
def _empty_block(schema: dtypes.Schema) -> TableBlock:
    cols = {
        f.name: np.empty(0, dtype=f.type.physical) for f in schema.fields
    }
    return TableBlock.from_numpy(cols, schema, capacity=1)


class ResultCollector(Actor):
    def __init__(self, schema: dtypes.Schema):
        super().__init__()
        self.schema = schema
        self.payloads: list[dict] = []
        self.done = False
        self.error: str | None = None

    def receive(self, message, sender):
        from ydb_tpu.runtime.interconnect import Undelivered

        if isinstance(message, QueryAborted):
            if self.error is None:
                self.error = message.reason
            return
        if isinstance(message, Undelivered):
            # a liveness ping (or any collector-sent envelope) bounced:
            # the peer node is gone — fail the query
            if self.error is None:
                self.error = f"peer unreachable: {message.reason}"
            return
        assert isinstance(message, ResultData)
        if message.payload is not None:
            self.payloads.append(message.payload)
        if message.finished:
            self.done = True

    def result_block(self) -> TableBlock:
        if not self.payloads:
            return _empty_block(self.schema)
        blocks = [payload_to_block(p, self.schema) for p in self.payloads]
        return blocks[0] if len(blocks) == 1 else concat_blocks(blocks)

    def table(self) -> OracleTable:
        return OracleTable.from_block(self.result_block())


def task_partitions(sources: dict[str, list], task: TaskSpec) -> list:
    """Source partitions assigned to one task: task p of an N-task stage
    reads partitions p, p+N, p+2N, … so every partition is read exactly
    once for any task-count / partition-count ratio. The ONE assignment
    rule — local build, remote task start, and the executer all share it
    (changing it anywhere else would silently double-read or drop data)."""
    out: list = []
    for inp in task.stage_spec.inputs:
        if isinstance(inp, SourceInput):
            parts = sources.get(inp.source_id, [])
            out.extend(parts[task.partition::task.stage_spec.tasks])
    return out


def compile_stages(
    stages: list[StageSpec],
    source_schemas: dict[str, dtypes.Schema],
    dicts=None,
    key_spaces=None,
    compile_cache: dict | None = None,
) -> list[_CompiledStage]:
    """Compile every stage, flowing schemas source -> downstream. Needs
    only the SOURCE SCHEMAS, not the data — a remote node re-derives the
    whole compiled chain from the shipped stage specs (the task-start
    path, kqp_node_service.cpp:121)."""
    from ydb_tpu.engine.scan import required_columns
    from ydb_tpu.obs import tracing

    compiled: list[_CompiledStage] = []
    cache_hits = cache_misses = 0
    for si, spec in enumerate(stages):
        in_schemas = []
        for inp in spec.inputs:
            if isinstance(inp, SourceInput):
                sch = source_schemas[inp.source_id]
                if spec.program is not None:
                    # scan projection: compile (and later stream) only
                    # the program's required columns
                    sch = sch.select(required_columns(spec.program, sch))
                in_schemas.append(sch)
            else:
                in_schemas.append(compiled[inp.from_stage].out_schema)
        if not in_schemas:
            raise ValueError("stage with no inputs")
        if spec.join is not None:
            if len(in_schemas) != 2:
                raise ValueError(
                    f"join stage {si} needs exactly (probe, build) inputs")
        elif any(s != in_schemas[0] for s in in_schemas[1:]):
            # every channel payload decodes with one schema; unequal
            # upstream schemas would silently mislabel columns
            raise ValueError(
                f"stage {si}: all inputs must share one schema, got "
                f"{[s.names for s in in_schemas]}"
            )
        ck = None
        if compile_cache is not None:
            # dicts participate by identity (aux tables bake dictionary
            # contents); key_spaces by value — mixing either across one
            # cache dict must miss, not alias
            ck = ("dq_stage", spec.program, spec.final_program, spec.join,
                  spec.dict_aliases, tuple(in_schemas), id(dicts),
                  tuple(sorted(key_spaces.items()))
                  if key_spaces else None)
            hit = compile_cache.get(ck)
            if hit is not None:
                cache_hits += 1
                compiled.append(hit)
                continue
        cache_misses += 1
        stage = _CompiledStage(spec, in_schemas, dicts, key_spaces)
        if ck is not None:
            compile_cache[ck] = stage
        compiled.append(stage)
    # stage-compile cache effectiveness rides the query trace (the DQ
    # half of the compile-vs-execute attribution)
    tracing.annotate(dq_compile_hits=cache_hits,
                     dq_compile_misses=cache_misses)
    return compiled


@dataclasses.dataclass
class GraphHandle:
    """A built-but-not-finished dataflow: the executer's live view."""

    actors: list
    actor_of_task: dict
    collector: "ResultCollector"
    collector_id: ActorId
    systems: list
    tasks: list
    result_stage: int
    coordinator: object = None
    coordinator_id: ActorId | None = None

    def start(self):
        sys_by_node = {s.node: s for s in self.systems}
        for t in self.tasks:
            aid = self.actor_of_task[t.task_id]
            sys_by_node[aid.node].send(aid, StartTask())

    def close(self):
        """Release per-task resources once the graph is finished or
        abandoned. Spillers hold blobs that only ``get`` deletes, so a
        graph torn down with parked/accumulated ids (abort, deadline
        cancellation) must close them here or the blobs leak for the
        store's lifetime. Idempotent."""
        for a in self.actors:
            a.spiller.close()


def build_stage_graph(
    stages: list[StageSpec],
    sources: dict[str, list[ColumnSource]],
    runtime,
    dicts=None,
    key_spaces=None,
    spill_quota_bytes: int = 64 << 20,
    window: int = DEFAULT_WINDOW,
    checkpoint_storage=None,
    restore_checkpoint: int | None = None,
    block_rows: int = 1 << 16,
    compile_cache: dict | None = None,
) -> GraphHandle:
    """Compile stages, place tasks round-robin over the runtime's nodes,
    wire channels (the executer-actor shape, kqp_executer_impl.h:120 +
    planner kqp_planner.cpp:116). With ``checkpoint_storage``, a
    CheckpointCoordinator is attached; with ``restore_checkpoint``,
    every task loads its saved state and sources resume mid-stream.
    ``compile_cache`` memoizes compiled stages across graphs (the
    computation-pattern-cache seam the single-chip executor has)."""
    # unreferenced sources may have zero partitions; referenced ones
    # must not (compile_stages then raises KeyError, as before)
    source_schemas = {sid: parts[0].schema
                      for sid, parts in sources.items() if parts}
    compiled = compile_stages(stages, source_schemas, dicts, key_spaces,
                              compile_cache)

    tasks, channels, result_stage = build_tasks(stages)
    systems = list(runtime.nodes.values()) if hasattr(runtime, "nodes") \
        else [runtime]
    collector = ResultCollector(compiled[result_stage].out_schema)
    collector_id = systems[0].register(collector)

    # place tasks, then wire channel targets
    actor_of_task: dict[int, ActorId] = {}
    actors: list[ComputeActor] = []
    chan_by_id = {c.channel_id: c for c in channels}
    for i, t in enumerate(tasks):
        srcs = task_partitions(sources, t)
        a = ComputeActor(
            t, compiled[t.stage], {}, chan_by_id, srcs,
            collector_id,
            spiller=Spiller(mem_quota_bytes=spill_quota_bytes,
                            prefix=f"spill/task{t.task_id}"),
            window=window,
            block_rows=block_rows,
            checkpoint_storage=checkpoint_storage,
            restore_checkpoint=restore_checkpoint,
        )
        sys_i = systems[i % len(systems)]
        actor_of_task[t.task_id] = sys_i.register(a)
        actors.append(a)
    for a in actors:
        for ch in a.task.output_channels:
            a.channel_targets[ch] = actor_of_task[chan_by_id[ch].dst_task]

    handle = GraphHandle(actors, actor_of_task, collector, collector_id,
                         systems, tasks, result_stage)
    if checkpoint_storage is not None:
        from ydb_tpu.dq.checkpoint import CheckpointCoordinator

        source_task_ids = [
            actor_of_task[t.task_id] for t in tasks
            if any(isinstance(i, SourceInput) for i in t.stage_spec.inputs)
        ]
        coord = CheckpointCoordinator(
            checkpoint_storage, source_task_ids, n_tasks=len(tasks),
            start_id=restore_checkpoint or 0)
        coord_id = systems[0].register(coord)
        for a in actors:
            a.coordinator_target = coord_id
        handle.coordinator = coord
        handle.coordinator_id = coord_id
    return handle


def run_stage_graph(
    stages: list[StageSpec],
    sources: dict[str, list[ColumnSource]],
    runtime,
    dicts=None,
    key_spaces=None,
    spill_quota_bytes: int = 64 << 20,
    window: int = DEFAULT_WINDOW,
    checkpoint_storage=None,
    restore_checkpoint: int | None = None,
    block_rows: int = 1 << 16,
    compile_cache: dict | None = None,
) -> OracleTable:
    """Build + run to completion, return the result table."""
    handle = build_stage_graph(
        stages, sources, runtime, dicts, key_spaces, spill_quota_bytes,
        window, checkpoint_storage, restore_checkpoint, block_rows,
        compile_cache)
    try:
        handle.start()
        if hasattr(runtime, "dispatch"):
            runtime.dispatch()
        else:
            runtime.run()
        err = handle.collector.error
        if err is not None and "deadline" in err:
            from ydb_tpu.chaos.deadline import StatementCancelled

            raise StatementCancelled(err)
        if not handle.collector.done:
            raise RuntimeError("stage graph did not complete")
        return handle.collector.table()
    finally:
        handle.close()
