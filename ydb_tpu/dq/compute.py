"""DQ compute actors: task execution with credit-based channel flow.

Mirror of the reference's compute-actor framework (SURVEY.md §2.10):
a generic actor hosts one task's program, drives its input/output
channels with a credit protocol (TEvChannelData / TEvChannelDataAck,
dq_compute_actor_channels.h:15), spills backlog beyond the memory quota
(spilling service), and streams the result channel to the executer.

Device work happens inside the task: each arriving block lifts to a
TableBlock, runs the stage's compiled SSA program on the accelerator, and
the (much smaller) result travels the channels host-side.
"""

from __future__ import annotations

import collections
import dataclasses

import jax.numpy as jnp
import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import TableBlock, concat_blocks
from ydb_tpu.dq.graph import (
    Broadcast,
    ChannelSpec,
    HashPartition,
    ResultOutput,
    SourceInput,
    StageSpec,
    TaskSpec,
    UnionAll,
    build_tasks,
)
from ydb_tpu.dq.spilling import Spiller
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.runtime.actors import Actor, ActorId
from ydb_tpu.ssa.compiler import compile_program

DEFAULT_WINDOW = 4  # unacked blocks per channel before spilling


# ---- channel protocol messages ----


@dataclasses.dataclass
class ChannelData:
    channel_id: int
    seq: int
    payload: dict | None
    finished: bool


@dataclasses.dataclass
class ChannelAck:
    channel_id: int
    seq: int


@dataclasses.dataclass
class StartTask:
    pass


@dataclasses.dataclass
class ResultData:
    payload: dict | None
    finished: bool


# ---- payload <-> block ----


def block_to_payload(block: TableBlock) -> dict:
    data = block.to_numpy()
    valid = block.validity_numpy()
    out = {}
    for k, v in data.items():
        out[k] = v
        out[f"__v_{k}"] = valid[k]
    return out


def payload_to_block(payload: dict, schema: dtypes.Schema) -> TableBlock:
    cols = {f.name: payload[f.name] for f in schema.fields}
    validity = {f.name: payload[f"__v_{f.name}"] for f in schema.fields}
    return TableBlock.from_numpy(cols, schema, validity)


def _hash_rows(payload: dict, schema, keys) -> np.ndarray:
    """Row hash for partition routing (the vectorized block hash
    partitioner, dq_output_consumer.cpp:338); computed once per block and
    reduced mod the channel count per consumer group. Runs in the native
    host library when built (ydb_tpu.native, bit-identical fallback)."""
    from ydb_tpu import native

    return native.hash_rows(
        [payload[k].astype(np.int64) for k in keys],
        [payload[f"__v_{k}"] for k in keys],
    )


def _split_by_hash(payload: dict, h: np.ndarray, n: int) -> list[dict]:
    if n == 1:
        return [payload]
    dest = (h % np.uint64(n)).astype(np.int64)
    out = []
    for d in range(n):
        m = dest == d
        out.append({k: v[m] for k, v in payload.items()})
    return out


class _CompiledStage:
    """Per-stage compiled programs + schemas (shared by its tasks)."""

    def __init__(self, spec: StageSpec, in_schema, dicts, key_spaces):
        self.in_schema = in_schema
        if spec.program is not None:
            self.per_block = compile_program(
                spec.program, in_schema, dicts, key_spaces
            )
            mid = self.per_block.out_schema
            self._pb_aux = {
                k: jnp.asarray(v) for k, v in self.per_block.aux.items()
            }
        else:
            self.per_block = None
            mid = in_schema
        self.mid_schema = mid
        if spec.final_program is not None:
            from ydb_tpu.ssa import twophase

            aliases = (
                twophase.dict_aliases(spec.program)
                if spec.program is not None else None
            )
            self.final = compile_program(
                spec.final_program, mid, dicts, key_spaces,
                dict_aliases=aliases,
            )
            self._f_aux = {
                k: jnp.asarray(v) for k, v in self.final.aux.items()
            }
            self.out_schema = self.final.out_schema
        else:
            self.final = None
            self.out_schema = mid

    def run_block(self, block: TableBlock) -> TableBlock:
        if self.per_block is None:
            return block
        return self.per_block.run(block, self._pb_aux)

    def run_final(self, blocks: list[TableBlock]) -> TableBlock:
        merged = blocks[0] if len(blocks) == 1 else concat_blocks(blocks)
        if self.final is None:
            return merged
        return self.final.run(merged, self._f_aux)


class ComputeActor(Actor):
    """Hosts one task (sync compute actor variant,
    dq_compute_actor_impl.h:95)."""

    def __init__(
        self,
        task: TaskSpec,
        compiled: _CompiledStage,
        channel_targets: dict[int, ActorId],  # my out channel -> consumer
        channel_specs: dict[int, ChannelSpec],
        sources: list[ColumnSource],
        result_target: ActorId | None,
        spiller: Spiller | None = None,
        window: int = DEFAULT_WINDOW,
        block_rows: int = 1 << 16,
    ):
        super().__init__()
        self.task = task
        self.compiled = compiled
        self.channel_targets = channel_targets
        self.channel_specs = channel_specs
        self.sources = sources
        self.result_target = result_target
        self.window = window
        self.block_rows = block_rows
        self.spiller = spiller or Spiller()

        self._in_finished: set[int] = set()
        self._acc: list[TableBlock] = []  # agg stages accumulate
        self._unacked: dict[int, int] = {c: 0 for c in task.output_channels}
        self._parked: dict[int, collections.deque] = {
            c: collections.deque() for c in task.output_channels
        }
        self._next_seq: dict[int, int] = {c: 0 for c in task.output_channels}
        self._fin_pending: set[int] = set()
        self._done = False
        groups: dict[tuple[int, int], list[int]] = {}
        for c in task.output_channels:
            spec = channel_specs[c]
            groups.setdefault((spec.dst_stage, spec.input_index),
                              []).append(c)
        # hash slot p must land on the consumer task with dst_index p
        self._consumer_groups: list[list[int]] = [
            sorted(chs, key=lambda c: channel_specs[c].dst_index)
            for chs in groups.values()
        ]

    # ---- input side ----

    def receive(self, message, sender):
        if isinstance(message, StartTask):
            self._consume_source()
        elif isinstance(message, ChannelData):
            self.send(sender, ChannelAck(message.channel_id, message.seq))
            if message.payload is not None:
                blk = payload_to_block(message.payload,
                                       self.compiled.in_schema)
                self._ingest(blk)
            if message.finished:
                self._in_finished.add(message.channel_id)
                if self._in_finished >= set(self.task.input_channels):
                    self._finish_input()
        elif isinstance(message, ChannelAck):
            self._on_ack(message)
        else:
            raise TypeError(message)

    def _consume_source(self):
        for source in self.sources:
            for blk in source.blocks(self.block_rows):
                self._ingest(blk)
        if not self.task.input_channels:
            self._finish_input()

    def _ingest(self, block: TableBlock):
        spec = self.task.stage_spec
        if spec.final_program is not None:
            # aggregate stage: per-block partial, accumulate for the merge
            self._acc.append(self.compiled.run_block(block))
        else:
            out = self.compiled.run_block(block)
            self._emit(out)

    def _finish_input(self):
        spec = self.task.stage_spec
        if spec.final_program is not None:
            if self._acc:
                self._emit(self.compiled.run_final(self._acc))
            else:
                # empty input still finalizes (COUNT over nothing etc.)
                empty = _empty_block(self.compiled.mid_schema)
                self._emit(self.compiled.run_final([empty]))
            self._acc = []
        self._finish_output()

    # ---- output side ----

    def _emit(self, block: TableBlock):
        if int(block.capacity) == 0:
            return
        payload = block_to_payload(block)
        out = self.task.stage_spec.output
        if isinstance(out, ResultOutput):
            self.send(self.result_target, ResultData(payload, False))
            return
        # each consumer edge gets the full routed stream independently;
        # the row hash is only needed when some edge actually fans out
        h = None
        if isinstance(out, HashPartition) and any(
                len(chans) > 1 for chans in self._consumer_groups):
            h = _hash_rows(payload, self.compiled.out_schema, out.keys)
        for chans in self._consumer_groups:
            if isinstance(out, HashPartition) and len(chans) > 1:
                for ch, part in zip(chans,
                                    _split_by_hash(payload, h, len(chans))):
                    if len(next(iter(part.values()))) == 0:
                        continue
                    self._send_channel(ch, part)
            else:  # Broadcast/UnionAll, or a single-task hash consumer
                for ch in chans:
                    self._send_channel(ch, payload)

    def _send_channel(self, ch: int, payload: dict):
        if self._unacked[ch] >= self.window:
            self._parked[ch].append(self.spiller.put(payload))
            return
        self._dispatch(ch, payload, finished=False)

    def _dispatch(self, ch: int, payload: dict | None, finished: bool):
        seq = self._next_seq[ch]
        self._next_seq[ch] += 1
        if payload is not None:
            self._unacked[ch] += 1
        self.send(self.channel_targets[ch],
                  ChannelData(ch, seq, payload, finished))

    def _finish_output(self):
        self._done = True
        if isinstance(self.task.stage_spec.output, ResultOutput):
            self.send(self.result_target, ResultData(None, True))
            return
        for ch in self.task.output_channels:
            if self._parked[ch] or self._unacked[ch] > 0:
                self._fin_pending.add(ch)
            else:
                self._dispatch(ch, None, finished=True)

    def _on_ack(self, ack: ChannelAck):
        ch = ack.channel_id
        self._unacked[ch] -= 1
        while self._parked[ch] and self._unacked[ch] < self.window:
            sid = self._parked[ch].popleft()
            self._dispatch(ch, self.spiller.get(sid), finished=False)
        if (
            ch in self._fin_pending
            and not self._parked[ch]
            and self._unacked[ch] == 0
        ):
            self._fin_pending.discard(ch)
            self._dispatch(ch, None, finished=True)


def _empty_block(schema: dtypes.Schema) -> TableBlock:
    cols = {
        f.name: np.empty(0, dtype=f.type.physical) for f in schema.fields
    }
    return TableBlock.from_numpy(cols, schema, capacity=1)


class ResultCollector(Actor):
    def __init__(self, schema: dtypes.Schema):
        super().__init__()
        self.schema = schema
        self.payloads: list[dict] = []
        self.done = False

    def receive(self, message, sender):
        assert isinstance(message, ResultData)
        if message.payload is not None:
            self.payloads.append(message.payload)
        if message.finished:
            self.done = True

    def table(self) -> OracleTable:
        if not self.payloads:
            blk = _empty_block(self.schema)
            return OracleTable.from_block(blk)
        blocks = [payload_to_block(p, self.schema) for p in self.payloads]
        return OracleTable.from_block(
            blocks[0] if len(blocks) == 1 else concat_blocks(blocks)
        )


def run_stage_graph(
    stages: list[StageSpec],
    sources: dict[str, list[ColumnSource]],
    runtime,
    dicts=None,
    key_spaces=None,
    spill_quota_bytes: int = 64 << 20,
    window: int = DEFAULT_WINDOW,
) -> OracleTable:
    """Compile stages, place tasks round-robin over the runtime's nodes,
    run to completion, return the result (the executer-actor shape,
    kqp_executer_impl.h:120 + planner kqp_planner.cpp:116)."""
    # schemas flow source -> downstream
    compiled: list[_CompiledStage] = []
    for si, spec in enumerate(stages):
        in_schemas = []
        for inp in spec.inputs:
            if isinstance(inp, SourceInput):
                in_schemas.append(sources[inp.source_id][0].schema)
            else:
                in_schemas.append(compiled[inp.from_stage].out_schema)
        if not in_schemas:
            raise ValueError("stage with no inputs")
        if any(s != in_schemas[0] for s in in_schemas[1:]):
            # every channel payload decodes with one schema; unequal
            # upstream schemas would silently mislabel columns
            raise ValueError(
                f"stage {si}: all inputs must share one schema, got "
                f"{[s.names for s in in_schemas]}"
            )
        compiled.append(
            _CompiledStage(spec, in_schemas[0], dicts, key_spaces)
        )

    tasks, channels, result_stage = build_tasks(stages)
    systems = list(runtime.nodes.values()) if hasattr(runtime, "nodes") \
        else [runtime]
    collector = ResultCollector(compiled[result_stage].out_schema)
    collector_id = systems[0].register(collector)

    # place tasks, then wire channel targets
    actor_of_task: dict[int, ActorId] = {}
    actors: list[ComputeActor] = []
    chan_by_id = {c.channel_id: c for c in channels}
    for i, t in enumerate(tasks):
        srcs: list[ColumnSource] = []
        for inp in t.stage_spec.inputs:
            if isinstance(inp, SourceInput):
                # strided assignment: task p reads partitions p, p+N, …
                # so every partition is read exactly once regardless of
                # the task-count / partition-count ratio
                parts = sources[inp.source_id]
                srcs.extend(parts[t.partition::t.stage_spec.tasks])
        a = ComputeActor(
            t, compiled[t.stage], {}, chan_by_id, srcs,
            collector_id,
            spiller=Spiller(mem_quota_bytes=spill_quota_bytes,
                            prefix=f"spill/task{t.task_id}"),
            window=window,
        )
        sys_i = systems[i % len(systems)]
        actor_of_task[t.task_id] = sys_i.register(a)
        actors.append(a)
    for a in actors:
        for ch in a.task.output_channels:
            a.channel_targets[ch] = actor_of_task[chan_by_id[ch].dst_task]
    sys_by_node = {s.node: s for s in systems}
    for t in tasks:
        aid = actor_of_task[t.task_id]
        sys_by_node[aid.node].send(aid, StartTask())

    if hasattr(runtime, "dispatch"):
        runtime.dispatch()
    else:
        runtime.run()
    if not collector.done:
        raise RuntimeError("stage graph did not complete")
    return collector.table()
