"""DQ stage/task/channel graph model.

Mirror of the reference's distributed-query task model (dq_tasks.proto:71-
207; SURVEY.md §2.10): a query phase is a DAG of *stages*; each stage runs
N parallel *tasks* hosting a program; tasks connect through *channels*
with partitioned (HashPartition), broadcast, or merge-less (UnionAll)
routing, with credit-based flow control between compute actors.

TPU-era position: when all stages fit one SPMD program the mesh executor
(ydb_tpu.parallel.MeshScan) fuses them — channels become collectives.
This layer is the general form: host-mediated streaming between compiled
device programs, for plans that don't fuse (multi-phase queries, sources
of different shapes, cross-pod DCN hops).
"""

from __future__ import annotations

import dataclasses

from ydb_tpu.ssa.program import Program


@dataclasses.dataclass(frozen=True)
class SourceInput:
    """Stage reads partitioned table data; task p of an N-task stage reads
    partitions p, p+N, p+2N, … so every partition is read exactly once for
    any task-count / partition-count ratio."""

    source_id: str


@dataclasses.dataclass(frozen=True)
class UnionAllInput:
    """Stage consumes every output channel of an upstream stage."""

    from_stage: int


@dataclasses.dataclass(frozen=True)
class HashPartition:
    keys: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Broadcast:
    pass


@dataclasses.dataclass(frozen=True)
class UnionAll:
    """Route every block to the consumer task (consumer stage has 1 task
    or doesn't care which task receives)."""


@dataclasses.dataclass(frozen=True)
class ResultOutput:
    pass


@dataclasses.dataclass(frozen=True)
class JoinSpec:
    """A join stage's operator: input 0 is the probe side, input 1 the
    build side; both arrive hash-partitioned on their join keys so each
    task joins its bucket device-locally (the GraceJoin shape,
    mkql_grace_join.cpp:558 — ICI/channels as the spill fabric)."""

    probe_keys: tuple[str, ...]
    build_keys: tuple[str, ...]
    payload: tuple[str, ...] = ()          # lookup join: build columns
    probe_payload: tuple[str, ...] = ()    # expand join
    build_payload: tuple[str, ...] = ()
    kind: str = "inner"  # inner | left | semi | anti (expand: inner|left)
    suffix: str = ""
    expand: bool = False  # N:M expansion vs N:1 lookup
    fanout_hint: float = 4.0  # expand: initial output capacity multiple


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One stage: per-block ``program`` (map/partial phase), optional
    ``final_program`` applied to the accumulated inputs (aggregate merge),
    optional ``join`` operator (two inputs: probe, build), input wiring,
    output routing and task parallelism."""

    program: Program | None
    inputs: tuple
    output: object
    tasks: int = 1
    final_program: Program | None = None
    join: JoinSpec | None = None
    # (renamed col -> dictionary source col) for program compilation
    dict_aliases: tuple[tuple[str, str], ...] = ()


@dataclasses.dataclass
class TaskSpec:
    task_id: int
    stage: int
    stage_spec: StageSpec
    partition: int
    # channel wiring filled by build_tasks
    input_channels: list[int] = dataclasses.field(default_factory=list)
    output_channels: list[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ChannelSpec:
    channel_id: int
    src_task: int
    dst_task: int
    # routing metadata: dst index within the consumer stage's task set —
    # hash slot p of a HashPartition output goes to the dst with
    # dst_index == p (consumer groups sort by this)
    dst_index: int
    # consumer edge: a producer feeding several consumer edges routes
    # each edge's channel group independently (full stream to each);
    # two edges from the same pair of stages stay distinct via
    # input_index (the edge's position in the consumer's inputs)
    dst_stage: int
    input_index: int = 0


def build_tasks(
    stages: list[StageSpec],
) -> tuple[list[TaskSpec], list[ChannelSpec], int]:
    """Expand stages into tasks + channels.

    Returns (tasks, channels, result_stage). The result stage must have
    exactly one task with ResultOutput.
    (reference: task graph construction kqp_tasks_graph.cpp:448,778)
    """
    tasks: list[TaskSpec] = []
    channels: list[ChannelSpec] = []
    stage_tasks: list[list[int]] = []
    next_channel = 0
    result_stage = -1
    for si, spec in enumerate(stages):
        ids = []
        for p in range(spec.tasks):
            t = TaskSpec(len(tasks), si, spec, p)
            ids.append(t.task_id)
            tasks.append(t)
        stage_tasks.append(ids)
        if isinstance(spec.output, ResultOutput):
            if result_stage >= 0 or spec.tasks != 1:
                raise ValueError("exactly one single-task result stage")
            result_stage = si
    if result_stage < 0:
        raise ValueError("no result stage")

    for si, spec in enumerate(stages):
        for ei, inp in enumerate(spec.inputs):
            if isinstance(inp, SourceInput):
                continue
            if not isinstance(inp, UnionAllInput):
                raise ValueError(inp)
            up = inp.from_stage
            up_spec = stages[up]
            consumers = stage_tasks[si]
            for src in stage_tasks[up]:
                for di, dst in enumerate(consumers):
                    ch = ChannelSpec(next_channel, src, dst, di, si, ei)
                    next_channel += 1
                    channels.append(ch)
                    tasks[src].output_channels.append(ch.channel_id)
                    tasks[dst].input_channels.append(ch.channel_id)
            if isinstance(up_spec.output, UnionAll) and len(consumers) != 1:
                raise ValueError("UnionAll output needs 1 consumer task")
    return tasks, channels, result_stage
