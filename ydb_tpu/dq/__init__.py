from ydb_tpu.dq.graph import (  # noqa: F401
    HashPartition,
    ResultOutput,
    SourceInput,
    StageSpec,
    UnionAllInput,
    build_tasks,
)
from ydb_tpu.dq.compute import run_stage_graph  # noqa: F401
