"""DQ node service + cross-process executer: the data plane on the wire.

The reference starts query tasks on remote nodes through a node service
(TEvStartKqpTasksRequest -> per-task compute actors,
kqp_node_service.cpp:55,121) and the executer wires channels between
compute actors on different nodes; channel traffic (TEvChannelData /
Ack) then flows peer-to-peer over the interconnect
(dq_compute_actor_channels.h:15). This module is that shape for the TPU
build:

  * ``DqNodeService`` — an actor registered on every worker node. On
    ``StartTasks`` it re-derives the compiled stage chain from the
    shipped stage specs + source schemas (compile_stages — schemas only,
    no data) and registers one ComputeActor per task, replying
    ``TasksStarted`` with their ActorIds.
  * ``DistExecuter`` — builds the task graph, places stages on nodes
    (scan stages stay where the data lives), starts remote tasks via the
    services, then two-phase-wires every channel: consumer ActorIds ship
    in ``WireTask`` once all registrations are back, so ChannelData
    crosses process boundaries transparently through the interconnect's
    remote transport. Credit flow (seq/ack windows) is preserved across
    the TCP hop because acks travel the same wire back.

Failure semantics: a dead peer surfaces as ``Undelivered`` on the
sender's channel data -> the ComputeActor sends ``QueryAborted`` to the
collector -> ``DistExecuter.run`` raises with the reason instead of
hanging (the TEvAbortExecution contract, dq_compute_actor.h:41).
"""

from __future__ import annotations

import dataclasses
import time

from ydb_tpu import dtypes
from ydb_tpu.dq.compute import (
    ComputeActor,
    QueryAborted,
    ResultCollector,
    StartTask,
    WireTask,
    compile_stages,
    task_partitions,
)
from ydb_tpu.dq.graph import SourceInput, StageSpec, build_tasks
from ydb_tpu.dq.spilling import Spiller
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.runtime.actors import Actor, ActorId


@dataclasses.dataclass
class StartTasks:
    """Start these tasks on the receiving node (kqp_node_service.cpp:55).

    ``stages`` is the FULL stage list (specs are tiny); the service
    compiles the chain locally from ``source_schemas`` — table data
    never ships, only programs and schemas. ``sources`` optionally
    carries host-resident partitions for scan tasks placed remotely."""

    query_id: str
    stages: list[StageSpec]
    tasks: list  # TaskSpec
    channels: list  # ChannelSpec (full list; tasks index into it)
    source_schemas: dict[str, dtypes.Schema]
    dicts: object = None
    key_spaces: dict | None = None
    block_rows: int = 1 << 16
    sources: dict[str, list] | None = None  # source_id -> partitions
    reply_to: ActorId | None = None
    # executer's address book: node id -> (host, port). Worker-to-worker
    # channels need routes the hello handshake alone cannot teach (a
    # worker only learns the EXECUTER's reverse route) — the reference
    # solves this with the nameservice table; here the executer ships it
    peers: dict[int, tuple] | None = None


@dataclasses.dataclass
class TasksStarted:
    query_id: str
    actor_of_task: dict[int, ActorId]


@dataclasses.dataclass
class ReleaseQuery:
    """Stop + deregister a query's compute actors on this node."""

    query_id: str


@dataclasses.dataclass
class Ping:
    """Liveness probe. Sent by the executer with the collector as the
    SENDER: a dead peer turns the ping into an Undelivered notification
    delivered straight to the collector, which fails the query — so a
    worker death is detected even when no channel data is in flight
    (the NodeDisconnected subscription the reference's executer holds
    on the interconnect session)."""


class DqNodeService(Actor):
    """Per-node task host (kqp_node_service.cpp:55). Set
    ``interconnect`` after construction so shipped peer routes
    (StartTasks.peers) reach the node's transport."""

    def __init__(self, interconnect=None):
        super().__init__()
        # query id -> [(actor id, actor)]: the actor ref is kept so
        # ReleaseQuery can close each task's spiller — stopping the
        # actor alone strands its spilled blobs in the store
        self._queries: dict[str, list[tuple[ActorId, object]]] = {}
        self.interconnect = interconnect
        # compiled stages repeat across queries (prepared statements):
        # memoize like the executer side does
        self._compile_cache: dict = {}

    def receive(self, message, sender):
        from ydb_tpu.runtime.interconnect import Undelivered

        if isinstance(message, StartTasks):
            self._start(message, sender)
        elif isinstance(message, ReleaseQuery):
            for aid, actor in self._queries.pop(message.query_id, []):
                self.system.stop(aid)
                actor.spiller.close()
        elif isinstance(message, Ping):
            pass  # liveness: delivery (vs Undelivered) is the signal
        elif isinstance(message, Undelivered):
            # a reply (TasksStarted) bounced — the executer died. The
            # worker must survive one peer's death (other queries keep
            # running); the executer's own failure handling owns cleanup
            pass
        else:
            raise TypeError(message)

    def _start(self, req: StartTasks, sender):
        if req.peers and self.interconnect is not None:
            for node, addr in req.peers.items():
                if node != self.system.node:
                    self.interconnect.add_peer(node, addr[0], addr[1])
        compiled = compile_stages(req.stages, req.source_schemas,
                                  req.dicts, req.key_spaces,
                                  compile_cache=self._compile_cache)
        chan_by_id = {c.channel_id: c for c in req.channels}
        out: dict[int, ActorId] = {}
        mine: list[tuple[ActorId, object]] = []
        for t in req.tasks:
            srcs = task_partitions(req.sources or {}, t)
            a = ComputeActor(
                t, compiled[t.stage], {}, chan_by_id, srcs,
                result_target=None,
                spiller=Spiller(prefix=f"spill/{req.query_id}"
                                       f"/task{t.task_id}"),
                block_rows=req.block_rows,
            )
            aid = self.system.register(a)
            out[t.task_id] = aid
            mine.append((aid, a))
        self._queries[req.query_id] = mine
        self.send(req.reply_to if req.reply_to is not None else sender,
                  TasksStarted(req.query_id, out))


class DistExecuter:
    """Cross-node query executer (kqp_executer_impl.h:120 shape).

    ``services`` maps remote node id -> DqNodeService ActorId; stages
    whose placement maps to this node run in-process. The caller owns
    pumping the local system/interconnect; ``run`` drives it via the
    supplied ``pump`` callable (defaults to draining the local system)."""

    def __init__(self, system, services: dict[int, ActorId] | None = None,
                 pump=None, peers: dict[int, tuple] | None = None):
        self.system = system
        self.services = dict(services or {})
        self._pump = pump if pump is not None else self._pump_local
        # node id -> (host, port); shipped to workers so worker-to-worker
        # channels have routes (see StartTasks.peers)
        self.peers = dict(peers or {})
        self._compile_cache: dict = {}
        self._seq = 0

    def _pump_local(self):
        self.system.run()
        time.sleep(0.002)

    def run(
        self,
        stages: list[StageSpec],
        sources: dict[str, list],
        placement: dict[int, int] | None = None,
        dicts=None,
        key_spaces=None,
        block_rows: int = 1 << 16,
        timeout: float = 120.0,
    ) -> OracleTable:
        """Execute a stage graph with stages placed across nodes.

        ``placement`` maps stage index -> node id (default: everything
        local). Scan stages must be placed where their partitions are
        reachable; this executer ships host-resident partitions of
        remotely-placed scan stages in StartTasks."""
        self._seq += 1
        qid = f"q{self._seq}"
        local_node = self.system.node
        placement = placement or {}
        source_schemas = {sid: parts[0].schema
                          for sid, parts in sources.items() if parts}
        compiled = compile_stages(stages, source_schemas, dicts, key_spaces,
                                  compile_cache=self._compile_cache)
        tasks, channels, result_stage = build_tasks(stages)
        chan_by_id = {c.channel_id: c for c in channels}

        collector = ResultCollector(compiled[result_stage].out_schema)
        collector_id = self.system.register(collector)

        # group tasks by node
        by_node: dict[int, list] = {}
        for t in tasks:
            node = placement.get(t.stage, local_node)
            by_node.setdefault(node, []).append(t)

        actor_of_task: dict[int, ActorId] = {}
        local_actors: list[ComputeActor] = []
        started: set[str] = set()
        replies: dict[int, TasksStarted] = {}
        start_error: list[str] = []

        class _Gather(Actor):
            def receive(self, message, sender):
                from ydb_tpu.runtime.interconnect import Undelivered

                if isinstance(message, Undelivered):
                    # StartTasks or a start-phase ping bounced: the
                    # worker is gone before the query even started
                    start_error.append(
                        f"peer unreachable during start: {message.reason}")
                    return
                assert isinstance(message, TasksStarted)
                replies[sender.node] = message

        gather_id = self.system.register(_Gather())

        for node, node_tasks in by_node.items():
            if node == local_node:
                for t in node_tasks:
                    srcs = task_partitions(sources, t)
                    a = ComputeActor(
                        t, compiled[t.stage], {}, chan_by_id, srcs,
                        result_target=collector_id,
                        spiller=Spiller(prefix=f"spill/{qid}"
                                               f"/task{t.task_id}"),
                        block_rows=block_rows,
                    )
                    actor_of_task[t.task_id] = self.system.register(a)
                    local_actors.append(a)
                continue
            svc = self.services.get(node)
            if svc is None:
                raise ValueError(f"no DqNodeService for node {node}")
            remote_sources = None
            ship = {
                inp.source_id
                for t in node_tasks
                for inp in t.stage_spec.inputs
                if isinstance(inp, SourceInput)
            }
            if ship:
                remote_sources = {sid: sources[sid] for sid in ship}
            # sender=gather_id: a bounce (dead worker) comes back as
            # Undelivered to the gather actor, not a silent dead letter
            self.system.send(svc, StartTasks(
                qid, stages, node_tasks, channels, source_schemas,
                dicts, key_spaces, block_rows, remote_sources,
                reply_to=gather_id, peers=self.peers or None),
                sender=gather_id)
            started.add(node)

        deadline = time.monotonic() + timeout
        remote_nodes = set(by_node) - {local_node}
        next_ping = time.monotonic() + 0.25
        while set(replies) < remote_nodes:
            if start_error:
                raise RuntimeError(f"query aborted: {start_error[0]}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"task start timed out; missing nodes "
                    f"{sorted(remote_nodes - set(replies))}")
            now = time.monotonic()
            if now >= next_ping:
                # start-phase liveness: detect a worker that died while
                # (or before) compiling its tasks
                for node in remote_nodes - set(replies):
                    self.system.send(self.services[node], Ping(),
                                     sender=gather_id)
                next_ping = now + 0.25
            self._pump()
        for msg in replies.values():
            actor_of_task.update(msg.actor_of_task)

        # two-phase wiring: every task learns its consumers' ActorIds
        # (local AND remote), results + aborts route to the collector
        for t in tasks:
            targets = {
                ch: actor_of_task[chan_by_id[ch].dst_task]
                for ch in t.output_channels
            }
            self.system.send(actor_of_task[t.task_id], WireTask(
                targets, result_target=collector_id,
                abort_target=collector_id))
        for t in tasks:
            self.system.send(actor_of_task[t.task_id], StartTask())

        try:
            next_ping = 0.0
            while not collector.done:
                if collector.error is not None:
                    raise RuntimeError(
                        f"query aborted: {collector.error}")
                if time.monotonic() > deadline:
                    raise TimeoutError("query timed out")
                now = time.monotonic()
                if remote_nodes and now >= next_ping:
                    # liveness: a dead peer bounces the ping back to the
                    # collector as Undelivered -> query fails fast
                    for node in remote_nodes:
                        self.system.send(self.services[node], Ping(),
                                         sender=collector_id)
                    next_ping = now + 0.25
                self._pump()
            return collector.table()
        finally:
            for node in started:
                self.system.send(self.services[node], ReleaseQuery(qid))
            for a in local_actors:
                self.system.stop(a.self_id)
                a.spiller.close()
            self.system.stop(collector_id)
            self.system.stop(gather_id)
            self._pump()
