"""Channel spilling: bounded in-memory buffers overflow to blob storage.

Reference: a per-node spilling service writes channel/compute blobs to
local files under quotas (dq/actors/spilling/spilling_file.cpp,
channel_storage.cpp; SURVEY.md §2.10). Here the spiller parks serialized
blocks in the blob store when a producer's unacked backlog exceeds its
memory quota, reloading lazily when credit returns — out-of-core operation
for skewed/slow consumers (SURVEY.md §5.7).
"""

from __future__ import annotations

import io
import itertools

import numpy as np

from ydb_tpu.analysis import leaksan
from ydb_tpu.engine.blobs import BlobStore, MemBlobStore


def _encode(payload: dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **payload)
    return buf.getvalue()


def _decode(raw: bytes) -> dict[str, np.ndarray]:
    with np.load(io.BytesIO(raw)) as z:
        return {k: z[k] for k in z.files}


class Spiller:
    """Byte-budgeted FIFO of block payloads; excess spills to blobs."""

    def __init__(self, store: BlobStore | None = None,
                 mem_quota_bytes: int = 64 << 20,
                 prefix: str = "spill"):
        self.store = store if store is not None else MemBlobStore()
        self.quota = mem_quota_bytes
        self.prefix = prefix
        self._seq = itertools.count()
        self._mem: dict[int, dict] = {}
        self._spilled: set[int] = set()
        self._mem_bytes = 0
        self.spill_count = 0
        # leak-sanitizer handle per live spilled blob; empty when off
        self._leaks: dict[int, object] = {}

    @staticmethod
    def _size(payload: dict[str, np.ndarray]) -> int:
        return sum(a.nbytes for a in payload.values())

    def put(self, payload: dict[str, np.ndarray]) -> int:
        sid = next(self._seq)
        size = self._size(payload)
        if self._mem_bytes + size > self.quota:
            self.store.put(f"{self.prefix}/{sid}", _encode(payload))
            self._spilled.add(sid)
            self.spill_count += 1
            lk = leaksan.track("dq.spill", f"{self.prefix}/{sid}")
            if lk is not None:
                self._leaks[sid] = lk
        else:
            self._mem[sid] = payload
            self._mem_bytes += size
        return sid

    def peek(self, sid: int) -> dict[str, np.ndarray]:
        """Read WITHOUT consuming (checkpoint snapshots of accumulated
        state read the same ids again at finalize)."""
        if sid in self._mem:
            return self._mem[sid]
        if sid in self._spilled:
            return _decode(self.store.get(f"{self.prefix}/{sid}"))
        raise KeyError(sid)

    def get(self, sid: int) -> dict[str, np.ndarray]:
        if sid in self._mem:
            payload = self._mem.pop(sid)
            self._mem_bytes -= self._size(payload)
            return payload
        if sid in self._spilled:
            self._spilled.discard(sid)
            raw = self.store.get(f"{self.prefix}/{sid}")
            self.store.delete(f"{self.prefix}/{sid}")
            if self._leaks:
                leaksan.close(self._leaks.pop(sid, None))
            return _decode(raw)
        raise KeyError(sid)

    def close(self) -> None:
        """Delete every blob still spilled and drop buffered payloads.

        Before this, the spiller had no teardown at all: a query
        aborted (peer death, deadline cancellation) with parked or
        accumulated block ids left its spill blobs in the store
        forever — only ``get`` deleted them (lifecycle R007 / the
        ``dq.spill`` leak-sanitizer kind). Idempotent; the spiller is
        unusable for those ids afterwards, which is fine — it is
        per-task and the task is gone."""
        for sid in self._spilled:
            self.store.delete(f"{self.prefix}/{sid}")
            if self._leaks:
                leaksan.close(self._leaks.pop(sid, None))
        self._spilled.clear()
        self._mem.clear()
        self._mem_bytes = 0
