"""Distributed checkpoints for streaming dataflows.

Mirror of the reference's checkpoint machinery (SURVEY.md §5.4):
``IDqTaskRunner::Save/Load`` serialize a running task's operator state
(dq_tasks_runner.h:406-408); a checkpoint coordinator injects barriers
at the sources (fq/libs/checkpointing/checkpoint_coordinator.h:25,
InjectCheckpoint :106); compute actors align barriers across their
input channels, persist state to checkpoint storage
(fq/libs/checkpoint_storage), forward the barrier downstream, and ack.

The protocol here is the aligned-barrier snapshot: barriers ride the
data channels IN BAND (they park behind data in the credit queue, so
they can never overtake a block), a task snapshots only once barriers
arrived on every input channel — buffering post-barrier blocks from
already-aligned channels — and a checkpoint completes when every task
acked. Recovery rebuilds the graph with each task's saved state and
sources resuming from their saved positions.

State serialization is pickle over numpy payloads — the internal
storage format of OUR checkpoint store (the reference uses its own
protobuf mini-format for the same purpose).
"""

from __future__ import annotations

import dataclasses
import pickle

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.runtime.actors import Actor, ActorId


# ---- protocol messages ----

@dataclasses.dataclass
class InjectCheckpoint:
    checkpoint_id: int


@dataclasses.dataclass
class TaskCheckpointed:
    task_id: int
    checkpoint_id: int


@dataclasses.dataclass
class TriggerCheckpoint:
    pass


BARRIER_KEY = "__ckpt__"


class CheckpointStorage:
    """Task-state persistence + completion markers on a blob store."""

    def __init__(self, store: BlobStore, graph_id: str = "g"):
        self.store = store
        self.graph_id = graph_id

    def _prefix(self, checkpoint_id: int) -> str:
        return f"ckpt/{self.graph_id}/{checkpoint_id:08d}/"

    def save_task(self, checkpoint_id: int, task_id: int,
                  state: dict) -> None:
        self.store.put(self._prefix(checkpoint_id) + f"task{task_id}",
                       pickle.dumps(state))

    def load_task(self, checkpoint_id: int, task_id: int) -> dict | None:
        blob = self._prefix(checkpoint_id) + f"task{task_id}"
        if not self.store.exists(blob):
            return None
        return pickle.loads(self.store.get(blob))

    def mark_complete(self, checkpoint_id: int) -> None:
        self.store.put(self._prefix(checkpoint_id) + "COMPLETE", b"1")

    def latest_complete(self) -> int | None:
        best = None
        for blob in self.store.list(f"ckpt/{self.graph_id}/"):
            if blob.endswith("/COMPLETE"):
                cid = int(blob.split("/")[-2])
                best = cid if best is None else max(best, cid)
        return best

    def drop_incomplete(self) -> None:
        """GC checkpoints that never completed (crash mid-snapshot)."""
        complete = set()
        for blob in self.store.list(f"ckpt/{self.graph_id}/"):
            if blob.endswith("/COMPLETE"):
                complete.add(blob.rsplit("/", 1)[0])
        for blob in list(self.store.list(f"ckpt/{self.graph_id}/")):
            if blob.rsplit("/", 1)[0] not in complete:
                self.store.delete(blob)


class CheckpointCoordinator(Actor):
    """Injects barriers at source tasks, collects acks, marks complete
    (checkpoint_coordinator.h shape)."""

    def __init__(self, storage: CheckpointStorage,
                 source_tasks: list[ActorId], n_tasks: int,
                 start_id: int = 0):
        super().__init__()
        self.storage = storage
        self.source_tasks = list(source_tasks)
        self.n_tasks = n_tasks
        self.next_id = start_id + 1
        self.pending: dict[int, set] = {}   # ckpt id -> acked task ids
        self.completed: list[int] = []

    def receive(self, message, sender):
        if isinstance(message, TriggerCheckpoint):
            cid = self.next_id
            self.next_id += 1
            self.pending[cid] = set()
            for aid in self.source_tasks:
                self.send(aid, InjectCheckpoint(cid))
        elif isinstance(message, TaskCheckpointed):
            acked = self.pending.get(message.checkpoint_id)
            if acked is None:
                return
            acked.add(message.task_id)
            if len(acked) >= self.n_tasks:
                del self.pending[message.checkpoint_id]
                self.storage.mark_complete(message.checkpoint_id)
                self.completed.append(message.checkpoint_id)
