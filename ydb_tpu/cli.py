"""Command-line interface: `python -m ydb_tpu.cli <command>`.

Mirror of the reference's `ydb` tool (apps/ydb, public/lib/ydb_cli;
SURVEY.md layer 9): server mode, interactive SQL, scheme browsing,
topic read/write, and workload benchmark runners.

Commands:
  serve     --data-dir D [--port P] [--auth-token T]   run a node
            [--pg-port P] [--kafka-port P]             wire-compat fronts
  sql       -e ENDPOINT "SELECT ..."                   run a query
  scheme ls -e ENDPOINT [PATH]                         list a directory
  scheme describe -e ENDPOINT PATH                     table metadata
  topic write|read -e ENDPOINT ...                     topic I/O
  workload tpch --sf 0.01 [--queries q1,q6]            embedded bench
"""

from __future__ import annotations

import argparse
import sys
import time


def _connect(args):
    from ydb_tpu.api.client import Driver

    return Driver(args.endpoint, auth_token=args.auth_token)


def cmd_serve(args):
    import jax

    jax.config.update("jax_platforms", args.platform)
    from ydb_tpu.api.server import make_server
    from ydb_tpu.config import AppConfig
    from ydb_tpu.engine.blobs import DirBlobStore, MemBlobStore
    from ydb_tpu.kqp.session import Cluster

    config = AppConfig()
    if args.yaml_config:
        with open(args.yaml_config) as f:
            config = AppConfig.from_yaml(f.read())
    data_dir = args.data_dir or config.data_dir
    port = args.port if args.port is not None else config.grpc_port
    store = DirBlobStore(data_dir) if data_dir else MemBlobStore()
    cluster = Cluster(store=store, config=config)
    tokens = set(config.auth_tokens) or None
    if args.auth_token:
        tokens = (tokens or set()) | {args.auth_token}
    server, port = make_server(cluster, port=port, auth_tokens=tokens)
    server.start()
    extra_fronts = []
    if args.pg_port is not None:
        from ydb_tpu.api.pgwire import PgWireServer

        pg = PgWireServer(cluster, port=args.pg_port,
                          auth_tokens=tokens,
                          lock=server.request_proxy.lock).start()
        extra_fronts.append(pg)
        print(f"pgwire listening on 127.0.0.1:{pg.port}", flush=True)
    if args.kafka_port is not None:
        from ydb_tpu.api.kafka import KafkaServer

        kf = KafkaServer(cluster, port=args.kafka_port,
                         auth_tokens=tokens,
                         lock=server.request_proxy.lock).start()
        extra_fronts.append(kf)
        print(f"kafka listening on 127.0.0.1:{kf.port}", flush=True)
    if args.mon_port is not None:
        from ydb_tpu.obs.viewer import Viewer

        mon = Viewer(cluster, port=args.mon_port, auth_tokens=tokens,
                     lock=server.request_proxy.lock).start()
        extra_fronts.append(mon)
        print(f"monitoring on http://127.0.0.1:{mon.port}", flush=True)
    if args.sqs_port is not None:
        from ydb_tpu.api.sqs import SqsHttpServer

        sqs = SqsHttpServer(cluster.store, port=args.sqs_port,
                            lock=server.request_proxy.lock).start()
        extra_fronts.append(sqs)
        print(f"sqs on http://127.0.0.1:{sqs.port}", flush=True)
    print(f"ydb_tpu serving on 127.0.0.1:{port}", flush=True)
    period = (args.background_period
              if args.background_period is not None
              else config.background_period_seconds)
    try:
        while True:
            time.sleep(period)
            # cluster state is single-writer: background maintenance
            # takes the same lock the RPC handlers serialize on
            with server.request_proxy.lock:
                cluster.run_background()
    except KeyboardInterrupt:
        for front in extra_fronts:
            front.stop()
        server.stop(1)


def cmd_sql(args):
    driver = _connect(args)
    q = driver.query_client()
    t0 = time.monotonic()
    out = q.execute(args.query)
    dt = time.monotonic() - t0
    import pyarrow as pa

    if isinstance(out, str):  # EXPLAIN: the rendered plan
        print(out)
    elif isinstance(out, pa.Table):
        print(out.to_pandas().to_string(index=False))
        print(f"-- {out.num_rows} rows in {dt:.3f}s", file=sys.stderr)
    else:
        step, committed = out
        print(f"-- {'committed' if committed else 'FAILED'} at step "
              f"{step} in {dt:.3f}s", file=sys.stderr)
    driver.close()


def cmd_scheme(args):
    driver = _connect(args)
    sc = driver.scheme_client()
    if args.scheme_cmd == "ls":
        for path, kind in sc.list_directory(args.path):
            print(f"{kind:8} {path}")
    else:
        d = sc.describe_table(args.path)
        print(f"table {d.path}  store={d.store}  shards={d.shards}  "
              f"version={d.schema_version}")
        for c in d.columns:
            null = "" if c.nullable else " NOT NULL"
            pk = " (pk)" if c.name in d.primary_key else ""
            print(f"  {c.name:24} {c.type}{null}{pk}")
    driver.close()


def cmd_topic(args):
    driver = _connect(args)
    tc = driver.topic_client()
    if args.topic_cmd == "write":
        p, off = tc.write(args.topic, args.data, key=args.key or "")
        print(f"partition {p} offset {off}")
    else:
        msgs = tc.read(args.topic, args.consumer, args.limit)
        for p, off, data in msgs:
            print(f"[{p}:{off}] {data.decode(errors='replace')}")
        if msgs and args.commit:
            tops = {}
            for p, off, _ in msgs:
                tops[p] = max(tops.get(p, -1), off)
            for p, off in tops.items():
                tc.commit(args.topic, args.consumer, p, off)
    driver.close()


def _run_workload(args, run, **kwargs):
    import jax

    jax.config.update("jax_platforms", args.platform)
    queries = args.queries.split(",") if args.queries else None
    results = run(queries=queries, iterations=args.iterations, **kwargs)
    for name, seconds, rows in results:
        print(f"{name:6} {seconds * 1000:9.1f} ms   {rows} rows")


def cmd_workload(args):
    from ydb_tpu.workload.runner import run_tpch

    _run_workload(args, run_tpch, sf=args.sf)


def cmd_clickbench(args):
    from ydb_tpu.workload.clickbench import run_clickbench

    _run_workload(args, run_clickbench, rows=args.rows,
                  verify=not args.no_verify)


def cmd_tpcds(args):
    from ydb_tpu.workload.tpcds import run_tpcds

    _run_workload(args, run_tpcds, sf=args.sf,
                  verify=not args.no_verify)


def cmd_loadtest(args):
    import jax

    jax.config.update("jax_platforms", args.platform)
    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.obs.loadtest import LoadService

    svc = LoadService(Cluster())
    r = svc.run(args.kind, requests=args.requests)
    print(f"{r['kind']:12} {r['requests']} reqs  {r['errors']} errors  "
          f"{r['rps']} rps  p50={r['p50_ms']}ms p99={r['p99_ms']}ms")


def main(argv=None):
    ap = argparse.ArgumentParser(prog="ydb_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def add_conn(p):
        p.add_argument("-e", "--endpoint", default="127.0.0.1:2136")
        p.add_argument("--auth-token", default=None)

    p = sub.add_parser("serve")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--auth-token", default=None)
    p.add_argument("--platform", default="cpu")
    p.add_argument("--background-period", type=float, default=None)
    p.add_argument("--yaml-config", default=None)
    p.add_argument("--pg-port", type=int, default=None,
                   help="also listen for PostgreSQL clients (0=auto)")
    p.add_argument("--kafka-port", type=int, default=None,
                   help="also listen for Kafka clients (0=auto)")
    p.add_argument("--mon-port", type=int, default=None,
                   help="monitoring HTTP endpoint (0=auto)")
    p.add_argument("--sqs-port", type=int, default=None,
                   help="SQS-compatible queue HTTP endpoint (0=auto)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("sql")
    add_conn(p)
    p.add_argument("query")
    p.set_defaults(fn=cmd_sql)

    p = sub.add_parser("scheme")
    ssub = p.add_subparsers(dest="scheme_cmd", required=True)
    pls = ssub.add_parser("ls")
    add_conn(pls)
    pls.add_argument("path", nargs="?", default="/")
    pls.set_defaults(fn=cmd_scheme)
    pd = ssub.add_parser("describe")
    add_conn(pd)
    pd.add_argument("path")
    pd.set_defaults(fn=cmd_scheme)

    p = sub.add_parser("topic")
    tsub = p.add_subparsers(dest="topic_cmd", required=True)
    tw = tsub.add_parser("write")
    add_conn(tw)
    tw.add_argument("topic")
    tw.add_argument("data")
    tw.add_argument("--key", default=None)
    tw.set_defaults(fn=cmd_topic)
    tr = tsub.add_parser("read")
    add_conn(tr)
    tr.add_argument("topic")
    tr.add_argument("--consumer", default="cli")
    tr.add_argument("--limit", type=int, default=20)
    tr.add_argument("--commit", action="store_true")
    tr.set_defaults(fn=cmd_topic)

    p = sub.add_parser("workload")
    wsub = p.add_subparsers(dest="workload_cmd", required=True)
    wt = wsub.add_parser("tpch")
    wt.add_argument("--sf", type=float, default=0.01)
    wt.add_argument("--queries", default=None)
    wt.add_argument("--iterations", type=int, default=1)
    wt.add_argument("--platform", default="cpu")
    wt.set_defaults(fn=cmd_workload)
    wc = wsub.add_parser("clickbench")
    wc.add_argument("--rows", type=int, default=100_000)
    wc.add_argument("--queries", default=None)
    wc.add_argument("--iterations", type=int, default=1)
    wc.add_argument("--platform", default="cpu")
    wc.add_argument("--no-verify", action="store_true")
    wc.set_defaults(fn=cmd_clickbench)
    wd = wsub.add_parser("tpcds")
    wd.add_argument("--sf", type=float, default=0.002)
    wd.add_argument("--queries", default=None)
    wd.add_argument("--iterations", type=int, default=1)
    wd.add_argument("--platform", default="cpu")
    wd.add_argument("--no-verify", action="store_true")
    wd.set_defaults(fn=cmd_tpcds)
    wl = wsub.add_parser("load")
    wl.add_argument("--kind", default="kv_upsert",
                    choices=["kv_upsert", "select", "storage_put"])
    wl.add_argument("--requests", type=int, default=100)
    wl.add_argument("--platform", default="cpu")
    wl.set_defaults(fn=cmd_loadtest)

    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
