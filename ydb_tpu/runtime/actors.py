"""Host control-plane actor runtime.

The reference builds everything on a C++ actor system: mailboxes, location
transparency, timers (IActor actor.h:345, TActorSystem actorsystem.h:133;
SURVEY.md §2.2). In the TPU split the *data* plane is XLA collectives
(ydb_tpu.parallel); this module is the remaining *control* plane: a small,
dependency-free actor layer used by DQ compute actors, shard services and
the API front.

Design choices:
  * cooperative single-threaded scheduling (an explicit run loop, not
    asyncio): messages deliver in deterministic FIFO order per mailbox,
    which makes the simulated test runtime (§4 tier 2) and the production
    runtime THE SAME code — tests swap the clock and add interceptors
    rather than using a different engine
  * location transparency: ActorId carries a node id; cross-node sends go
    through a pluggable transport (in-process loopback by default, the
    wire transport in ydb_tpu.api), invisible to the sender
  * timers ride the same queue via a schedule heap against the runtime's
    clock — virtual in tests (AdvanceCurrentTime analog,
    testlib/test_runtime.h:258)
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import time
from collections import deque
from typing import Any, Callable


@dataclasses.dataclass(frozen=True)
class ActorId:
    node: int
    local: int

    def __str__(self):
        return f"[{self.node}:{self.local}]"


@dataclasses.dataclass
class Envelope:
    target: ActorId
    sender: ActorId | None
    message: Any
    seq: int = 0


class Actor:
    """Base actor: override receive(). Lifecycle: registered -> receive()
    per message -> passivated via system.stop()."""

    def __init__(self):
        self.system: "ActorSystem" = None  # set on register
        self.self_id: ActorId = None

    def on_start(self) -> None:
        pass

    def receive(self, message: Any, sender: ActorId | None) -> None:
        raise NotImplementedError

    # convenience
    def send(self, target: ActorId, message: Any) -> None:
        self.system.send(target, message, sender=self.self_id)

    def schedule(self, delay: float, message: Any) -> None:
        self.system.schedule(delay, self.self_id, message,
                             sender=self.self_id)


class ActorSystem:
    """One 'node' worth of actors with a deterministic run loop.

    ``interceptor``: optional fn(Envelope) -> bool; return False to drop
    the message (the event-observer hook the reference's TTestActorRuntime
    uses for race/failure interleaving tests, test_runtime.h:220).
    ``clock``: fn() -> float; tests install a virtual clock.
    """

    def __init__(self, node: int = 1, clock: Callable[[], float] | None = None):
        self.node = node
        self._actors: dict[int, Actor] = {}
        self._next_local = itertools.count(1)
        self._queue: deque[Envelope] = deque()
        self._timers: list = []  # (fire_at, seq, Envelope)
        self._seq = itertools.count()
        self._clock = clock or time.monotonic
        self.interceptor: Callable[[Envelope], bool] | None = None
        self._remote_send: Callable[[Envelope], None] | None = None
        self.dead_letters: list[Envelope] = []

    # ---- registration ----

    def register(self, actor: Actor) -> ActorId:
        aid = ActorId(self.node, next(self._next_local))
        actor.system = self
        actor.self_id = aid
        self._actors[aid.local] = actor
        actor.on_start()
        return aid

    def stop(self, aid: ActorId) -> None:
        self._actors.pop(aid.local, None)

    def actor(self, aid: ActorId) -> Actor | None:
        return self._actors.get(aid.local)

    # ---- messaging ----

    def send(self, target: ActorId, message: Any,
             sender: ActorId | None = None) -> None:
        env = Envelope(target, sender, message, next(self._seq))
        if target.node != self.node:
            if self._remote_send is None:
                self.dead_letters.append(env)
                return
            self._remote_send(env)
            return
        self._queue.append(env)

    def set_remote_transport(self, fn: Callable[[Envelope], None]) -> None:
        self._remote_send = fn

    def inject(self, env: Envelope) -> None:
        """Entry point for messages arriving from another node."""
        self._queue.append(env)

    def schedule(self, delay: float, target: ActorId, message: Any,
                 sender: ActorId | None = None) -> None:
        env = Envelope(target, sender, message, next(self._seq))
        heapq.heappush(self._timers, (self._clock() + delay, env.seq, env))

    # ---- run loop ----

    def _fire_due_timers(self) -> None:
        now = self._clock()
        while self._timers and self._timers[0][0] <= now:
            _, _, env = heapq.heappop(self._timers)
            self._queue.append(env)

    def step(self) -> bool:
        """Deliver one message. Returns False when idle."""
        self._fire_due_timers()
        if not self._queue:
            return False
        env = self._queue.popleft()
        if self.interceptor is not None and not self.interceptor(env):
            return True  # intercepted/dropped
        actor = self._actors.get(env.target.local)
        if actor is None:
            self.dead_letters.append(env)
            return True
        actor.receive(env.message, env.sender)
        return True

    def run(self, max_steps: int = 1_000_000) -> int:
        """Drain until idle (all mailboxes empty, no due timers)."""
        steps = 0
        while steps < max_steps and self.step():
            steps += 1
        return steps

    def pending(self) -> int:
        return len(self._queue)

    def has_timers(self) -> bool:
        return bool(self._timers)

    def next_timer_at(self) -> float | None:
        return self._timers[0][0] if self._timers else None
