"""Quoter service: hierarchical token-bucket rate limiting.

Reference: the kesus-backed quoter service
(ydb/core/quoter/quoter_service.cpp; rate-limiter API SURVEY §2.14).
Resources form a path hierarchy ("account/queries"); each node is a
token bucket with a fill rate and burst ceiling, and a child consumes
from every bucket on its path (parent throttles the subtree).
"""

from __future__ import annotations

import threading
import time


class _Bucket:
    def __init__(self, rate: float, burst: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.at = None  # lazily set on first use (injectable clock)

    def refill(self, now: float) -> None:
        if self.at is None:
            self.at = now
        self.tokens = min(self.burst,
                          self.tokens + (now - self.at) * self.rate)
        self.at = now


class Quoter:
    """Token buckets keyed by resource path; consuming `amount` from
    "a/b" draws from "a" AND "a/b" (hierarchical throttling)."""

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self._buckets: dict[str, _Bucket] = {}
        self._lock = threading.Lock()

    def configure(self, path: str, rate: float,
                  burst: float | None = None) -> None:
        with self._lock:
            self._buckets[path] = _Bucket(
                rate, burst if burst is not None else rate)

    def _path_buckets(self, path: str) -> list[_Bucket]:
        parts = path.split("/")
        out = []
        for i in range(1, len(parts) + 1):
            b = self._buckets.get("/".join(parts[:i]))
            if b is not None:
                out.append(b)
        return out

    def try_acquire(self, path: str, amount: float = 1.0) -> bool:
        """All-or-nothing consume along the path; False = throttled."""
        now = self._clock()
        with self._lock:
            buckets = self._path_buckets(path)
            for b in buckets:
                b.refill(now)
            if any(b.tokens < amount for b in buckets):
                return False
            for b in buckets:
                b.tokens -= amount
            return True

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._buckets

    def describe(self, path: str) -> dict | None:
        """{"rate", "burst", "tokens"} refreshed to now, or None."""
        now = self._clock()
        with self._lock:
            b = self._buckets.get(path)
            if b is None:
                return None
            b.refill(now)
            return {"rate": b.rate, "burst": b.burst,
                    "tokens": b.tokens}

    def wait_time(self, path: str, amount: float = 1.0) -> float:
        """Seconds until `amount` could be available (0 = now)."""
        now = self._clock()
        with self._lock:
            worst = 0.0
            for b in self._path_buckets(path):
                b.refill(now)
                if b.tokens < amount and b.rate > 0:
                    worst = max(worst, (amount - b.tokens) / b.rate)
            return worst


class ThrottledError(Exception):
    """Raised by callers when a quoter rejects a request."""
