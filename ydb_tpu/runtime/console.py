"""Console dynamic config tablet + CMS maintenance permissions.

Mirror of the reference's cluster-management plane (ydb/core/cms/
console: the Console tablet stores versioned YAML configs with
selector-based overrides that nodes receive via ConfigsDispatcher
subscriptions, kikimr_services_initializers.h:474 + yaml_config.cpp;
ydb/core/cms: maintenance requests granting node-down permissions
under an availability budget; SURVEY.md §2.14 "CMS / console").

Console semantics:
  * one main YAML config, versioned; set_config with an expected
    version is compare-and-swap (lost-update protection);
  * overrides attach to selectors ({tenant: ..., node_kind: ...});
    resolve(node_attrs) deep-merges main <- each matching override in
    registration order (the reference's selector_config semantics);
  * dispatchers subscribe with node attrs and get called back with the
    merged AppConfig whenever the effective config changes.

CMS semantics: a maintenance request names a node and a duration; it
is granted while fewer than ``max_unavailable`` nodes hold active
permissions, otherwise queued and granted in order as permissions
expire/return (the availability-budget contract of cms_impl).
All state is durable (tablet WAL) — a rebooted console still knows
every version, override and outstanding permission.
"""

from __future__ import annotations

import time

import yaml

from ydb_tpu.config import AppConfig, ConfigError
from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.executor import TabletExecutor


def deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class VersionMismatch(Exception):
    pass


class Console:
    """Versioned dynamic config with selector overrides (durable)."""

    def __init__(self, store: BlobStore):
        self.executor = TabletExecutor.boot("console", store)
        self._subs: list["ConfigsDispatcher"] = []

    @property
    def version(self) -> int:
        row = self.executor.db.table("meta").get(("version",))
        return row["v"] if row else 0

    def set_config(self, yaml_text: str,
                   expected_version: int | None = None) -> int:
        AppConfig.from_yaml(yaml_text)  # strict-validate BEFORE commit

        def fn(txc):
            cur = self.version
            if expected_version is not None and cur != expected_version:
                raise VersionMismatch(
                    f"config is v{cur}, expected v{expected_version}")
            txc.put("config", ("main",), {"yaml": yaml_text})
            txc.put("meta", ("version",), {"v": cur + 1})
            return cur + 1
        v = self.executor.run(fn)
        self._notify()
        return v

    def get_config(self) -> tuple[str, int]:
        row = self.executor.db.table("config").get(("main",))
        return (row["yaml"] if row else "", self.version)

    def add_override(self, selector: dict, yaml_fragment: str) -> int:
        # validate the EFFECTIVE config before commit, like set_config:
        # a fragment with unknown keys/bad types must not durably
        # poison resolve() for matching nodes
        frag = yaml.safe_load(yaml_fragment) or {}
        if not isinstance(frag, dict):
            raise ConfigError("override fragment must be a mapping")
        main_row = self.executor.db.table("config").get(("main",))
        base = yaml.safe_load(main_row["yaml"]) if main_row else {}
        AppConfig.from_yaml(yaml.safe_dump(
            deep_merge(base or {}, frag)))

        def fn(txc):
            n = sum(1 for _ in
                    self.executor.db.table("overrides").range())
            txc.put("overrides", (n,), {
                "selector": dict(selector), "yaml": yaml_fragment})
            v = self.version + 1
            txc.put("meta", ("version",), {"v": v})
            return v
        v = self.executor.run(fn)
        self._notify()
        return v

    def resolve(self, node_attrs: dict | None = None) -> AppConfig:
        """Effective config for a node: main merged with every override
        whose selector is a subset of the node's attributes."""
        attrs = node_attrs or {}
        main_row = self.executor.db.table("config").get(("main",))
        merged = yaml.safe_load(main_row["yaml"]) if main_row else {}
        merged = merged or {}
        for (_n,), row in self.executor.db.table("overrides").range():
            if all(attrs.get(k) == v for k, v in
                   row["selector"].items()):
                frag = yaml.safe_load(row["yaml"]) or {}
                merged = deep_merge(merged, frag)
        return AppConfig.from_yaml(yaml.safe_dump(merged))

    # -- subscriptions (ConfigsDispatcher plane) --

    def subscribe(self, dispatcher: "ConfigsDispatcher") -> None:
        self._subs.append(dispatcher)
        dispatcher._deliver(self)

    def _notify(self) -> None:
        for d in self._subs:
            d._deliver(self)


class ConfigsDispatcher:
    """Per-node config subscriber: holds the node's selector attrs and
    invokes callbacks with the merged AppConfig on every change."""

    def __init__(self, node_attrs: dict | None = None):
        self.node_attrs = node_attrs or {}
        self.config: AppConfig | None = None
        self.version = -1
        self._callbacks = []

    def on_change(self, fn):
        """Register a callback; returns an unsubscribe callable so a
        component torn down before its node (pool reconfig, tests)
        detaches instead of leaking the callback — and a reference to
        itself — for the dispatcher's lifetime (lifecycle R007)."""
        self._callbacks.append(fn)
        if self.config is not None:
            fn(self.config)

        def unsubscribe() -> None:
            try:
                self._callbacks.remove(fn)
            except ValueError:  # already detached: idempotent
                pass

        return unsubscribe

    def _deliver(self, console: Console) -> None:
        v = console.version
        if v == self.version:
            return
        self.version = v
        self.config = console.resolve(self.node_attrs)
        for fn in self._callbacks:
            fn(self.config)


class Cms:
    """Maintenance permissions under an availability budget."""

    def __init__(self, store: BlobStore, max_unavailable: int = 1,
                 now=time.time):
        self.executor = TabletExecutor.boot("cms", store)
        self.max_unavailable = max_unavailable
        self.now = now

    def _active(self, now: float) -> list[int]:
        return [nid for (nid,), row in
                self.executor.db.table("permissions").range()
                if row["deadline"] > now]

    def _grant_queued(self, txc, now: float,
                      exclude: frozenset = frozenset()
                      ) -> tuple[list[int], int]:
        """Drop expired/excluded permissions, then grant queued
        requests FIFO while the availability budget allows. Returns
        (granted node ids, resulting active count). Shared by
        request()/done()/tick() so queue order is honored no matter
        HOW budget frees up (return or expiry). All counting is done
        against the committed view plus this tx's own effects, since
        in-tx reads do not see in-tx writes."""
        perms = list(self.executor.db.table("permissions").range())
        active = [nid for (nid,), row in perms
                  if row["deadline"] > now and nid not in exclude]
        for (nid,), row in perms:
            if row["deadline"] <= now or nid in exclude:
                txc.erase("permissions", (nid,))
        granted = []
        for (qn,), row in list(self.executor.db.table("queue").range()):
            if len(active) + len(granted) >= self.max_unavailable:
                break
            txc.erase("queue", (qn,))
            txc.put("permissions", (row["node"],), {
                "action": row["action"],
                "deadline": now + row["duration"],
            })
            granted.append(row["node"])
        return granted, len(active) + len(granted)

    def request(self, node_id: int, action: str = "restart",
                duration_s: float = 600.0) -> bool:
        """True = permission granted now; False = queued. Earlier
        queued requests are served first — a fresh request cannot jump
        a queue that freed-up budget could satisfy."""
        def fn(txc):
            now = self.now()
            if node_id in self._active(now):
                return True  # already permitted
            granted, active_n = self._grant_queued(txc, now)
            if node_id in granted:
                return True
            # already queued: keep the original position, no duplicate
            for (_qn,), row in self.executor.db.table("queue").range():
                if row["node"] == node_id:
                    return False
            q_committed = sum(
                1 for _ in self.executor.db.table("queue").range())
            still_queued = q_committed - len(granted)
            if active_n < self.max_unavailable and still_queued == 0:
                txc.put("permissions", (node_id,), {
                    "action": action,
                    "deadline": now + duration_s,
                })
                return True
            # FIFO key from a monotonic counter: a count-based key
            # would sort fresh entries before older surviving ones
            seq_row = self.executor.db.table("meta").get(("queue_seq",))
            seq = seq_row["v"] if seq_row else 0
            txc.put("meta", ("queue_seq",), {"v": seq + 1})
            txc.put("queue", (seq,), {
                "node": node_id, "action": action,
                "duration": duration_s,
            })
            return False
        return self.executor.run(fn)

    def tick(self, now: float | None = None) -> list[int]:
        """Expire lapsed permissions and grant queued requests FIFO."""
        now = self.now() if now is None else now
        return self.executor.run(
            lambda txc: self._grant_queued(txc, now)[0])

    def done(self, node_id: int) -> list[int]:
        """Return a permission; grants queued requests that now fit."""
        def fn(txc):
            return self._grant_queued(txc, self.now(),
                                      exclude=frozenset({node_id}))[0]
        return self.executor.run(fn)

    def permitted(self, node_id: int) -> bool:
        row = self.executor.db.table("permissions").get((node_id,))
        return row is not None and row["deadline"] > self.now()
