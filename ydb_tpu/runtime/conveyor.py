"""Background-task plane: conveyor worker pool + resource broker quotas.

The reference never runs maintenance on the user path: compactions, TTL
and GC are queued as tasks with categories and quotas through the
resource broker (ydb/core/tablet/resource_broker.h) and executed by the
conveyor's worker threads (ydb/core/tx/conveyor/service/service.h:73),
with an ICSController test seam to stall/step background work
(ydb/core/tx/columnshard/hooks/abstract/abstract.h:49).

TPU-era position: background work is HOST work (blob IO, merges,
metadata) — the accelerator never blocks on it. This module provides:

  * ``ResourceBroker`` — per-queue concurrency quotas under one total
  * ``Conveyor``       — worker threads draining a priority queue,
                         gated per-task by the controller
  * ``ConveyorController`` — the test seam: stall / step / resume
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import os
import threading
import time

from ydb_tpu import chaos
from ydb_tpu.analysis import leaksan, sanitizer
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.obs import timeline, tracing

#: queue-wait samples retained per queue between ``queue_stats``
#: snapshots; beyond it the extra waits still count in the totals but
#: are not individually sampled (the histograms are statistical)
WAIT_SAMPLE_CAP = 512


class ConveyorController:
    """Test hook gating task execution (ICSController analog).

    ``stall()`` blocks workers before each task body; ``step(n)`` lets
    exactly n tasks through while stalled; ``resume()`` reopens fully.
    """

    def __init__(self):
        self._open = threading.Event()
        self._open.set()
        self._steps = threading.Semaphore(0)
        self._lock = threading.Lock()

    def stall(self) -> None:
        self._open.clear()

    def resume(self) -> None:
        self._open.set()

    def step(self, n: int = 1) -> None:
        for _ in range(n):
            self._steps.release()

    def _admit(self, stop: threading.Event | None = None) -> None:
        while not self._open.is_set():
            if stop is not None and stop.is_set():
                raise _Cancelled()
            # stalled: wait for either a step token or a resume, checking
            # the gate between waits so resume() always unblocks
            if self._steps.acquire(timeout=0.02):
                return


class _Cancelled(BaseException):
    """Task admitted during shutdown: surfaced through the handle."""


class ConveyorTimeout(TimeoutError):
    """Typed conveyor timeout: a handle wait that ran out of time, a
    broker slot not granted within the task's deadline, or wait_idle
    expiring (with the still-busy queues named). Callers can now tell
    'timed out' from 'task legitimately returned None'."""


class ResourceBroker:
    """Concurrency quotas per task queue under one total (the resource
    broker's queue configuration, resource_broker.h)."""

    def __init__(self, quotas: dict[str, int] | None = None,
                 total: int | None = None):
        self.quotas = dict(quotas or {})
        self.total = total
        self._running = sanitizer.share(
            {}, f"broker.{id(self):x}.running")
        self._all = 0
        self.rejected_deadline = 0  # guarded by _lock
        self._lock = sanitizer.make_lock(f"broker.{id(self):x}.lock")
        # a Condition over the tracked lock: wait/notify release and
        # re-acquire through it, so the held-set stays exact under TSAN
        self._freed = threading.Condition(self._lock)
        # leak-sanitizer grant handles per queue (guarded by _freed);
        # empty whenever the sanitizer is off
        self._leaks: dict[str, list] = {}

    def acquire(self, queue: str,
                stop: threading.Event | None = None,
                deadline: "statement_deadline.Deadline | None" = None
                ) -> None:
        """Wait for a slot. ``deadline`` bounds the wait: a task whose
        statement budget expires while queued for admission raises
        :class:`ConveyorTimeout` instead of holding the admission path
        (chaos-delayed tasks can otherwise wedge a quota forever)."""
        with self._freed:
            while not self._may_run(queue):
                if stop is not None and stop.is_set():
                    raise _Cancelled()
                if deadline is not None:
                    remaining = deadline.remaining()
                    if remaining <= 0.0:
                        self.rejected_deadline += 1
                        raise ConveyorTimeout(
                            f"broker slot for {queue!r} not granted "
                            "within the task deadline")
                    self._freed.wait(timeout=min(remaining, 0.1))
                else:
                    self._freed.wait(timeout=0.1)
            self._running[queue] = self._running.get(queue, 0) + 1
            self._all += 1
            lk = leaksan.track("broker.slot", queue)
            if lk is not None:
                self._leaks.setdefault(queue, []).append(lk)

    def _may_run(self, queue: str) -> bool:
        if self.total is not None and self._all >= self.total:
            return False
        q = self.quotas.get(queue)
        return q is None or self._running.get(queue, 0) < q

    def release(self, queue: str) -> None:
        with self._freed:
            self._running[queue] -= 1
            self._all -= 1
            if self._leaks:
                hs = self._leaks.get(queue)
                if hs:
                    hs.pop().close()
            self._freed.notify_all()


@dataclasses.dataclass
class TaskHandle:
    queue: str
    done: threading.Event
    result: object = None
    error: BaseException | None = None
    #: statement deadline captured at submit (None = unbounded); bounds
    #: the broker admission wait on the worker
    deadline: object = None
    #: leak-sanitizer handle opened at submit, closed when done is set
    #: (None whenever the sanitizer is off)
    leak: object = None

    def wait(self, timeout: float | None = None):
        if not self.done.wait(timeout):
            raise ConveyorTimeout(
                f"background task ({self.queue}) pending after "
                f"{timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class Conveyor:
    """Worker pool for background jobs (compaction/TTL/GC off the commit
    path). Priorities: lower value first; FIFO within a priority."""

    def __init__(self, workers: int = 2,
                 broker: ResourceBroker | None = None,
                 controller: ConveyorController | None = None):
        self.broker = broker or ResourceBroker()
        self.controller = controller or ConveyorController()
        self._heap: list = []
        # heapq mutates the list at the C level, bypassing any proxy:
        # the push/pop sites carry explicit sanitizer notes instead
        self._heap_tok = sanitizer.token(f"conveyor.{id(self):x}.heap")
        self._seq = itertools.count()
        self._cv = sanitizer.make_condition(f"conveyor.{id(self):x}.cv")
        # queue telemetry, all guarded by _cv: lifetime totals, the
        # depth high-water mark since the last queue_stats() snapshot,
        # and per-queue wait-time samples drained on that cadence
        self._submitted = 0
        self._completed = 0
        self._rejected = 0
        self._max_depth = 0
        self._waits = sanitizer.share(
            {}, f"conveyor.{id(self):x}.waits")
        self._stopping = False
        self._stop_event = threading.Event()
        self._active = 0
        # per-queue running-task counts (guarded by _cv): lets wait_idle
        # name the queues that were still busy when it gave up
        self._active_q: dict[str, int] = {}
        self._threads = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(workers)
        ]
        for t in self._threads:
            t.start()

    def submit(self, queue: str, fn, *args, priority: int = 10,
               **kwargs) -> TaskHandle:
        # the submitter's active trace span AND statement deadline follow
        # the task onto the worker thread (scan prefetch producers record
        # under the query's trace id and observe its cancellation);
        # no-ops when neither is active
        fn = tracing.wrap_current(fn)
        fn = statement_deadline.wrap_current(fn)
        h = TaskHandle(queue, threading.Event(),
                       deadline=statement_deadline.current(),
                       leak=leaksan.track("conveyor.task", queue))
        with self._cv:
            if self._stopping:
                leaksan.close(h.leak)
                raise RuntimeError("conveyor is shut down")
            sanitizer.note(self._heap_tok, "heappush")
            heapq.heappush(
                self._heap,
                (priority, next(self._seq), queue, fn, args, kwargs, h,
                 time.perf_counter()))
            self._submitted += 1
            self._max_depth = max(self._max_depth, len(self._heap))
            self._cv.notify()
        return h

    def submit_if_free(self, queue: str, fn, *args,
                       **kwargs) -> TaskHandle | None:
        """Submit ONLY if an idle worker can take the task right now
        (atomic check-and-push), else None. For long-lived pipeline
        tasks (scan prefetch producers) that must never queue behind
        each other: a parked producer whose consumer is itself waiting
        on a queued producer would starve — callers degrade to a
        synchronous path instead."""
        fn = tracing.wrap_current(fn)  # trace follows the producer
        fn = statement_deadline.wrap_current(fn)  # so does the deadline
        with self._cv:
            if (self._stopping or self._heap
                    or self._active >= len(self._threads)):
                self._rejected += 1
                return None
            h = TaskHandle(queue, threading.Event(),
                           deadline=statement_deadline.current(),
                           leak=leaksan.track("conveyor.task", queue))
            sanitizer.note(self._heap_tok, "heappush")
            heapq.heappush(
                self._heap,
                (10, next(self._seq), queue, fn, args, kwargs, h,
                 time.perf_counter()))
            self._submitted += 1
            self._max_depth = max(self._max_depth, len(self._heap))
            self._cv.notify()
            return h

    def pending(self, queue: str | None = None) -> int:
        """Queued (not yet running) task count, optionally for one
        queue — promotion-backlog observability for the resident tier
        (a deep "resident_promote" backlog means HBM promotion is
        falling behind ingest)."""
        with self._cv:
            if queue is None:
                return len(self._heap)
            return sum(1 for item in self._heap if item[2] == queue)

    def queue_stats(self) -> dict:
        """Telemetry snapshot: lifetime submitted/completed/rejected
        totals, instantaneous depth/active, the depth high-water mark
        since the LAST snapshot (reset here), and the per-queue wait
        seconds sampled since then (drained here — the background
        cadence folds them into the ``component="conveyor"``
        histograms)."""
        with self._cv:
            waits = {q: list(v) for q, v in self._waits.items()}
            self._waits.clear()
            out = {
                "submitted": self._submitted,
                "completed": self._completed,
                "rejected": self._rejected,
                "rejected_deadline": self.broker.rejected_deadline,
                "depth": len(self._heap),
                "active": self._active,
                "workers": len(self._threads),
                "max_depth": self._max_depth,
                "waits": waits,
            }
            self._max_depth = len(self._heap)
        return out

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._stopping:
                    self._cv.wait()
                if self._stopping and not self._heap:
                    return
                sanitizer.note(self._heap_tok, "heappop")
                _, _, queue, fn, args, kwargs, h, t_sub = heapq.heappop(
                    self._heap)
                self._active += 1
                self._active_q[queue] = self._active_q.get(queue, 0) + 1
                t_pop = time.perf_counter()
                ws = self._waits.get(queue)
                if ws is None:
                    ws = self._waits[queue] = []
                if len(ws) < WAIT_SAMPLE_CAP:
                    ws.append(t_pop - t_sub)
            tl = timeline.timeline_enabled()
            if tl:
                timeline.RING.record(
                    f"{queue}.wait", "conveyor.wait", t_sub, t_pop,
                    args={"queue": queue})
            die = False
            try:
                try:
                    # stop-aware gates: shutdown() while the controller
                    # is stalled (or a quota is exhausted) cancels the
                    # popped task instead of wedging the worker; an
                    # expired task deadline bounds the broker wait
                    self.controller._admit(self._stop_event)
                    self.broker.acquire(queue, self._stop_event,
                                        deadline=h.deadline)
                except _Cancelled:
                    h.error = RuntimeError(
                        "conveyor shut down before the task ran")
                    continue
                except ConveyorTimeout as e:
                    h.error = e  # slot never granted: nothing to release
                    continue
                t_run = time.perf_counter() if tl else t_pop
                try:
                    fault = chaos.hit("conveyor.task", queue=queue)
                    if fault is not None:
                        fault.sleep()  # 'delay' faults are just this
                        if fault.kind == "drop":
                            raise chaos.ChaosError(
                                f"injected task drop (queue={queue})")
                        if fault.kind == "worker_death":
                            die = True
                            raise chaos.ChaosError(
                                f"injected worker death (queue={queue})")
                    h.result = fn(*args, **kwargs)
                except BaseException as e:  # surfaced via handle.wait()
                    h.error = e
                finally:
                    self.broker.release(queue)
                    if tl:
                        timeline.RING.record(
                            f"{queue}.run", "conveyor.run", t_run,
                            time.perf_counter(),
                            args={"queue": queue})
            finally:
                leaksan.close(h.leak)
                h.done.set()
                with self._cv:
                    self._active -= 1
                    self._active_q[queue] -= 1
                    self._completed += 1
                    self._cv.notify_all()
            if die:
                # the injected death kills THIS thread; the pool heals
                # by spawning a replacement before it exits
                self._respawn()
                return

    def wait_idle(self, timeout: float = 30.0) -> None:
        deadline = threading.Event()
        t = threading.Timer(timeout, deadline.set)
        t.start()
        try:
            with self._cv:
                while (self._heap or self._active) and not deadline.is_set():
                    self._cv.wait(timeout=0.05)
                if self._heap or self._active:
                    # name the stuck queues: queued items still in the
                    # heap plus tasks running right now
                    busy = sorted(
                        {item[2] for item in self._heap}
                        | {q for q, n in self._active_q.items() if n})
                    raise ConveyorTimeout(
                        f"conveyor busy after {timeout}s: "
                        f"queues={busy}")
        finally:
            t.cancel()

    def _respawn(self) -> None:
        """Replace the calling (dying) worker thread so injected worker
        deaths never shrink the pool."""
        cur = threading.current_thread()
        with self._cv:
            if self._stopping:
                return
            t = threading.Thread(target=self._worker, daemon=True)
            try:
                self._threads.remove(cur)
            except ValueError:
                pass
            self._threads.append(t)
        t.start()

    def shutdown(self, wait: bool = True) -> None:
        with self._cv:
            self._stopping = True
            self._stop_event.set()
            self._cv.notify_all()
        if wait:
            for t in self._threads:
                t.join(timeout=10)


_shared: Conveyor | None = None
_shared_lock = threading.Lock()
_stream: Conveyor | None = None
_stream_lock = threading.Lock()


def shared_conveyor() -> Conveyor:
    """Process-wide conveyor for scan prefetch/staging work.

    Before this pool every ``stream_blocks`` spun up (and tore down) its
    own ``ThreadPoolExecutor(1)`` — thread churn per scan, and no global
    bound on prefetch concurrency. The shared pool gives both: workers
    are created ONCE (YDB_TPU_CONVEYOR_WORKERS, default 4) and every
    scan's staging producer runs as a "scan_prefetch" task on them.

    A scan's producer occupies one worker for the scan's lifetime (it
    parks on a bounded queue between blocks), so the worker count bounds
    how many block streams stage CONCURRENTLY; with every worker busy,
    additional streams do NOT queue — ``submit_if_free`` turns them away
    and ``stream_blocks`` degrades to synchronous (no-prefetch) staging,
    which can never starve but loses the overlap. Never shut this
    instance down — its threads are daemons and die with the process.
    """
    global _shared
    with _shared_lock:
        if _shared is None:
            workers = int(os.environ.get("YDB_TPU_CONVEYOR_WORKERS", "4"))
            _shared = Conveyor(workers=max(1, workers))
        return _shared


def stream_conveyor() -> Conveyor:
    """Process-wide pool for morsel IO/decode tasks
    (engine.stream_sched) — deliberately SEPARATE from
    ``shared_conveyor``.

    The shared pool's workers host long-lived scan staging PRODUCERS
    that park for a scan's whole lifetime; short morsel tasks queued
    behind them could wait on workers that never free while the
    producers themselves wait on those morsels — a cycle. A dedicated
    pool breaks it structurally, and the scheduler's work stealing
    (engine.stream_sched) keeps even THIS pool's saturation from ever
    blocking a consumer: an unstarted head morsel runs inline instead.
    Unlike the shared pool, tasks here queue freely (they are finite,
    not scan-lifetime), so ``submit`` is the right admission, not
    ``submit_if_free``. YDB_TPU_STREAM_WORKERS sizes it (default 4).
    Never shut this instance down — its threads are daemons and die
    with the process."""
    global _stream
    with _stream_lock:
        if _stream is None:
            workers = int(os.environ.get("YDB_TPU_STREAM_WORKERS", "4"))
            _stream = Conveyor(workers=max(1, workers))
        return _stream
