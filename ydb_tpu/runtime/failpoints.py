"""Failpoint registry: named fault-injection points.

Mirror of the reference's failure-injection plane (datashard
failpoints datashard_failpoints.h:9; the config-driven global
failure-injection actor core/util/failure_injection.cpp; PDiskFIT's
fail-injection harness; SURVEY.md §5.3): tests arm a named point with
a trigger policy and the code path under test calls ``hit(name)`` at
the instrumented spot — firing raises (or calls a custom action)
exactly where the real fault would land.

Policies: fail always, fail the Nth hit, fail N times then recover,
probabilistic (seeded — deterministic replay). Instrumented spots so
far: blob-store put/get (FailpointBlobStore wrapper usable around any
backend), and anything else can call ``failpoints.hit`` directly.
"""

from __future__ import annotations

import random
import threading


class InjectedFault(Exception):
    """The armed failpoint fired."""


class _Point:
    def __init__(self, name, kind, arg, action, rng):
        self.name = name
        self.kind = kind
        self.arg = arg
        self.action = action
        self.rng = rng
        self.hits = 0
        self.fired = 0

    def should_fire(self) -> bool:
        self.hits += 1
        if self.kind == "always":
            return True
        if self.kind == "nth":
            return self.hits == self.arg
        if self.kind == "times":
            return self.fired < self.arg
        if self.kind == "prob":
            return self.rng.random() < self.arg
        raise ValueError(self.kind)


class Failpoints:
    """Process-wide registry (a fresh instance per test is cleaner)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._points: dict[str, _Point] = {}

    def arm(self, name: str, kind: str = "always", arg=None,
            action=None, seed: int = 0) -> None:
        """kind: always | nth (arg=N, 1-based) | times (arg=N) |
        prob (arg=p, seeded rng). ``action``: optional callable fired
        instead of raising InjectedFault. Misconfiguration fails HERE,
        at the arm site, not inside the instrumented production path."""
        if kind not in ("always", "nth", "times", "prob"):
            raise ValueError(f"unknown failpoint kind {kind!r}")
        if kind in ("nth", "times") and not isinstance(arg, int):
            raise ValueError(f"kind {kind!r} needs an integer arg")
        if kind == "prob" and not isinstance(arg, (int, float)):
            raise ValueError("kind 'prob' needs a probability arg")
        with self._lock:
            self._points[name] = _Point(
                name, kind, arg, action, random.Random(seed))

    def disarm(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._points.clear()
            else:
                self._points.pop(name, None)

    def hit(self, name: str, **ctx) -> None:
        """Call at the instrumented spot; no-op unless armed."""
        with self._lock:
            p = self._points.get(name)
            if p is None or not p.should_fire():
                return
            p.fired += 1
            action = p.action
        if action is not None:
            action(**ctx)
        else:
            raise InjectedFault(f"failpoint {name} fired")

    def stats(self, name: str) -> dict:
        with self._lock:
            p = self._points.get(name)
            return ({"hits": p.hits, "fired": p.fired}
                    if p else {"hits": 0, "fired": 0})


#: default process-wide registry (tests may build their own)
FAILPOINTS = Failpoints()


class FailpointBlobStore:
    """BlobStore wrapper arming per-op failpoints: blob.put /
    blob.get / blob.get_range / blob.delete (PDiskFIT-style storage
    fault injection around any backend, without the backend knowing).
    The wrapped store is ``base`` — the repo's wrapper convention
    (CachedBlobStore), so one-level unwraps like ColumnShard's tier
    eviction see through this wrapper."""

    def __init__(self, base, points: Failpoints | None = None):
        self.base = base
        self.points = points if points is not None else FAILPOINTS

    def put(self, blob_id: str, data: bytes) -> None:
        self.points.hit("blob.put", blob_id=blob_id)
        self.base.put(blob_id, data)

    def get(self, blob_id: str) -> bytes:
        self.points.hit("blob.get", blob_id=blob_id)
        return self.base.get(blob_id)

    def get_range(self, blob_id: str, off: int, length: int) -> bytes:
        self.points.hit("blob.get_range", blob_id=blob_id, off=off,
                        length=length)
        return self.base.get_range(blob_id, off, length)

    def delete(self, blob_id: str) -> None:
        self.points.hit("blob.delete", blob_id=blob_id)
        self.base.delete(blob_id)

    def exists(self, blob_id: str) -> bool:
        return self.base.exists(blob_id)

    def list(self, prefix: str = "") -> list[str]:
        return self.base.list(prefix)
