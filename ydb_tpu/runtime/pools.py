"""Executor pools: a multi-threaded actor runtime.

Reference: the actor system schedules mailboxes over named executor
pools of worker threads (System/User/IC/Batch pools, actorsystem.h:133;
harmonizer balancing — SURVEY §2.2 executor-pools row). The TPU build's
cooperative single-thread ActorSystem stays THE deterministic core (sim
tests == prod code); this module composes several of them into a
process-wide pooled runtime:

  * each pool is one ActorSystem driven by its own worker thread —
    actors in a pool stay single-threaded (mailbox FIFO preserved),
    pools run in parallel (blob IO / background / API separation)
  * cross-pool sends are location-transparent: ActorId.node identifies
    the pool; the remote-transport hook injects into the target pool's
    queue (GIL-atomic deque append, same contract the TCP interconnect
    relies on)
  * ``stats()`` is the harmonizer's observable: per-pool queue depths
    and delivered counts for rebalancing decisions
"""

from __future__ import annotations

import threading
import time

from ydb_tpu.runtime.actors import Actor, ActorId, ActorSystem


class ThreadedPools:
    """N executor pools, each an ActorSystem on its own thread."""

    def __init__(self, n_pools: int = 2, idle_sleep: float = 0.002):
        self.pools = [ActorSystem(node=i + 1) for i in range(n_pools)]
        self.idle_sleep = idle_sleep
        self._delivered = [0] * n_pools
        self._busy = [False] * n_pools  # inside run(): handler in flight
        self._stop = threading.Event()
        for sys_ in self.pools:
            sys_.set_remote_transport(self._route)
        self._threads = [
            threading.Thread(target=self._drive, args=(i,), daemon=True)
            for i in range(n_pools)
        ]

    # -- wiring --

    def _route(self, env) -> None:
        pool = env.target.node - 1
        if not (0 <= pool < len(self.pools)):
            self.pools[0].dead_letters.append(env)
            return
        self.pools[pool].inject(env)

    def register(self, actor: Actor, pool: int = 0) -> ActorId:
        return self.pools[pool].register(actor)

    def send(self, target: ActorId, message, sender=None) -> None:
        self._route_from(target, message, sender)

    def _route_from(self, target, message, sender) -> None:
        # enter through any pool's send so remote routing applies
        self.pools[0].send(target, message, sender=sender)

    # -- lifecycle --

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def _drive(self, i: int) -> None:
        sys_ = self.pools[i]
        while not self._stop.is_set():
            self._busy[i] = True
            steps = sys_.run()
            self._busy[i] = False
            self._delivered[i] += steps
            if steps == 0:
                time.sleep(self.idle_sleep)

    def _all_idle(self) -> bool:
        # pending counts queued envelopes; busy covers a handler that
        # popped the last one and may still produce sends
        return all(p.pending() == 0 for p in self.pools) and not any(
            self._busy)

    def drain(self, timeout: float = 10.0) -> None:
        """Block until every pool is idle (tests/shutdown barriers)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._all_idle():
                # double-check after a beat: a cross-pool send may be
                # mid-flight between queues
                time.sleep(self.idle_sleep * 2)
                if self._all_idle():
                    return
            time.sleep(self.idle_sleep)
        raise TimeoutError("pools busy")

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=5)

    def stats(self) -> list[dict]:
        """Per-pool load view (the harmonizer's input)."""
        return [
            {"pool": i + 1, "queued": p.pending(),
             "delivered": self._delivered[i]}
            for i, p in enumerate(self.pools)
        ]
