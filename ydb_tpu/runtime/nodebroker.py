"""NodeBroker: dynamic node registration + TenantPool slots.

Mirror of the reference's dynamic-node plane (ydb/core/mind/
node_broker.cpp: dynamic node ids leased with expiry, resolved by the
rest of the cluster; mind/tenant_pool.cpp: per-node slots offered to
tenants; mind/local.cpp registers the node with Hive — our LocalAgent
in tablet/hive.py already plays that part; SURVEY.md §2.5 row
"NodeBroker / Local / TenantPool").

The broker is a durable tablet: node registrations survive broker
reboot, so a restarted broker still resolves every live node. Dynamic
ids are leased: a node must extend its lease or it expires and the id
returns to the free pool (epoch-bumped so stale resolutions are
detectable). Re-registration from the same host:port inside the lease
keeps the same id — the restart-friendly contract.
"""

from __future__ import annotations

import dataclasses
import time

from ydb_tpu.engine.blobs import BlobStore
from ydb_tpu.tablet.executor import TabletExecutor


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    node_id: int
    host: str
    port: int
    tenant: str
    lease_deadline: float
    epoch: int


class NodeBroker:
    """Leased dynamic node ids over a durable tablet."""

    def __init__(self, store: BlobStore, dynamic_id_base: int = 1024,
                 lease_s: float = 60.0, now=time.time):
        self.executor = TabletExecutor.boot("nodebroker", store)
        self.base = dynamic_id_base
        self.lease_s = lease_s
        self.now = now

    def _epoch(self) -> int:
        row = self.executor.db.table("meta").get(("epoch",))
        return row["v"] if row else 1

    def register(self, host: str, port: int,
                 tenant: str = "/Root") -> NodeInfo:
        """Assign (or renew) a dynamic node id for host:port."""
        def fn(txc):
            epoch = self._epoch()
            deadline = self.now() + self.lease_s
            used = set()
            for (nid,), row in self.executor.db.table("nodes").range():
                if row["host"] == host and row["port"] == port:
                    txc.put("nodes", (nid,), dict(
                        row, deadline=deadline, tenant=tenant))
                    return NodeInfo(nid, host, port, tenant, deadline,
                                    epoch)
                used.add(nid)
            nid = self.base
            while nid in used:
                nid += 1
            txc.put("nodes", (nid,), {
                "host": host, "port": port, "tenant": tenant,
                "deadline": deadline,
            })
            return NodeInfo(nid, host, port, tenant, deadline, epoch)
        return self.executor.run(fn)

    def extend(self, node_id: int) -> float:
        def fn(txc):
            row = txc.get("nodes", (node_id,))
            if row is None:
                raise KeyError(f"no node {node_id}")
            deadline = self.now() + self.lease_s
            txc.put("nodes", (node_id,), dict(row, deadline=deadline))
            return deadline
        return self.executor.run(fn)

    def tick(self, now: float | None = None) -> list[int]:
        """Expire lapsed leases; returns the node ids that went away.
        Any expiry bumps the epoch (stale-resolution fencing)."""
        now = self.now() if now is None else now

        def fn(txc):
            dead = [nid for (nid,), row in
                    self.executor.db.table("nodes").range()
                    if row["deadline"] < now]
            for nid in dead:
                txc.erase("nodes", (nid,))
            if dead:
                txc.put("meta", ("epoch",), {"v": self._epoch() + 1})
            return dead
        return self.executor.run(fn)

    def nodes(self) -> list[NodeInfo]:
        epoch = self._epoch()
        return [
            NodeInfo(nid, row["host"], row["port"], row["tenant"],
                     row["deadline"], epoch)
            for (nid,), row in self.executor.db.table("nodes").range()
        ]

    def resolve(self, node_id: int) -> tuple[str, int]:
        row = self.executor.db.table("nodes").get((node_id,))
        if row is None:
            raise KeyError(f"no node {node_id}")
        return row["host"], row["port"]

    def connect_peers(self, interconnect) -> None:
        """Feed the live node table into an Interconnect's peer map
        (dynamic discovery replacing static add_peer wiring)."""
        for info in self.nodes():
            if info.node_id != interconnect.system.node:
                interconnect.add_peer(info.node_id, info.host,
                                      info.port)


class TenantPool:
    """Per-node compute slots offered to tenants (tenant_pool.cpp
    analog): a fixed slot budget; tenants claim/release slots; the
    assignment drives which tenants' tablets this node may host."""

    def __init__(self, slots: int = 4):
        self.slots = slots
        self.assigned: dict[str, int] = {}

    def free_slots(self) -> int:
        return self.slots - sum(self.assigned.values())

    def claim(self, tenant: str, count: int = 1) -> bool:
        if self.free_slots() < count:
            return False
        self.assigned[tenant] = self.assigned.get(tenant, 0) + count
        return True

    def release(self, tenant: str, count: int | None = None) -> None:
        have = self.assigned.get(tenant, 0)
        drop = have if count is None else min(count, have)
        if have - drop <= 0:
            self.assigned.pop(tenant, None)
        else:
            self.assigned[tenant] = have - drop

    def tenants(self) -> dict[str, int]:
        return dict(self.assigned)
