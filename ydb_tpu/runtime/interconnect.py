"""TCP interconnect between actor systems in different processes.

The reference's interconnect runs a per-peer proxy actor owning a TCP
session with handshake, reconnect and undelivered-notification semantics
(interconnect_tcp_proxy.h:20, interconnect_handshake.cpp; SURVEY.md §2.2
L2). This is the TPU build's control-plane equivalent: the ActorSystem's
pluggable remote transport (actors.py set_remote_transport) backed by
per-peer TCP sessions.

Semantics mirrored from the reference:
  * location transparency — senders address ActorId(node, local); the
    proxy routes by node id, connecting lazily on first send
  * per-peer SESSIONS with a hello handshake (node ids + session ids);
    a reconnect starts a new session
  * at-most-once delivery: on connection loss, queued/unsent envelopes
    produce ``Undelivered`` notifications back to their senders (the
    TEvUndelivered contract) — senders own retries, exactly like the
    reference's tablet pipes do on NodeDisconnected
  * frames are length-prefixed pickles — a Python↔Python wire for our
    own processes, NOT a trust boundary (the reference's interconnect
    likewise assumes a private cluster fabric; authn happens at the
    gRPC API layer, not between nodes)

Threading: reader threads inject envelopes into the target ActorSystem's
queue (deque appends are GIL-atomic against the run loop's popleft);
``pump()``/``serve()`` drive the cooperative run loop from the owner
thread.
"""

from __future__ import annotations

import dataclasses
import io
import pickle
import socket
import struct
import threading
import time
from typing import Any

from ydb_tpu import chaos
from ydb_tpu.analysis import sanitizer
from ydb_tpu.chaos.retry import RetryPolicy
from ydb_tpu.runtime.actors import ActorSystem, Envelope

_HDR = struct.Struct("!I")

# wire protocol version: gated in the hello handshake (the reference
# gates compatibility in interconnect_handshake.cpp) — a peer speaking
# a different version is REFUSED at session setup with an explicit
# reason instead of failing cryptically mid-stream on an
# unpicklable/renamed message class. Bump on incompatible changes to
# the envelope or channel message formats.
PROTOCOL_VERSION = 1


class HandshakeRejected(OSError):
    """Peer refused the session permanently (version mismatch): not a
    transient failure — no reconnect/backoff, the session closes."""


@dataclasses.dataclass
class Undelivered:
    """Returned to the sender when a cross-node envelope could not be
    handed to the peer (connection refused / lost before flush)."""

    target: object  # ActorId
    message: Any
    reason: str


def _send_frame(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


def _recv_exact(sock: socket.socket, n: int):
    buf = io.BytesIO()
    while buf.tell() < n:
        chunk = sock.recv(n - buf.tell())
        if not chunk:
            return None
        buf.write(chunk)
    return buf.getvalue()


class _Session:
    """One peer's outbound session: a dedicated sender thread drains a
    queue, so the cooperative actor loop NEVER blocks on connects or
    retries (a peer dropping SYNs stalls only this session's thread).
    Lazy connect, handshake, bounded-backoff reconnect, undelivered
    notification on final failure."""

    def __init__(self, ic: "Interconnect", peer_node: int,
                 addr: tuple[str, int]):
        import queue

        self.ic = ic
        self.peer_node = peer_node
        self.addr = addr
        self.sock: socket.socket | None = None
        self.session_id = 0
        self.lock = sanitizer.make_lock(
            f"interconnect.session.{peer_node}.{id(self):x}.lock")
        self._q: "queue.Queue" = queue.Queue()
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._sender_loop,
                                        daemon=True)
        self._thread.start()

    def send(self, env: Envelope) -> None:
        """Non-blocking enqueue (called from the actor run loop)."""
        if self._closed.is_set():
            self.ic._notify_undelivered(env, "session closed")
            return
        self._q.put(env)
        if self._closed.is_set():
            # close() may have drained BEFORE our put landed: nothing
            # will ever read the queue again, so drain it ourselves
            # (any queued envelope is equally undeliverable)
            while True:
                try:
                    stranded = self._q.get_nowait()
                except Exception:
                    break
                self.ic._notify_undelivered(stranded, "session closed")

    def _sender_loop(self) -> None:
        while not self._closed.is_set():
            try:
                env = self._q.get(timeout=0.1)
            except Exception:
                continue
            self._deliver(env)

    def _deliver(self, env: Envelope) -> None:
        # no lock across the attempt loop: the blocking work (connect,
        # sendall, backoff sleeps) runs lock-free on this sender thread,
        # so close() from another thread is never stalled behind a
        # retry storm — self.lock guards only the self.sock field
        for attempt in range(self.ic.max_retries + 1):
            if self._closed.is_set():
                break
            try:
                # chaos: 'delay' sleeps on THIS sender thread (reorder-
                # safe — one thread drains the queue in order),
                # 'disconnect' forces the reconnect+retry path below
                fault = chaos.hit("interconnect.send",
                                  peer=self.peer_node)
                if fault is not None:
                    fault.sleep()
                    if fault.kind == "disconnect":
                        raise OSError("injected peer disconnect")
                sock = self._ensure_sock()
                _send_frame(sock, ("env", env.target, env.sender,
                                   env.message))
                return
            except HandshakeRejected as e:
                # permanent: close the session so later envelopes
                # fail fast instead of re-dialing a refusing peer
                self._drop()
                self._closed.set()
                self.ic._notify_undelivered(env, str(e))
                return
            except OSError as e:
                self._drop()
                if attempt >= self.ic.max_retries:
                    self.ic._notify_undelivered(env, str(e))
                    return
                chaos.note_retry("interconnect.send", attempt + 1, e)
                time.sleep(self.ic.retry_policy.delay(attempt))
        self.ic._notify_undelivered(env, "session closed")

    def _ensure_sock(self) -> socket.socket:
        """The current socket, dialing a fresh session if none. Only
        the sender thread calls this; the lock covers the handover of
        the connected socket into self.sock against close()."""
        with self.lock:
            s = self.sock
        if s is not None:
            return s
        s = self._connect()
        with self.lock:
            if self._closed.is_set():
                s.close()
                raise OSError("session closed")
            self.sock = s
        return s

    def _connect(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.ic.timeout)
        s.settimeout(self.ic.timeout)
        self.session_id += 1
        # the hello advertises our own listen port so the peer learns the
        # reverse route from the same handshake (mutual discovery), and
        # the protocol version so incompatible peers are refused HERE
        _send_frame(s, ("hello", self.ic.node, self.session_id,
                        self.ic.port, PROTOCOL_VERSION))
        resp = _recv_frame(s)
        if isinstance(resp, tuple) and resp[0] == "reject":
            s.close()
            raise HandshakeRejected(
                f"handshake rejected by {self.addr}: {resp[1]}")
        if not (isinstance(resp, tuple) and resp[0] == "hello"):
            s.close()
            raise OSError(f"bad handshake from {self.addr}: {resp!r}")
        # the gate is MUTUAL: an old listener that accepted our hello
        # still fails here if its own version differs
        resp_ver = resp[4] if len(resp) > 4 else 0
        if resp_ver != PROTOCOL_VERSION:
            s.close()
            raise HandshakeRejected(
                f"peer {self.addr} speaks protocol {resp_ver}, "
                f"we speak {PROTOCOL_VERSION}")
        return s

    def _drop(self) -> None:
        with self.lock:
            s, self.sock = self.sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        # drain: anything still queued is undeliverable
        while True:
            try:
                env = self._q.get_nowait()
            except Exception:
                break
            self.ic._notify_undelivered(env, "session closed")
        self._drop()


class Interconnect:
    """Wire transport for one ActorSystem ('node')."""

    def __init__(self, system: ActorSystem, listen_port: int = 0,
                 peers: dict[int, tuple[str, int]] | None = None,
                 timeout: float = 5.0, max_retries: int = 2,
                 retry_delay: float = 0.1):
        self.system = system
        self.node = system.node
        self.peers = dict(peers or {})
        self.timeout = timeout
        self.max_retries = max_retries
        self.retry_delay = retry_delay
        # shared backoff shape (exponential + jitter) for the sender
        # retry loop; the loop stays hand-rolled because reconnect
        # state (drop/redial) lives between attempts
        self.retry_policy = RetryPolicy(
            max_attempts=max_retries + 1, base_delay=retry_delay,
            max_delay=max(4 * retry_delay, retry_delay))
        # session map is sanitizer-tracked under YDB_TPU_TSAN=1: the
        # actor loop, reader threads (reverse-route add_peer) and
        # close() all touch it
        self._sessions = sanitizer.share(
            {}, f"interconnect.{self.node}.{id(self):x}.sessions")
        self._slock = sanitizer.make_lock(
            f"interconnect.{self.node}.{id(self):x}.slock")
        self._listener: socket.socket | None = None
        self.port: int | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        if listen_port is not None:
            self._listen(listen_port)
        system.set_remote_transport(self._send_remote)

    # ---- outbound ----

    def add_peer(self, node: int, host: str, port: int) -> None:
        with self._slock:
            addr = (host, port)
            old = self._sessions.get(node)
            if old is not None and old.addr == addr:
                # same address (e.g. a peer's inbound reconnect): keep
                # the healthy outbound session
                self.peers[node] = addr
                return
            self.peers[node] = addr
            if old is not None:
                old.close()  # stop the sender thread; no fd leak
                del self._sessions[node]

    def remove_peer(self, node: int) -> None:
        """Forget a peer (dynamic node removal): close its outbound
        session and drop the address, so nodes coming and going cannot
        grow the peer map without bound (lifecycle R007)."""
        with self._slock:
            self.peers.pop(node, None)
            sess = self._sessions.pop(node, None)
            if sess is not None:
                sess.close()

    def _send_remote(self, env: Envelope) -> None:
        addr = self.peers.get(env.target.node)
        if addr is None:
            self._notify_undelivered(env, f"unknown node {env.target.node}")
            return
        with self._slock:
            sess = self._sessions.get(env.target.node)
            if sess is None or sess.addr != addr:
                if sess is not None:
                    sess.close()
                sess = _Session(self, env.target.node, addr)
                self._sessions[env.target.node] = sess
        sess.send(env)

    def _notify_undelivered(self, env: Envelope, reason: str) -> None:
        if env.sender is not None and env.sender.node == self.node:
            self.system.send(
                env.sender, Undelivered(env.target, env.message, reason))
        else:
            self.system.dead_letters.append(env)

    # ---- inbound ----

    def _listen(self, port: int) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(16)
        self._listener = srv
        self.port = srv.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._reader, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _reader(self, conn: socket.socket) -> None:
        try:
            hello = _recv_frame(conn)
            if not (isinstance(hello, tuple) and hello[0] == "hello"):
                return
            peer_ver = hello[4] if len(hello) > 4 else 0
            if peer_ver != PROTOCOL_VERSION:
                # version gate (interconnect_handshake.cpp shape): an
                # incompatible peer gets an explicit reject + reason
                _send_frame(conn, (
                    "reject",
                    f"protocol version {peer_ver} != "
                    f"{PROTOCOL_VERSION}"))
                return
            peer_node, peer_port = hello[1], hello[3]
            if peer_port is not None:
                # learn the reverse route (replies cross a new session)
                self.add_peer(peer_node, conn.getpeername()[0], peer_port)
            _send_frame(conn, ("hello", self.node, hello[2],
                                self.port, PROTOCOL_VERSION))
            while not self._stop.is_set():
                frame = _recv_frame(conn)
                if frame is None:
                    return
                kind, target, sender, message = frame
                if kind == "env":
                    # GIL-atomic deque append; drained by pump()/serve()
                    self.system.inject(
                        Envelope(target, sender, message))
        except OSError:
            return
        finally:
            conn.close()

    # ---- driving the cooperative loop alongside the network ----

    def pump(self, duration: float = 0.5, idle_sleep: float = 0.005
             ) -> None:
        """Drive the actor run loop for ``duration`` seconds, interleaving
        network-injected messages."""
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            if self.system.run() == 0:
                time.sleep(idle_sleep)

    def serve(self) -> None:
        """Run until close() — a node process's main loop."""
        while not self._stop.is_set():
            if self.system.run() == 0:
                time.sleep(0.005)

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        with self._slock:
            for s in self._sessions.values():
                s.close()
            self._sessions.clear()
