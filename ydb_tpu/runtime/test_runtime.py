"""Deterministic multi-node simulated runtime (the tier-2 test workhorse).

Mirror of the reference's TTestActorRuntime (testlib/test_runtime.h:206;
SURVEY.md §4 tier 2): N virtual nodes in one process, a virtual clock
(AdvanceCurrentTime :258), deterministic dispatch (DispatchEvents :280)
and message observers/interceptors (:220) for dropping, reordering and
delaying messages — how multi-node behavior, races and failure
interleavings are tested without a cluster.

Production and simulated runtimes share ActorSystem; this adds the
multi-node weave, virtual time, and observation points.
"""

from __future__ import annotations

from typing import Any, Callable

from ydb_tpu.runtime.actors import ActorSystem, Envelope


class SimRuntime:
    def __init__(self, n_nodes: int = 1):
        self.now = 0.0
        self.nodes: dict[int, ActorSystem] = {}
        self.observer: Callable[[Envelope], str] | None = None
        self.delivery_log: list[Envelope] = []
        for n in range(1, n_nodes + 1):
            sys = ActorSystem(node=n, clock=lambda: self.now)
            sys.set_remote_transport(self._route)
            sys.interceptor = self._intercept
            self.nodes[n] = sys

    def system(self, node: int) -> ActorSystem:
        return self.nodes[node]

    # ---- cross-node routing (interconnect stand-in) ----

    def _route(self, env: Envelope) -> None:
        target_sys = self.nodes.get(env.target.node)
        if target_sys is None:
            return
        target_sys.inject(env)

    def _intercept(self, env: Envelope) -> bool:
        if self.observer is not None:
            verdict = self.observer(env)
            if verdict == "drop":
                return False
            # "pass" or anything else delivers
        self.delivery_log.append(env)
        return True

    # ---- deterministic dispatch ----

    def dispatch(self, max_steps: int = 1_000_000) -> int:
        """Round-robin nodes until every mailbox is idle."""
        total = 0
        progressed = True
        while progressed and total < max_steps:
            progressed = False
            for sys in self.nodes.values():
                if sys.step():
                    progressed = True
                    total += 1
        return total

    def advance_time(self, seconds: float) -> None:
        """Virtual clock jump; due timers fire on next dispatch."""
        self.now += seconds

    def run_until(self, cond: Callable[[], bool],
                  max_iterations: int = 1000) -> bool:
        """Dispatch + auto-advance time to the next timer until cond()."""
        for _ in range(max_iterations):
            self.dispatch()
            if cond():
                return True
            nxt = None
            for sys in self.nodes.values():
                t = sys.next_timer_at()
                if t is not None and (nxt is None or t < nxt):
                    nxt = t
            if nxt is None:
                return cond()
            self.now = max(self.now, nxt)
        return cond()
