from ydb_tpu.runtime.actors import Actor, ActorSystem, ActorId  # noqa: F401
