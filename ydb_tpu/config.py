"""Configuration: YAML static config, immediate-control-board knobs,
feature flags.

Mirror of the reference's config planes (SURVEY.md §5.6): a strict
YAML-parsed static config (yaml_config_parser.cpp analog — unknown keys
and type mismatches are errors, not warnings), lock-free-ish runtime
knobs registered by name and clamped to bounds (TControlWrapper,
immediate_control_board_wrapper.h:7), and feature flags consulted at
gates (TFeatureFlags analog).
"""

from __future__ import annotations

import dataclasses
import threading


class ConfigError(Exception):
    pass


@dataclasses.dataclass
class FeatureFlags:
    enable_row_tables: bool = True
    enable_changefeeds: bool = True
    enable_sys_views: bool = True
    enable_native_kernels: bool = True


@dataclasses.dataclass
class AppConfig:
    n_shards: int = 4
    plan_cache_size: int = 128
    scan_block_rows: int = 1 << 20
    compact_portion_threshold: int = 8
    checkpoint_interval: int = 64
    # load-driven shard management (schemeshard__table_stats.cpp
    # analog): a column table whose rows/shard exceed the split
    # threshold doubles its shard count at the next background pass
    # (0 disables); merges halve when rows/shard fall below
    # threshold/8 (hysteresis against flapping)
    split_rows_per_shard: int = 0
    max_auto_shards: int = 64
    min_auto_shards: int = 1  # MinPartitionsCount analog
    # page-cache memory pressure: run_background shrinks the
    # shared cache as RSS nears this soft limit (0 disables)
    memory_soft_limit_bytes: int = 0
    grpc_port: int = 2136
    data_dir: str | None = None
    auth_tokens: tuple = ()
    background_period_seconds: float = 5.0
    feature_flags: FeatureFlags = dataclasses.field(
        default_factory=FeatureFlags)

    @classmethod
    def from_yaml(cls, text: str) -> "AppConfig":
        import yaml

        raw = yaml.safe_load(text) or {}
        if not isinstance(raw, dict):
            raise ConfigError("config root must be a mapping")
        kwargs = {}
        fields = {f.name: f for f in dataclasses.fields(cls)}
        for key, value in raw.items():
            if key not in fields:
                raise ConfigError(f"unknown config key {key!r}")
            if key == "feature_flags":
                if not isinstance(value, dict):
                    raise ConfigError("feature_flags must be a mapping")
                known = {f.name for f in
                         dataclasses.fields(FeatureFlags)}
                bad = set(value) - known
                if bad:
                    raise ConfigError(
                        f"unknown feature flag(s): {sorted(bad)}")
                for k, v in value.items():
                    if not isinstance(v, bool):
                        raise ConfigError(
                            f"feature flag {k} must be a boolean")
                kwargs[key] = FeatureFlags(**value)
            elif key == "auth_tokens":
                if not isinstance(value, list) or not all(
                        isinstance(v, str) for v in value):
                    raise ConfigError("auth_tokens must be a string list")
                kwargs[key] = tuple(value)
            elif key == "data_dir":
                if value is not None and not isinstance(value, str):
                    raise ConfigError("data_dir must be a string")
                kwargs[key] = value
            else:
                want = fields[key].type
                if want in ("int", int) and not (
                        isinstance(value, int) and
                        not isinstance(value, bool)):
                    raise ConfigError(f"{key} must be an integer")
                if want in ("float", float) and not isinstance(
                        value, (int, float)):
                    raise ConfigError(f"{key} must be a number")
                kwargs[key] = value
        cfg = cls(**kwargs)
        if cfg.n_shards < 1:
            raise ConfigError("n_shards must be >= 1")
        if cfg.scan_block_rows < 1:
            raise ConfigError("scan_block_rows must be >= 1")
        if cfg.compact_portion_threshold < 2:
            raise ConfigError("compact_portion_threshold must be >= 2")
        if cfg.checkpoint_interval < 1:
            raise ConfigError("checkpoint_interval must be >= 1")
        if cfg.plan_cache_size < 1:
            raise ConfigError("plan_cache_size must be >= 1")
        return cfg


@dataclasses.dataclass
class _Control:
    name: str
    value: int
    default: int
    lo: int
    hi: int


class ControlBoard:
    """Runtime knobs: registered with bounds, settable live, consulted
    at hot spots (the ICB pattern — tuning without restart)."""

    def __init__(self):
        self._controls: dict[str, _Control] = {}
        self._lock = threading.Lock()

    def register(self, name: str, default: int, lo: int,
                 hi: int) -> None:
        default = max(lo, min(hi, int(default)))  # bounds always hold
        with self._lock:
            if name not in self._controls:
                self._controls[name] = _Control(name, default, default,
                                                lo, hi)

    def set(self, name: str, value: int) -> int:
        """Clamped to the registered bounds; returns the applied value."""
        with self._lock:
            c = self._controls[name]
            c.value = max(c.lo, min(c.hi, int(value)))
            return c.value

    def get(self, name: str) -> int:
        with self._lock:
            return self._controls[name].value

    def reset(self, name: str) -> None:
        with self._lock:
            c = self._controls[name]
            c.value = c.default

    def dump(self) -> dict:
        with self._lock:
            return {n: dataclasses.asdict(c)
                    for n, c in self._controls.items()}
