"""Mesh-parallel scan execution: SPMD partial aggregation + ICI merge.

The TPU-native equivalent of the reference's distributed aggregate pipeline
(SURVEY.md §2.11): per-tablet partial states + inter-node shuffle/merge over
DQ channels become ONE SPMD program under shard_map:

  device-local partial SSA program (filters/assigns/group-by states)
    → state merge over the ``shard`` mesh axis:
        dense/keyless group layouts: elementwise psum / pmin / pmax of
          slot-aligned states (the gradient-psum-shaped path — BASELINE
          north star)
        generic layouts: all_gather of compacted partial rows + local
          re-aggregation (the DQ UnionAll-then-final-agg shape)
    → final SSA program (AVG fixups, HAVING, ORDER BY) replicated.

Everything here is jit-compiled once per (program, block shape, mesh) — the
whole distributed query step is a single XLA executable with fused
collectives, not a message exchange.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import Column, TableBlock
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, required_columns
from ydb_tpu.parallel.mesh import SHARD_AXIS, make_mesh
from ydb_tpu.ssa import twophase
from ydb_tpu.ssa.compiler import compile_program
from ydb_tpu.ssa.ops import Agg
from ydb_tpu.ssa.program import Program


def stack_blocks(blocks: list[TableBlock]) -> TableBlock:
    """Stack per-shard blocks along a leading device axis."""
    sch = blocks[0].schema
    cols = {}
    for n in sch.names:
        cols[n] = Column(
            jnp.stack([b.columns[n].data for b in blocks]),
            jnp.stack([b.columns[n].validity for b in blocks]),
        )
    length = jnp.stack([b.length for b in blocks])
    return TableBlock(cols, length, sch)


def _local(stacked: TableBlock) -> TableBlock:
    """Inside shard_map: strip the (size-1) leading device axis."""
    cols = {
        n: Column(c.data[0], c.validity[0])
        for n, c in stacked.columns.items()
    }
    return TableBlock(cols, stacked.length[0], stacked.schema)


def _relocal(block: TableBlock) -> TableBlock:
    """Inside shard_map: re-add the singleton device axis so per-shard
    outputs concatenate under out_specs=P(shard)."""
    cols = {
        n: Column(c.data[None], c.validity[None])
        for n, c in block.columns.items()
    }
    return TableBlock(cols, block.length[None], block.schema)


def _merge_slots(
    block: TableBlock,
    merge_kinds: dict[str, Agg | str],
    rank_tables: dict[str, jax.Array],
):
    """Elementwise merge of slot-aligned partial states across the mesh.

    String MIN/MAX states hold dictionary ids; ids do not order like the
    strings, so those columns re-pack as (lexicographic rank << 32 | id)
    before pmin/pmax and unpack after (``rank_tables`` ships the plan-time
    rank arrays)."""
    cols = {}
    for name, col in block.columns.items():
        kind = merge_kinds[name]
        d, v = col.data, col.validity
        packed = kind in (Agg.MIN, Agg.MAX) and name in rank_tables
        if packed:
            rank = rank_tables[name][jnp.clip(d, 0, rank_tables[name].shape[0] - 1)]
            d = (rank.astype(jnp.int64) << 32) | d.astype(jnp.int64)
        if kind in ("key", Agg.SOME, Agg.MAX):
            lo = _neutral(d.dtype, maximum=False)
            d = jax.lax.pmax(jnp.where(v, d, lo), SHARD_AXIS)
            v = jax.lax.pmax(v, SHARD_AXIS)
        elif kind is Agg.MIN:
            hi = _neutral(d.dtype, maximum=True)
            d = jax.lax.pmin(jnp.where(v, d, hi), SHARD_AXIS)
            v = jax.lax.pmax(v, SHARD_AXIS)
        else:  # SUM / COUNT / COUNT_ALL states
            d = jax.lax.psum(jnp.where(v, d, jnp.zeros_like(d)), SHARD_AXIS)
            v = jax.lax.pmax(v, SHARD_AXIS)
        if packed:
            d = (d & 0xFFFFFFFF).astype(jnp.int32)
        cols[name] = Column(d, v)
    return TableBlock(cols, block.length, block.schema)


def _neutral(dtype, maximum: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if maximum else -jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.array(maximum, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if maximum else info.min, dtype)


def _gather_rows(block: TableBlock) -> TableBlock:
    """all_gather compacted partial rows from every shard into one block."""
    cap = block.capacity
    cols = {}
    for n, c in block.columns.items():
        d = jax.lax.all_gather(c.data, SHARD_AXIS)      # (ndev, cap)
        v = jax.lax.all_gather(c.validity, SHARD_AXIS)
        cols[n] = Column(d.reshape(-1), v.reshape(-1))
    lens = jax.lax.all_gather(block.length, SHARD_AXIS)  # (ndev,)
    ndev = lens.shape[0]
    row = jnp.arange(cap, dtype=jnp.int32)
    mask = (row[None, :] < lens[:, None]).reshape(-1)
    big = TableBlock(cols, jnp.int32(ndev * cap), block.schema)
    from ydb_tpu.ssa import kernels

    return kernels.compact(big, mask)


class MeshScan:
    """A distributed scan+aggregate program over a device mesh."""

    def __init__(
        self,
        program: Program,
        schema: dtypes.Schema,
        dicts: DictionarySet | None = None,
        key_spaces: dict[str, int] | None = None,
        mesh=None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.read_cols = required_columns(program, schema)
        in_schema = schema.select(self.read_cols)
        partial_prog, final_prog = twophase.split(
            program, with_row_counts=True
        )
        self.partial = compile_program(
            partial_prog, in_schema, dicts, key_spaces, partial_slots=True
        )
        self.final = (
            compile_program(final_prog, self.partial.out_schema, dicts,
                            key_spaces,
                            dict_aliases=twophase.dict_aliases(partial_prog))
            if final_prog is not None
            else None
        )
        self.out_schema = (
            self.final.out_schema if self.final else self.partial.out_schema
        )
        layout = self.partial.group_layout[0]
        self._use_slots = layout in ("dense_slots", "keyless")

        merge_kinds: dict[str, Agg | str] = {}
        rank_tables: dict[str, jax.Array] = {}
        gb = partial_prog.group_by
        if gb is not None:
            for k in gb.keys:
                merge_kinds[k] = "key"
            for spec in gb.aggs:
                merge_kinds[spec.out_name] = spec.func
                if (
                    spec.func in (Agg.MIN, Agg.MAX)
                    and spec.column is not None
                    and self.partial.out_schema.field(
                        spec.out_name
                    ).type.is_string
                ):
                    rank_tables[spec.out_name] = jnp.asarray(
                        dicts[spec.column].sort_rank()
                    )
        self._merge_kinds = merge_kinds
        self._rank_tables = rank_tables

        paux = {k: jnp.asarray(v) for k, v in self.partial.aux.items()}
        faux = (
            {k: jnp.asarray(v) for k, v in self.final.aux.items()}
            if self.final
            else {}
        )

        def step(stacked: TableBlock) -> TableBlock:
            block = _local(stacked)
            part = self.partial.run(block, paux)
            if self.final is None:
                return _gather_rows(part)
            if self._use_slots:
                merged = _merge_slots(
                    part, self._merge_kinds, self._rank_tables
                )
                # drop dead group slots (keyless keeps its single row:
                # COUNT()=0 over empty input is still one output row)
                if (
                    self.partial.group_layout[0] == "dense_slots"
                    and "__rows" in merged.columns
                ):
                    from ydb_tpu.ssa import kernels

                    live = merged.columns["__rows"].data > 0
                    merged = kernels.compact(merged, live & merged.row_mask())
            else:
                merged = _gather_rows(part)
            return self.final.run(merged, faux)

        self._step = jax.jit(
            jax.shard_map(
                step,
                mesh=self.mesh,
                in_specs=P(SHARD_AXIS),
                out_specs=P(),
                check_vma=False,
            )
        )

    # ---- host-side drivers ----

    def run_stacked(self, stacked: TableBlock) -> TableBlock:
        """stacked: leading device axis == mesh shard count."""
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        stacked = jax.device_put(stacked, sharding)
        return self._step(stacked)

    def execute(self, source: ColumnSource) -> OracleTable:
        """Partition a host table across the mesh and run one SPMD step."""
        n_shards = self.mesh.shape[SHARD_AXIS]
        n = source.num_rows
        per = -(-n // n_shards)
        blocks = []
        sch = source.schema.select(self.read_cols)
        for s in range(n_shards):
            lo, hi = min(s * per, n), min((s + 1) * per, n)
            arrays = {m: source.columns[m][lo:hi] for m in self.read_cols}
            validity = None
            if source.validity:
                validity = {
                    m: source.validity[m][lo:hi]
                    for m in self.read_cols
                    if m in source.validity
                }
            blocks.append(
                TableBlock.from_numpy(arrays, sch, validity, capacity=per)
            )
        out = self.run_stacked(stack_blocks(blocks))
        return OracleTable.from_block(out)
