"""Mesh-parallel scan execution: SPMD partial aggregation + ICI merge.

The TPU-native equivalent of the reference's distributed aggregate pipeline
(SURVEY.md §2.11): per-tablet partial states + inter-node shuffle/merge over
DQ channels become ONE SPMD program under shard_map:

  device-local partial SSA program (filters/assigns/group-by states)
    → state merge over the ``shard`` mesh axis:
        dense/keyless group layouts: elementwise psum / pmin / pmax of
          slot-aligned states (the gradient-psum-shaped path — BASELINE
          north star)
        generic layouts: all_gather of compacted partial rows + local
          re-aggregation (the DQ UnionAll-then-final-agg shape)
    → final SSA program (AVG fixups, HAVING, ORDER BY) replicated.

Everything here is jit-compiled once per (program, block shape, mesh) — the
whole distributed query step is a single XLA executable with fused
collectives, not a message exchange.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ydb_tpu import dtypes
from ydb_tpu.analysis import budget_ok, memsan
from ydb_tpu.blocks.block import Column, TableBlock
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, required_columns
from ydb_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
from ydb_tpu.ssa import twophase
from ydb_tpu.ssa.compiler import compile_program
from ydb_tpu.ssa.ops import Agg
from ydb_tpu.ssa.program import Program


def stack_blocks(blocks: list[TableBlock]) -> TableBlock:
    """Stack per-shard blocks along a leading device axis."""
    sch = blocks[0].schema
    cols = {}
    with memsan.seam("stack"):
        for n in sch.names:
            cols[n] = Column(
                jnp.stack([b.columns[n].data for b in blocks]),
                jnp.stack([b.columns[n].validity for b in blocks]),
            )
        length = jnp.stack([b.length for b in blocks])
    out = TableBlock(cols, length, sch)
    if memsan.armed():
        memsan.charge(memsan.nbytes_of(out), "stack",
                      owner="stack_blocks")
    return out


def _local(stacked: TableBlock) -> TableBlock:
    """Inside shard_map: strip the (size-1) leading device axis."""
    cols = {
        n: Column(c.data[0], c.validity[0])
        for n, c in stacked.columns.items()
    }
    return TableBlock(cols, stacked.length[0], stacked.schema)


def _relocal(block: TableBlock) -> TableBlock:
    """Inside shard_map: re-add the singleton device axis so per-shard
    outputs concatenate under out_specs=P(shard)."""
    cols = {
        n: Column(c.data[None], c.validity[None])
        for n, c in block.columns.items()
    }
    return TableBlock(cols, block.length[None], block.schema)


def _merge_states(cols_in, merge_kinds, rank_tables, red_max, red_min,
                  red_sum, red_any):
    """Shared state-merge core: per-column masked reduction by aggregate
    kind, with string MIN/MAX ids re-packed as (lexicographic rank << 32
    | id) around the reduction (ids do not order like the strings;
    ``rank_tables`` ships the plan-time rank arrays). The reduction ops
    are injected: mesh collectives for the cross-shard merge
    (_merge_slots), elementwise folds for the streaming pairwise merge
    (_merge_pair) — one logic, two execution shapes."""
    cols = {}
    for name, (d, v) in cols_in.items():
        kind = merge_kinds[name]
        packed = kind in (Agg.MIN, Agg.MAX) and name in rank_tables
        if packed:
            rt = rank_tables[name]
            rank = rt[jnp.clip(d, 0, rt.shape[0] - 1)]
            d = (rank.astype(jnp.int64) << 32) | d.astype(jnp.int64)
        if kind in ("key", Agg.SOME, Agg.MAX):
            lo = _neutral(d.dtype, maximum=False)
            d = red_max(jnp.where(v, d, lo))
        elif kind is Agg.MIN:
            hi = _neutral(d.dtype, maximum=True)
            d = red_min(jnp.where(v, d, hi))
        else:  # SUM / COUNT / COUNT_ALL states
            d = red_sum(jnp.where(v, d, jnp.zeros_like(d)))
        v = red_any(v)
        if packed:
            d = (d & 0xFFFFFFFF).astype(jnp.int32)
        cols[name] = Column(d, v)
    return cols


def _merge_slots(
    block: TableBlock,
    merge_kinds: dict[str, Agg | str],
    rank_tables: dict[str, jax.Array],
):
    """Elementwise merge of slot-aligned partial states across the mesh."""
    cols = _merge_states(
        {n: (c.data, c.validity) for n, c in block.columns.items()},
        merge_kinds, rank_tables,
        red_max=lambda x: jax.lax.pmax(x, SHARD_AXIS),
        red_min=lambda x: jax.lax.pmin(x, SHARD_AXIS),
        red_sum=lambda x: jax.lax.psum(x, SHARD_AXIS),
        red_any=lambda v: jax.lax.pmax(v, SHARD_AXIS),
    )
    return TableBlock(cols, block.length, block.schema)


def _live_prefix_host(block: TableBlock):
    """(host arrays dict, host validity dict, schema) of the live rows."""
    n = int(block.length)
    arrays = {m: np.asarray(c.data)[:n] for m, c in block.columns.items()}
    valid = {m: np.asarray(c.validity)[:n]
             for m, c in block.columns.items()}
    return arrays, valid, block.schema


def _concat_states(parts: list) -> TableBlock:
    """Concatenate host live-prefix states (from _live_prefix_host)."""
    sch = parts[0][2]
    arrays = {
        n: np.concatenate([p[0][n] for p in parts]) for n in sch.names
    }
    validity = {
        n: np.concatenate([p[1][n] for p in parts]) for n in sch.names
    }
    return TableBlock.from_numpy(arrays, sch, validity)


@budget_ok("transient pad-to-capacity copy: every call site feeds the"
           " result straight into a charging stack_blocks seam, which"
           " accounts the stacked footprint")
def _pad_state(block: TableBlock, capacity: int) -> TableBlock:
    if block.capacity == capacity:
        return block
    cols = {}
    for n, c in block.columns.items():
        pad = capacity - c.data.shape[0]
        cols[n] = Column(
            jnp.concatenate(
                [c.data, jnp.zeros((pad,), dtype=c.data.dtype)]),
            jnp.concatenate([c.validity, jnp.zeros((pad,), dtype=bool)]),
        )
    return TableBlock(cols, block.length, block.schema)


def _merge_pair(a: TableBlock, b: TableBlock, merge_kinds, rank_tables):
    """Pairwise (device-local) twin of _merge_slots: fold two slot-aligned
    partial-state blocks into one. Drives the streaming per-shard state
    accumulation — each shard folds its block stream into ONE bounded
    state before the mesh-wide collective merge."""
    cols = _merge_states(
        {
            n: (jnp.stack([ca.data, b.columns[n].data]),
                jnp.stack([ca.validity, b.columns[n].validity]))
            for n, ca in a.columns.items()
        },
        merge_kinds, rank_tables,
        red_max=lambda x: jnp.max(x, axis=0),
        red_min=lambda x: jnp.min(x, axis=0),
        red_sum=lambda x: jnp.sum(x, axis=0),
        red_any=lambda v: jnp.any(v, axis=0),
    )
    return TableBlock(cols, jnp.maximum(a.length, b.length), a.schema)


def _neutral(dtype, maximum: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if maximum else -jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.array(maximum, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if maximum else info.min, dtype)


def merge_spec(partial_prog: Program, partial_out_schema, dicts):
    """(merge_kinds, rank_tables) for cross-shard partial-state merges:
    per-column reduction kind from the partial program's group-by, plus
    lexicographic rank tables for string MIN/MAX (dictionary ids do not
    order like the strings they intern). Shared by MeshScan and the
    fused mesh lowering (parallel/mesh_fuse)."""
    merge_kinds: dict[str, Agg | str] = {}
    rank_tables: dict[str, jax.Array] = {}
    gb = partial_prog.group_by
    if gb is not None:
        for k in gb.keys:
            merge_kinds[k] = "key"
        for spec in gb.aggs:
            merge_kinds[spec.out_name] = spec.func
            if (
                spec.func in (Agg.MIN, Agg.MAX)
                and spec.column is not None
                and partial_out_schema.field(spec.out_name).type.is_string
            ):
                rt = jnp.asarray(dicts[spec.column].sort_rank())
                if memsan.armed():
                    memsan.charge(memsan.nbytes_of(rt), "staging",
                                  owner="rank_tables")
                rank_tables[spec.out_name] = rt
    return merge_kinds, rank_tables


def _gather_rows(block: TableBlock) -> TableBlock:
    """all_gather compacted partial rows from every shard into one block."""
    cap = block.capacity
    cols = {}
    for n, c in block.columns.items():
        d = jax.lax.all_gather(c.data, SHARD_AXIS)      # (ndev, cap)
        v = jax.lax.all_gather(c.validity, SHARD_AXIS)
        cols[n] = Column(d.reshape(-1), v.reshape(-1))
    lens = jax.lax.all_gather(block.length, SHARD_AXIS)  # (ndev,)
    ndev = lens.shape[0]
    row = jnp.arange(cap, dtype=jnp.int32)
    mask = (row[None, :] < lens[:, None]).reshape(-1)
    big = TableBlock(cols, jnp.int32(ndev * cap), block.schema)
    from ydb_tpu.ssa import kernels

    return kernels.compact(big, mask)


class MeshScan:
    """A distributed scan+aggregate program over a device mesh."""

    def __init__(
        self,
        program: Program,
        schema: dtypes.Schema,
        dicts: DictionarySet | None = None,
        key_spaces: dict[str, int] | None = None,
        mesh=None,
        dict_aliases: dict[str, str] | None = None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.read_cols = required_columns(program, schema)
        in_schema = schema.select(self.read_cols)
        partial_prog, final_prog = twophase.split(
            program, with_row_counts=True
        )
        aliases = dict(dict_aliases or {})
        self.partial = compile_program(
            partial_prog, in_schema, dicts, key_spaces, partial_slots=True,
            dict_aliases=aliases,
        )
        self.final = (
            compile_program(final_prog, self.partial.out_schema, dicts,
                            key_spaces,
                            dict_aliases={
                                **aliases,
                                **twophase.dict_aliases(partial_prog),
                            })
            if final_prog is not None
            else None
        )
        self.out_schema = (
            self.final.out_schema if self.final else self.partial.out_schema
        )
        layout = self.partial.group_layout[0]
        self._use_slots = layout in ("dense_slots", "keyless")

        merge_kinds, rank_tables = merge_spec(
            partial_prog, self.partial.out_schema, dicts)
        self._merge_kinds = merge_kinds
        self._rank_tables = rank_tables

        with memsan.seam("staging"):
            paux = {k: jnp.asarray(v)
                    for k, v in self.partial.aux.items()}
            faux = (
                {k: jnp.asarray(v) for k, v in self.final.aux.items()}
                if self.final
                else {}
            )
        if memsan.armed():
            memsan.charge(memsan.nbytes_of((paux, faux)), "staging",
                          owner="mesh_aux")

        def merge_final(part: TableBlock) -> TableBlock:
            if self.final is None:
                return _gather_rows(part)
            if self._use_slots:
                merged = _merge_slots(
                    part, self._merge_kinds, self._rank_tables
                )
                # drop dead group slots (keyless keeps its single row:
                # COUNT()=0 over empty input is still one output row)
                if (
                    self.partial.group_layout[0] == "dense_slots"
                    and "__rows" in merged.columns
                ):
                    from ydb_tpu.ssa import kernels

                    live = merged.columns["__rows"].data > 0
                    merged = kernels.compact(merged, live & merged.row_mask())
            else:
                merged = _gather_rows(part)
            return self.final.run(merged, faux)

        def step(stacked: TableBlock) -> TableBlock:
            block = _local(stacked)
            part = self.partial.run(block, paux)
            return merge_final(part)

        self._step = jax.jit(
            shard_map(
                step,
                mesh=self.mesh,
                in_specs=P(SHARD_AXIS),
                out_specs=P(),
                check_vma=False,
            )
        )
        # merge+final over PRE-COMPUTED per-shard partial states (the
        # streaming driver computes states shard-locally block by block)
        self._merge_final_step = jax.jit(
            shard_map(
                lambda st: merge_final(_local(st)),
                mesh=self.mesh,
                in_specs=P(SHARD_AXIS),
                out_specs=P(),
                check_vma=False,
            )
        )
        self._partial_jit = jax.jit(
            lambda blk: self.partial.run(blk, paux))
        self._pair_jit = jax.jit(
            lambda a, b: _merge_pair(a, b, self._merge_kinds,
                                     self._rank_tables))

    # ---- host-side drivers ----

    def run_stacked(self, stacked: TableBlock) -> TableBlock:
        """stacked: leading device axis == mesh shard count."""
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        with memsan.seam("staging"):
            stacked = jax.device_put(stacked, sharding)
        if memsan.armed():
            memsan.charge(memsan.nbytes_of(stacked), "staging",
                          owner="mesh_place")
        return self._step(stacked)

    def execute_sources(self, sources, block_rows: int = 1 << 20
                        ) -> OracleTable:
        """Streaming SPMD scan over per-shard block-stream sources (the
        portion store feeding the mesh — VERDICT r4 item 4).

        Each shard's stream (e.g. a PortionStreamSource over its on-disk
        portions) folds block-by-block into ONE bounded partial state on
        its device (slot layouts: pairwise merge; compact layouts:
        concatenated partial rows), then a single collective step merges
        states across the mesh and finalizes. Host memory per shard stays
        bounded by the stream's working set — out-of-core and multi-chip
        compose."""
        n_shards = self.mesh.shape[SHARD_AXIS]
        if len(sources) != n_shards:
            raise ValueError(
                f"{len(sources)} sources for a {n_shards}-shard mesh")
        layout = self.partial.group_layout[0]
        foldable = layout in ("keyless", "dense_slots")
        states = []
        for sub in sources:
            st = None
            parts = []
            for blk in sub.blocks(block_rows, self.read_cols):
                part = self._partial_jit(blk)
                if not foldable:
                    # keep only the live prefix ON HOST: holding every
                    # full-capacity device block would grow device memory
                    # linearly with the stream
                    parts.append(_live_prefix_host(part))
                elif st is None:
                    st = part
                else:
                    st = self._pair_jit(st, part)
            states.append(st if foldable else _concat_states(parts))
        if not foldable:
            # compact states vary in size shard-to-shard: pad to common
            cap = max(s.capacity for s in states)
            states = [_pad_state(s, cap) for s in states]
        with memsan.seam("staging"):
            placed = jax.device_put(
                stack_blocks(states),
                NamedSharding(self.mesh, P(SHARD_AXIS)))
        if memsan.armed():
            memsan.charge(memsan.nbytes_of(placed), "staging",
                          owner="mesh_place")
        out = self._merge_final_step(placed)
        return OracleTable.from_block(out)

    def execute(self, source: ColumnSource) -> OracleTable:
        """Partition a host table across the mesh and run one SPMD step."""
        n_shards = self.mesh.shape[SHARD_AXIS]
        n = source.num_rows
        per = -(-n // n_shards)
        blocks = []
        sch = source.schema.select(self.read_cols)
        for s in range(n_shards):
            lo, hi = min(s * per, n), min((s + 1) * per, n)
            arrays = {m: source.columns[m][lo:hi] for m in self.read_cols}
            validity = None
            if source.validity:
                validity = {
                    m: source.validity[m][lo:hi]
                    for m in self.read_cols
                    if m in source.validity
                }
            blocks.append(
                TableBlock.from_numpy(arrays, sch, validity, capacity=per)
            )
        out = self.run_stacked(stack_blocks(blocks))
        return OracleTable.from_block(out)
