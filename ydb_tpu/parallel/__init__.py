from ydb_tpu.parallel.mesh import make_mesh, shard_axis  # noqa: F401
from ydb_tpu.parallel.dist import MeshScan  # noqa: F401
