"""Hash-partition shuffle over the mesh: the DQ repartitioning channel.

Reference: rows route to output partitions by key hash between stages
(TDqOutputHashPartitionConsumer, dq_output_consumer.cpp:105; vectorized
block path :338). TPU-native: each device buckets its rows by destination
shard and the buckets exchange via ``jax.lax.all_to_all`` over ICI — the
same collective shape as MoE expert dispatch (SURVEY.md §2.11).

XLA needs static shapes, so each device sends a fixed-capacity bucket to
every peer. Full local capacity is always enough — worst case all local
rows hash to one shard — but ships ndev × capacity rows per exchange;
``size_buckets`` instead sizes the bucket from column statistics (mean
destination load × safety margin + the count-min heaviest-hitter bound,
rounded to a plan_fuse shape class so same-class re-runs never retrace).
Undersized buckets cannot corrupt results: ``repartition`` returns the
traced worst per-destination count, the host compares it against the
static capacity and grows-and-retraces on overflow (the grace-join
respill protocol with ICI as the spill fabric). ``YDB_TPU_SHUFFLE_STATS=0``
restores full-capacity buckets. After the exchange each device owns
exactly the rows whose key hash maps to it — the precondition for
partitioned (grace-style) joins and re-keyed aggregation.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ydb_tpu.blocks.block import Column, TableBlock
from ydb_tpu.parallel.mesh import SHARD_AXIS

#: in-process override for stats-sized buckets (bench A/B seam); None
#: defers to the YDB_TPU_SHUFFLE_STATS environment gate
SHUFFLE_STATS_FORCE: "bool | None" = None

#: headroom over the mean per-destination load: absorbs ordinary hash
#: imbalance without a grow-retrace; measured skew beyond it still
#: corrects itself through the overflow protocol
SAFETY_MARGIN = 1.5


def shuffle_stats_enabled() -> bool:
    if SHUFFLE_STATS_FORCE is not None:
        return SHUFFLE_STATS_FORCE
    return os.environ.get("YDB_TPU_SHUFFLE_STATS", "1") not in (
        "0", "", "off")


def size_buckets(local_rows: int, n_shards: int, heavy: int = 0,
                 margin: float = SAFETY_MARGIN) -> int:
    """Stats-sized per-destination send bucket for ``repartition``.

    Uniform keys spread ``local_rows`` evenly over ``n_shards``
    destinations, so the bucket holds mean × margin; a heavy hitter can
    pile its whole frequency onto one destination, so the estimate adds
    ``heavy`` (the table-wide count-min bound — every local occurrence
    routes to the same shard in the worst case). Rounded UP to a
    plan_fuse shape class (same-class re-runs reuse the compiled
    exchange) and clamped to the always-sufficient full capacity.
    Stats off (or a degenerate 1-shard mesh) keeps full capacity."""
    from ydb_tpu.ssa.plan_fuse import shape_class

    full = max(int(local_rows), 1)
    if n_shards <= 1 or not shuffle_stats_enabled():
        return full
    mean = -(-full // n_shards)
    est = int(mean * margin) + max(int(heavy), 0)
    return min(full, shape_class(est))


def row_bytes(schema) -> int:
    """Physical bytes one row ships in an exchange: column payloads
    plus one validity byte per column (host-side accounting helper —
    the traced exchange itself never calls this)."""
    return sum(f.type.physical.itemsize + 1 for f in schema.fields)


def exchange_bytes_per_device(schema, n_shards: int,
                              bucket_rows: int) -> int:
    """Bytes ONE device sends in one ``repartition`` exchange: a
    fixed-capacity bucket to every peer (static shapes — the shape of
    the all_to_all, not the live row count). Callers feed this to
    ``timeline.add_bytes("shuffle_bytes_dev<i>", ...)`` so per-device
    movement (and stats-sizing wins / skew grows) shows up as counter
    rates."""
    return int(n_shards) * int(bucket_rows) * row_bytes(schema)


def heavy_bound(stats, keys) -> int:
    """Heaviest joint-key frequency bound from aggregator statistics.

    Each key column's bound is the max matching ``ColumnStats.heavy``
    across tables (join keys may appear under the same name on both
    sides; the max stays conservative). A composite key occurs at most
    as often as its rarest component, so the joint bound is the min
    over per-key bounds — any single known component already bounds the
    pair. Unknown columns contribute nothing (0 = no bound)."""
    if not stats:
        return 0
    per_key = []
    for k in keys:
        best = 0
        for ts in stats.values():
            cs = getattr(ts, "columns", {}).get(k)
            if cs is not None:
                best = max(best, int(getattr(cs, "heavy", 0)))
        if best:
            per_key.append(best)
    return min(per_key) if per_key else 0

# splitmix64-style avalanche constants
_C1 = jnp.uint64(0xBF58476D1CE4E5B9)
_C2 = jnp.uint64(0x94D049BB133111EB)


def hash_rows(cols: list[Column]) -> jax.Array:
    """Vectorized 64-bit row hash over key columns (uint64)."""
    h = jnp.full(cols[0].data.shape, jnp.uint64(0x9E3779B97F4A7C15))
    for c in cols:
        k = c.data.astype(jnp.int64).astype(jnp.uint64)
        # null keys hash as a distinct class via the validity bit
        k = k ^ (c.validity.astype(jnp.uint64) << 63)
        x = h ^ k
        x = (x ^ (x >> 30)) * _C1
        x = (x ^ (x >> 27)) * _C2
        h = x ^ (x >> 31)
    return h


def repartition(
    block: TableBlock,
    key_names: list[str],
    n_shards: int,
    bucket_rows: int | None = None,
    with_counts: bool = False,
) -> "TableBlock | tuple[TableBlock, jax.Array]":
    """Exchange rows so each shard owns hash(keys) % n_shards == its index.

    Must run inside shard_map over the ``shard`` axis. Returns a local
    block of capacity n_shards * bucket_rows. With ``with_counts``,
    returns (block, worst: int32 scalar) — the mesh-wide max rows any
    device wanted to send to one destination. worst > bucket_rows means
    rows were dropped somewhere; callers re-exchange with bucket_rows
    grown to hold ``worst`` exactly (the grace-join respill protocol,
    mkql_grace_join_imp.cpp bucket overflow, sized by the observed count
    instead of blind doubling)."""
    cap = block.capacity
    B = bucket_rows if bucket_rows is not None else cap
    live = block.row_mask()
    h = hash_rows([block.columns[k] for k in key_names])
    dest = (h % jnp.uint64(n_shards)).astype(jnp.int32)
    dest = jnp.where(live, dest, n_shards)  # dead rows -> drop bucket

    # stable-sort rows by destination => contiguous buckets
    order = jnp.argsort(dest, stable=True)
    dest_s = dest[order]
    # position of each row within its bucket
    ones = jnp.ones_like(dest_s, dtype=jnp.int32)
    counts = jnp.zeros(n_shards + 1, dtype=jnp.int32).at[dest_s].add(
        ones, mode="drop"
    )
    starts = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]]
    )
    pos_in_bucket = (
        jnp.arange(cap, dtype=jnp.int32) - starts[jnp.clip(dest_s, 0, n_shards)]
    )
    # scatter into (n_shards, B) send buffers; overflow/dead rows drop
    slot = jnp.where(
        (dest_s < n_shards) & (pos_in_bucket < B),
        dest_s * B + pos_in_bucket,
        n_shards * B,
    )

    sent_counts = jnp.minimum(counts[:n_shards], B)  # per-destination rows

    new_cols = {}
    for n, c in block.columns.items():
        d = c.data[order]
        v = c.validity[order]
        buf = jnp.zeros((n_shards * B,), dtype=d.dtype).at[slot].set(
            d, mode="drop"
        ).reshape(n_shards, B)
        vbuf = jnp.zeros((n_shards * B,), dtype=v.dtype).at[slot].set(
            v, mode="drop"
        ).reshape(n_shards, B)
        rd = jax.lax.all_to_all(buf, SHARD_AXIS, 0, 0, tiled=False)
        rv = jax.lax.all_to_all(vbuf, SHARD_AXIS, 0, 0, tiled=False)
        new_cols[n] = Column(rd.reshape(-1), rv.reshape(-1))

    recv_counts = jax.lax.all_to_all(
        sent_counts.reshape(n_shards, 1), SHARD_AXIS, 0, 0
    ).reshape(-1)  # rows received from each peer
    row = jnp.arange(B, dtype=jnp.int32)
    mask = (row[None, :] < recv_counts[:, None]).reshape(-1)

    big = TableBlock(
        new_cols, jnp.int32(n_shards * B), block.schema
    )
    from ydb_tpu.ssa import kernels

    out = kernels.compact(big, mask)
    if not with_counts:
        return out
    worst = jnp.max(counts[:n_shards])
    # a drop anywhere poisons every shard's result: reduce over the mesh
    # so every device (and the host, once) sees the same grow target
    worst = jax.lax.pmax(worst, SHARD_AXIS)
    return out, worst
