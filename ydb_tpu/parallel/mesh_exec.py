"""Distributed plan execution over the device mesh: grace joins + SPMD
aggregation driving the SAME logical plan trees the single-chip executor
runs (ydb_tpu.plan.nodes).

The reference distributes a query as stage tasks exchanging rows through
hash-partition channels (kqp_tasks_graph.cpp:448; vectorized partition
consumer dq_output_consumer.cpp:338) and joins with GraceJoin buckets
(mkql_grace_join_imp.cpp). The TPU-native design maps those pieces onto
mesh collectives:

  * table scans run per shard (each mesh device owns a table partition;
    filters/projections execute in the per-shard compiled scan),
  * every equi-join hash-REPARTITIONS both sides over the ``shard`` axis
    with ``jax.lax.all_to_all`` (parallel/shuffle.py) so matching keys
    land on the same device, then joins device-locally with the
    sort/searchsorted kernels (ssa/join.py) — the grace-join shape with
    ICI as the spill fabric; bucket overflow retries with doubled
    capacity (the respill protocol),
  * the final Transform (aggregate/HAVING/ORDER BY) reuses the MeshScan
    two-phase machinery: per-device partial states, psum/pmin/pmax or
    all_gather merge, replicated finalization.

Each stage is one jitted shard_map step; data stays device-resident and
mesh-sharded between stages.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ydb_tpu import chaos
from ydb_tpu.analysis import host_ok, memsan
from ydb_tpu.blocks.block import Column, TableBlock
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
from ydb_tpu.parallel.dist import (
    MeshScan,
    _local,
    _pad_state,
    _relocal,
    stack_blocks,
)
from ydb_tpu.obs import timeline
from ydb_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
from ydb_tpu.parallel.shuffle import (
    exchange_bytes_per_device,
    heavy_bound,
    repartition,
    size_buckets,
)
from ydb_tpu.plan.nodes import ExpandJoin, LookupJoin, TableScan, Transform
from ydb_tpu.ssa import join as join_kernels
from ydb_tpu.ssa import kernels
from ydb_tpu.ssa.plan_fuse import shape_class
from ydb_tpu.ssa.program import SortStep, WindowStep


def _round_up(n: int) -> int:
    """Intermediate staging capacity: plan_fuse's shape classes (1024
    quantum, quarter-of-power-of-two steps), replacing the walk's old
    ad-hoc 64-row quantum so the per-node mesh walk and the fused mesh
    path land on the SAME block capacities — one compile-cache entry per
    class serves both executors instead of two near-identical traces."""
    return shape_class(n)


class MeshDatabase:
    """Per-shard table partitions + shared dictionaries for mesh runs.

    ``sources[table]`` is a list of per-shard ColumnSource /
    PortionStreamSource objects, EXACTLY one per mesh device
    (row-partitioned tables; partition a small table with empty-slice
    sources for the extra devices).
    """

    def __init__(self, sources: dict[str, list], dicts=None,
                 key_spaces=None, table_stats=None):
        self.sources = sources
        self.dicts = dicts if dicts is not None else DictionarySet()
        self.key_spaces = key_spaces
        # aggregator TableStats by name: sizes shuffle buckets (the
        # count-min heavy-hitter bound); advisory — missing stats only
        # cost a grow-retrace under skew, never correctness
        self.table_stats = table_stats


class _ChainSource:
    """Several per-shard sources presented as ONE device's scan input
    (shard count need not equal mesh size: shards group round-robin
    onto devices). Duck-types the ColumnSource surface ScanExecutor
    streams from; sub-streams rechunk to ONE fixed capacity so the
    compiled per-block program never retraces, and start_block seeks
    work (the stream_blocks contract every other source honors)."""

    def __init__(self, subs: list):
        self.subs = list(subs)
        self.schema = subs[0].schema
        self.dicts = subs[0].dicts

    @property
    def num_rows(self) -> int:
        return sum(s.num_rows for s in self.subs)

    def blocks(self, block_rows: int, columns=None, start_block: int = 0):
        from ydb_tpu.engine.reader import stream_blocks

        names = tuple(columns) if columns is not None else self.schema.names
        sch = self.schema.select(names)
        cap = min(block_rows, max(self.num_rows, 1))

        def payloads():
            for s in self.subs:
                for b in s.blocks(block_rows, names):
                    yield b.to_numpy(), b.validity_numpy()

        yield from stream_blocks(payloads(), names, sch, cap,
                                 start_block=start_block)


@host_ok("mesh partition grouping: bounded by device count; only"
         " EMPTY mesh slots allocate (0-row placeholder sources)")
def device_partitions(sources: list, n: int, schema, dicts) -> list:
    """Group a table's per-shard sources onto exactly ``n`` mesh devices
    (round-robin; empty devices get an empty source) — the seam that
    lets any shard count ride any mesh size."""
    out = []
    for d in range(n):
        g = sources[d::n]
        if not g:
            out.append(ColumnSource(
                {f.name: np.empty(0, dtype=f.type.physical)
                 for f in schema.fields}, schema, dicts))
        elif len(g) == 1:
            out.append(g[0])
        else:
            out.append(_ChainSource(g))
    return out


def _chaos_dispatch(n_devices: int) -> None:
    """``mesh.dispatch`` injection site: 'device_lost' raises
    :class:`chaos.DeviceLostError`, which the plan executor's fallback
    chain turns into single-chip execution (fused, then the walk)."""
    fault = chaos.hit("mesh.dispatch", devices=n_devices)
    if fault is not None:
        fault.sleep()
        if fault.kind == "device_lost":
            raise chaos.DeviceLostError(
                f"injected device loss on the {n_devices}-device mesh")


class MeshPlanExecutor:
    """Executes a logical plan tree SPMD over the mesh."""

    def __init__(self, db: MeshDatabase, mesh=None):
        self.db = db
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n = self.mesh.shape[SHARD_AXIS]
        self._jit_cache: dict = {}

    # ---- node execution (stacked, device-sharded results) ----

    def execute(self, plan) -> OracleTable:
        _chaos_dispatch(self.n)
        out = self._exec(plan, {}, root=True)
        return OracleTable.from_block(out)

    # ---- whole-plan sharded fusion (parallel/mesh_fuse) ----

    def execute_fused(self, plan) -> OracleTable | None:
        """One sharded jitted dispatch for the whole plan, or None when
        the plan does not mesh-fuse (the caller falls through to the
        per-node walk above). Compiled MeshFusedPlans — and the negative
        doesn't-fuse verdicts — cache per (plan fingerprint, shape-class
        vector, mesh size) in the executor's jit cache."""
        from ydb_tpu.obs import tracing
        from ydb_tpu.parallel import mesh_fuse

        if not mesh_fuse.mesh_fusion_enabled():
            return None
        sig = mesh_fuse.mesh_signature(plan, self.db, self.n)
        if sig is None or not sig.sites:
            return None
        key = ("mesh_fuse", self.n, sig.cache_key(self.db))
        fused = self._jit_cache.get(key)
        if fused == "unfusible":
            return None
        fresh = fused is None
        with tracing.span("plan.fuse") as fsp:
            if fresh:
                try:
                    fused = mesh_fuse.build(sig, self.db, self.mesh,
                                            stats=self.db.table_stats)
                except (mesh_fuse.Unfusible, NotImplementedError):
                    # negative verdicts cache too: plan_signature is
                    # cheap but build walks every program
                    self._jit_cache[key] = "unfusible"
                    return None
                self._jit_cache[key] = fused
            ft0 = fused.first_trace_seconds or 0.0
            grows0 = fused.shuffle_grows
            inputs = self._stage_fused(fused)
            while True:
                # cancellation + device-loss points between dispatches:
                # a statement past its deadline stops HERE (the fused
                # computation itself is uninterruptible), and an
                # injected device loss degrades to the single-chip path
                statement_deadline.check_current("mesh dispatch")
                _chaos_dispatch(self.n)
                out, totals = fused.run(inputs)
                over = fused.overflowed(totals)
                if not over:
                    break
                # a shuffle bucket or expand join outgrew its static
                # capacity: widen to the observed size (the cached plan
                # keeps it for later statements) and re-stage — donation
                # consumed the inputs
                for j in over:
                    fused.grow(j, totals[j])
                inputs = self._stage_fused(fused)
            if fsp.recording:
                fsp.set(fused_stages=fused.fused_stages,
                        fragments_elided=fused.fused_stages - 1,
                        compile_cache=("miss" if fresh else "hit"),
                        mesh_devices=self.n,
                        shuffle_capacity=fused.shuffle_capacity(),
                        shuffle_grows=fused.shuffle_grows - grows0)
                ft = (fused.first_trace_seconds or 0.0) - ft0
                if ft:
                    fsp.set(first_trace_seconds=round(ft, 6))
        return OracleTable.from_block(out)

    def _stage_fused(self, fused) -> dict:
        """Stage every scan site as a mesh-sharded stacked block: each
        device's partition streams, fits to the per-device shape-class
        capacity (plan_fuse.fit_blocks — fresh buffers, safe to donate),
        and the per-device blocks stack under NamedSharding(P(shard))."""
        from ydb_tpu.ssa.plan_fuse import fit_blocks

        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        inputs: dict = {}
        for site in fused.sites:
            subs = self.db.sources[site.table]
            if len(subs) != self.n:
                raise ValueError(
                    f"table {site.table} has {len(subs)} shards for a"
                    f" {self.n}-device mesh (need exactly one per device)")
            devs = []
            for sub in subs:
                blocks = tuple(sub.blocks(1 << 22, site.read_cols))
                if not blocks:
                    # portion streams yield nothing for an empty shard
                    blocks = (TableBlock.from_numpy(
                        {f.name: np.empty(0, dtype=f.type.physical)
                         for f in site.in_schema.fields},
                        site.in_schema),)
                devs.append(fit_blocks(blocks, site.capacity))
            with memsan.seam("staging"):
                inputs[site.key] = jax.device_put(
                    stack_blocks(devs), sharding)
        if memsan.armed():
            memsan.charge(memsan.nbytes_of(inputs), "staging",
                          owner="stage_fused")
        return inputs

    def _exec(self, plan, memo: dict, root: bool = False):
        hit = memo.get(id(plan))
        if hit is not None:
            return hit
        if isinstance(plan, TableScan):
            out = self._scan(plan)
        elif isinstance(plan, LookupJoin):
            out = self._join(plan, memo, expand=False)
        elif isinstance(plan, ExpandJoin):
            out = self._join(plan, memo, expand=True)
        elif isinstance(plan, Transform):
            out = self._transform(plan, memo, root)
        else:
            raise NotImplementedError(plan)
        memo[id(plan)] = out
        return out

    def _shard_it(self, stacked: TableBlock) -> TableBlock:
        sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        with memsan.seam("staging"):
            placed = jax.device_put(stacked, sharding)
        if memsan.armed():
            memsan.charge(memsan.nbytes_of(placed), "staging",
                          owner="mesh_place")
        return placed

    def _scan(self, plan: TableScan) -> TableBlock:
        """Per-shard scan: pushdown program runs in each shard's scan
        executor; per-shard results pad-stack onto the mesh."""
        subs = self.db.sources[plan.table]
        if len(subs) != self.n:
            # more sources than devices would silently drop every block
            # past the first per device (sharded leading axis)
            raise ValueError(
                f"table {plan.table} has {len(subs)} shards for a"
                f" {self.n}-device mesh (need exactly one per device)")
        locals_: list[TableBlock] = []
        for sub in subs:
            if plan.program is None:
                names = plan.columns or sub.schema.names
                blks = list(sub.blocks(1 << 20, names))
                blk = blks[0] if len(blks) == 1 else _concat(blks)
            else:
                ex = ScanExecutor(plan.program, sub, block_rows=1 << 20,
                                  key_spaces=self.db.key_spaces)
                blk = ex.run_stream(sub.blocks(1 << 20, ex.read_cols))
            locals_.append(blk)
        cap = _round_up(max(int(b.length) for b in locals_))
        return self._shard_it(stack_blocks(
            [_pad_state(self._slice(b, cap), cap) for b in locals_]))

    @staticmethod
    def _slice(block: TableBlock, cap: int) -> TableBlock:
        if block.capacity <= cap:
            return block
        cols = {
            n: Column(c.data[:cap], c.validity[:cap])
            for n, c in block.columns.items()
        }
        return TableBlock(cols, block.length, block.schema)

    def _join(self, plan, memo, expand: bool) -> TableBlock:
        probe = self._exec(plan.probe, memo)
        build = self._exec(plan.build, memo)
        pkeys = list(plan.probe_keys)
        bkeys = list(plan.build_keys)
        probe = self._repartition(probe, pkeys)
        build = self._repartition(build, bkeys)
        if not expand:
            return self._local_lookup(plan, probe, build)
        return self._local_expand(plan, probe, build)

    # -- repartition with overflow retry --

    def _repartition(self, stacked: TableBlock, keys: list[str]):
        cap = stacked.capacity
        # stats-sized first attempt (mean load × margin + the count-min
        # heavy-hitter bound) instead of the old blind 2/n-of-capacity;
        # overflow grows to the shape class of the OBSERVED worst count
        # — one exact retry, not a doubling ladder
        B = size_buckets(cap, self.n,
                         heavy=heavy_bound(self.db.table_stats, keys))
        while True:
            key = ("repart", stacked.schema, tuple(keys), cap, B)
            step = self._jit_cache.get(key)
            if step is None:
                n = self.n

                def go(st, _B=B):
                    blk, worst = repartition(
                        _local(st), keys, n, bucket_rows=_B,
                        with_counts=True)
                    return _relocal(blk), worst

                step = jax.jit(shard_map(
                    go, mesh=self.mesh, in_specs=P(SHARD_AXIS),
                    out_specs=(P(SHARD_AXIS), P()),
                    check_vma=False,
                ))
                self._jit_cache[key] = step
            out, worst = step(stacked)
            # every attempt (including an overflow retry) was a real
            # mesh exchange — account its per-device bytes, and charge
            # the send/recv bucket capacity to the shuffle budget (an
            # overflow retry re-allocates GROWN buckets: each attempt
            # charges its own footprint)
            per_dev = exchange_bytes_per_device(stacked.schema, self.n, B)
            for d in range(self.n):
                timeline.add_bytes(f"shuffle_bytes_dev{d}", per_dev)
            if memsan.armed():
                memsan.charge(per_dev * self.n, "shuffle",
                              owner="repartition")
            w = int(np.asarray(worst))
            if w <= B:
                return self._tighten(out)
            B = shape_class(w)  # grace respill, sized by the observation

    def _tighten(self, stacked: TableBlock) -> TableBlock:
        """Slice a front-packed stacked block down to a tight capacity so
        join/shuffle output capacities do not compound across stages."""
        max_len = int(np.asarray(stacked.length).max())
        cap = _round_up(max_len)
        if cap >= stacked.capacity:
            return stacked
        cols = {
            n: Column(c.data[:, :cap], c.validity[:, :cap])
            for n, c in stacked.columns.items()
        }
        return TableBlock(cols, stacked.length, stacked.schema)

    # -- local joins --

    def _local_lookup(self, plan: LookupJoin, probe, build):
        key = ("lookup", plan.probe_keys, plan.build_keys, plan.payload,
               plan.kind, plan.suffix, probe.schema, build.schema,
               probe.capacity, build.capacity)
        step = self._jit_cache.get(key)
        if step is None:
            def go(pst, bst):
                # shared dispatch with the single-chip executor/DQ path
                # (lookup joins are jit-safe; no host retry involved)
                out = join_kernels.run_equi_join(
                    _local(pst), _local(bst), plan.probe_keys,
                    plan.build_keys, kind=plan.kind, suffix=plan.suffix,
                    payload=plan.payload)
                return _relocal(out)

            step = jax.jit(shard_map(
                go, mesh=self.mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                out_specs=P(SHARD_AXIS), check_vma=False,
            ))
            self._jit_cache[key] = step
        return self._tighten(step(probe, build))

    def _local_expand(self, plan: ExpandJoin, probe, build):
        cap = _round_up(max(int(probe.capacity * plan.fanout_hint), 1024))
        while True:
            key = ("expand", plan.probe_keys, plan.build_keys,
                   plan.probe_payload, plan.build_payload, plan.kind,
                   plan.build_suffix, probe.schema, build.schema,
                   probe.capacity, build.capacity, cap)
            step = self._jit_cache.get(key)
            if step is None:
                def go(pst, bst):
                    out, total = join_kernels.expand_join(
                        _local(pst), _local(bst),
                        list(plan.probe_keys), list(plan.build_keys),
                        list(plan.probe_payload), list(plan.build_payload),
                        out_capacity=cap, build_suffix=plan.build_suffix,
                        kind=plan.kind)
                    return _relocal(out), total[None]

                step = jax.jit(shard_map(
                    go, mesh=self.mesh,
                    in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    check_vma=False,
                ))
                self._jit_cache[key] = step
            out, totals = step(probe, build)
            worst = int(np.asarray(totals).max())
            if worst <= cap:
                return self._tighten(out)
            cap = _round_up(worst)

    # -- final transform (two-phase over the mesh) --

    def _transform(self, plan: Transform, memo, root: bool):
        stacked = self._exec(plan.input, memo)
        has_gb = plan.program.group_by is not None
        has_sort = any(isinstance(s, SortStep) for s in plan.program.steps)
        if any(isinstance(s, WindowStep) for s in plan.program.steps):
            # ranking windows need every row at once; a per-shard
            # elementwise run would rank within shards. Fall back to
            # the single-chip/DQ path.
            raise NotImplementedError("window function on the mesh")
        if not (has_gb or has_sort):
            # distributed elementwise transform: stays sharded
            key = ("xform", plan.program, plan.dict_aliases,
                   stacked.schema, stacked.capacity)
            step = self._jit_cache.get(key)
            if step is None:
                from ydb_tpu.ssa.compiler import compile_program

                cp = compile_program(
                    plan.program, stacked.schema, self.db.dicts,
                    self.db.key_spaces,
                    dict_aliases=dict(plan.dict_aliases))
                with memsan.seam("staging"):
                    aux = {k: jnp.asarray(v)
                           for k, v in cp.aux.items()}
                if memsan.armed():
                    memsan.charge(memsan.nbytes_of(aux), "staging",
                                  owner="xform_aux")

                def go(st):
                    return _relocal(cp.run(_local(st), aux))

                step = jax.jit(shard_map(
                    go, mesh=self.mesh, in_specs=P(SHARD_AXIS),
                    out_specs=P(SHARD_AXIS), check_vma=False,
                ))
                self._jit_cache[key] = step
            return self._tighten(step(stacked))
        if not root:
            raise NotImplementedError(
                "non-root aggregating Transform on the mesh")
        key = ("final", plan.program, plan.dict_aliases, stacked.schema,
               stacked.capacity)
        scan = self._jit_cache.get(key)
        if scan is None:
            scan = MeshScan(
                plan.program, stacked.schema, self.db.dicts,
                self.db.key_spaces, mesh=self.mesh,
                dict_aliases=dict(plan.dict_aliases),
            )
            self._jit_cache[key] = scan
        # MeshScan's step expects the partial program's read columns only
        return scan.run_stacked(stacked)


def _concat(blocks: list[TableBlock]) -> TableBlock:
    from ydb_tpu.blocks.block import concat_blocks

    return concat_blocks(blocks)
