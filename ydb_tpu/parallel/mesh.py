"""Device mesh construction — the TPU-native communication substrate.

The reference's cross-node transport is Interconnect (SURVEY.md §2.2): TCP
sessions + virtual channels between every node pair. The TPU build splits
that into two planes (SURVEY.md §5.8): bulk data rides XLA collectives over
the ICI mesh (this module + ydb_tpu.parallel.dist/shuffle); control traffic
stays on the host actor shim (ydb_tpu.runtime).

Mesh axes used by the engine:
  * ``shard`` — table-partition parallelism (the DP axis): each device owns
    a horizontal slice; scans/aggregations fan out here, partial states
    merge with psum/pmin/pmax, shuffles ride all_to_all.
  * ``pipe``  — optional stage-pipelining axis for multi-stage dataflows
    (kept size 1 until the DQ stage graph spans it).

On real hardware the shard axis should map contiguously onto the physical
ring so psum/all_to_all ride ICI neighbor links; jax's default device order
on TPU slices already does this.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

SHARD_AXIS = "shard"
PIPE_AXIS = "pipe"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map(..., check_vma=...)``; older
    releases only have ``jax.experimental.shard_map.shard_map`` whose
    replication check is spelled ``check_rep``. Every engine call site
    routes through this wrapper so the mesh runs on either."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy

    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def make_mesh(
    n_shards: int | None = None,
    n_pipe: int = 1,
    devices=None,
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if n_shards is None:
        n_shards = len(devices) // n_pipe
    need = n_shards * n_pipe
    if need > len(devices):
        raise ValueError(
            f"mesh {n_shards}x{n_pipe} needs {need} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(n_shards, n_pipe)
    return Mesh(arr, (SHARD_AXIS, PIPE_AXIS))


def shard_axis(mesh: Mesh) -> str:
    return SHARD_AXIS
