"""Sharded whole-plan fusion: ONE jitted SPMD computation per plan.

The tentpole of ROADMAP item 3: instead of a third parallel executor,
the PR 9 whole-plan lowering (ssa.plan_fuse) gets sharding annotations.
A fusible plan lowers ONCE into a ``jax.shard_map`` over the ``shard``
mesh axis — per-device scan fragments, ``all_to_all`` hash repartition
in front of every equi-join (parallel/shuffle), psum/gather
partial→final merges for the root aggregate (parallel/dist) — and jits
with donated staged inputs, exactly like "Query Processing on Tensor
Computation Runtimes" compiles whole queries to single sharded tensor
programs. One compiled executable per (plan fingerprint, shape-class
vector, mesh shape); a 1-device mesh degenerates to the single-chip
lowering verbatim (MeshLowering inherits PlanLowering's node hooks);
plans that do not lower fall back to the per-node mesh walk
(mesh_exec.MeshPlanExecutor) and from there to DQ/single-chip.

Shuffle buckets are STATS-SIZED (ISSUE 10 tentpole part 2): the send
bucket per destination is mean load × safety margin plus the
aggregator's count-min heaviest-hitter bound (shuffle.size_buckets),
shape-class rounded so same-class re-runs stay zero-retrace. The traced
worst per-destination count returns to the host with the expand-join
totals; overflow reuses the FusedPlan.grow protocol — the capacity is a
trace-time constant, so growing re-jits with the exact observed size
and the cached plan keeps it for later statements. Correct under 100%
skew, ~n_dev× fewer rows moved on uniform keys.

Results are bit-identical to the single-chip executor: row
partitioning only changes the ORDER partial states fold in, and every
merge is exact (int/decimal sums are int64 limb adds; MIN/MAX/COUNT are
order-free; AVG divides identical sums by identical counts in the
replicated final program).

Env gates: ``YDB_TPU_MESH_FUSE=0`` keeps the per-node mesh walk (A/B
escape hatch); ``YDB_TPU_SHUFFLE_STATS=0`` restores full-capacity
buckets; ``YDB_TPU_MESH=1`` (kqp.session) enables the mesh itself.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ydb_tpu.blocks.block import TableBlock, device_aux
from ydb_tpu.parallel import shuffle as shuffle_mod
from ydb_tpu.parallel.dist import (
    _gather_rows,
    _local,
    _merge_slots,
    merge_spec,
)
from ydb_tpu.parallel.mesh import SHARD_AXIS, shard_map
from ydb_tpu.plan.nodes import (
    Concat,
    ExpandJoin,
    LookupJoin,
    PlanNode,
    TableScan,
    Transform,
)
from ydb_tpu.ssa import join as join_kernels
from ydb_tpu.ssa import plan_fuse, twophase
from ydb_tpu.ssa.plan_fuse import (
    FusedPlan,
    PlanLowering,
    PlanSignature,
    Unfusible,
    expand_schema,
    lookup_schema,
    shape_class,
)
from ydb_tpu.ssa.program import SortStep, WindowStep

#: in-process override (bench/test A/B seam); None defers to the env
MESH_FUSE_FORCE: "bool | None" = None


def mesh_fusion_enabled() -> bool:
    if MESH_FUSE_FORCE is not None:
        return MESH_FUSE_FORCE
    return os.environ.get("YDB_TPU_MESH_FUSE", "1") not in (
        "0", "", "off")


def _walk(plan: PlanNode):
    stack = [plan]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (LookupJoin, ExpandJoin)):
            stack += [n.probe, n.build]
        elif isinstance(n, Transform):
            stack.append(n.input)
        elif isinstance(n, Concat):
            stack += list(n.inputs)


def _aggregating(program) -> bool:
    return (program is not None
            and (program.group_by is not None
                 or any(isinstance(s, (SortStep, WindowStep))
                        for s in program.steps)))


class _DeviceBound:
    """Facade scan source for plan_signature: per-DEVICE staging bound
    (max rows any one mesh device holds for the table), so shape
    classes — and the FUSE_MAX_ROWS cutoff — size per device, not per
    table. A mesh effectively raises the fusible-table ceiling to
    ndev × FUSE_MAX_ROWS."""

    def __init__(self, num_rows: int, schema):
        self.num_rows = num_rows
        self.schema = schema


class _FacadeDB:
    def __init__(self, sources, dicts, key_spaces):
        self.sources = sources
        self.dicts = dicts
        self.key_spaces = key_spaces


def mesh_signature(plan: PlanNode, db, ndev: int) -> PlanSignature | None:
    """Classify a plan for sharded fusion, None when it doesn't map.

    On top of plan_signature's fusibility rules, the mesh needs the
    ROOT to be a group-by Transform (its two-phase split is the only
    cross-device merge point) and every other program to be elementwise
    — a non-root aggregate or sort would need its own global merge.
    Windows need every row on one device; not mesh-fusible."""
    if not isinstance(plan, Transform):
        return None
    if plan.program.group_by is None:
        return None
    if any(isinstance(s, WindowStep) for s in plan.program.steps):
        return None
    fsources: dict = {}
    for node in _walk(plan):
        if isinstance(node, Transform) and node is not plan:
            if _aggregating(node.program):
                return None
        elif isinstance(node, TableScan):
            if _aggregating(node.program):
                return None  # per-device pushdown aggregate won't merge
            if node.table in fsources:
                continue
            if node.table not in db.sources:
                return None
            subs = db.sources[node.table]
            if not isinstance(subs, (list, tuple)) or not subs:
                return None
            per_dev = max(int(s.num_rows) for s in subs)
            fsources[node.table] = _DeviceBound(per_dev, subs[0].schema)
    return plan_fuse.plan_signature(
        plan, _FacadeDB(fsources, db.dicts, db.key_spaces))


class MeshLowering(PlanLowering):
    """PlanLowering with sharding: every emit runs device-local inside
    shard_map; joins repartition both sides over the shard axis first;
    the root transform merges two-phase partial states across the mesh.
    A 1-device mesh skips every collective and inherits the single-chip
    hooks unchanged — the degenerate case IS the base lowering."""

    def __init__(self, sig: PlanSignature, db, mesh, stats=None):
        super().__init__(sig, db)
        self.mesh = mesh
        self.ndev = int(mesh.shape[SHARD_AXIS])
        self.stats = stats or {}
        self.root = sig.plan
        # (cap slot, physical row bytes) per shuffle — the dispatch-time
        # byte accounting reads the CURRENT cap, so bucket grows show
        self.shuffle_rows: list[tuple[int, int]] = []

    # -- stats-sized shuffle slots (grow protocol, kind="shuffle") --

    def shuffle_slot(self, subtree_cap: int, keys, sch=None) -> int:
        heavy = shuffle_mod.heavy_bound(self.stats, keys)
        self.caps.append(shuffle_mod.size_buckets(
            subtree_cap, self.ndev, heavy=heavy))
        self.cap_kinds.append("shuffle")
        slot = len(self.caps) - 1
        if sch is not None:
            self.shuffle_rows.append((slot, shuffle_mod.row_bytes(sch)))
        return slot

    def _repart(self, block: TableBlock, keys, slot: int, totals):
        out, worst = shuffle_mod.repartition(
            block, list(keys), self.ndev,
            bucket_rows=self.caps[slot], with_counts=True)
        totals[slot] = worst
        return out

    def expand_total(self, total):
        # per-device match counts differ; the host must see the global
        # worst to grow once for everyone
        if self.ndev > 1:
            return jax.lax.pmax(total, SHARD_AXIS)
        return total

    # -- node hooks --

    def lower_lookup(self, node: LookupJoin):
        if self.ndev == 1:
            return super().lower_lookup(node)
        p_emit, p_sch, p_cap = self.lower(node.probe)
        b_emit, b_sch, b_cap = self.lower(node.build)
        sch = lookup_schema(node, p_sch, b_sch)
        pi = self.shuffle_slot(p_cap, node.probe_keys, p_sch)
        bi = self.shuffle_slot(b_cap, node.build_keys, b_sch)
        # after the exchange a device holds at most its receive buffer:
        # one stats-sized bucket from every peer
        out_cap = self.ndev * self.caps[pi]

        def emit(inputs, aux, memo, totals, _n=node, _pe=p_emit,
                 _be=b_emit, _pi=pi, _bi=bi):
            p = self._repart(_pe(inputs, aux, memo, totals),
                             _n.probe_keys, _pi, totals)
            b = self._repart(_be(inputs, aux, memo, totals),
                             _n.build_keys, _bi, totals)
            return join_kernels.run_equi_join(
                p, b, _n.probe_keys, _n.build_keys, kind=_n.kind,
                suffix=_n.suffix, payload=_n.payload)

        return emit, sch, out_cap

    def lower_expand(self, node: ExpandJoin):
        if self.ndev == 1:
            return super().lower_expand(node)
        p_emit, p_sch, p_cap = self.lower(node.probe)
        b_emit, b_sch, b_cap = self.lower(node.build)
        sch = expand_schema(node, p_sch, b_sch)
        pi = self.shuffle_slot(p_cap, node.probe_keys, p_sch)
        bi = self.shuffle_slot(b_cap, node.build_keys, b_sch)
        ei = self.expand_slot(self.ndev * self.caps[pi],
                              node.fanout_hint)
        caps = self.caps

        def emit(inputs, aux, memo, totals, _n=node, _pe=p_emit,
                 _be=b_emit, _pi=pi, _bi=bi, _ei=ei):
            p = self._repart(_pe(inputs, aux, memo, totals),
                             _n.probe_keys, _pi, totals)
            b = self._repart(_be(inputs, aux, memo, totals),
                             _n.build_keys, _bi, totals)
            out, total = join_kernels.expand_join(
                p, b, list(_n.probe_keys), list(_n.build_keys),
                list(_n.probe_payload), list(_n.build_payload),
                out_capacity=caps[_ei],
                build_suffix=_n.build_suffix, kind=_n.kind)
            totals[_ei] = self.expand_total(total)
            return out

        return emit, sch, self.caps[ei]

    def lower_transform(self, node: Transform):
        prog = node.program
        if any(isinstance(s, WindowStep) for s in prog.steps):
            raise Unfusible("window function on the mesh")
        if self.ndev == 1 or not _aggregating(prog):
            # 1-device mesh: the base (single-chip) lowering IS the
            # degenerate case; elementwise transforms stay device-local
            return super().lower_transform(node)
        if node is not self.root or prog.group_by is None:
            raise Unfusible("non-root aggregating Transform on the mesh")
        i_emit, i_sch, i_cap = self.lower(node.input)
        partial_prog, final_prog = twophase.split(
            prog, with_row_counts=True)
        aliases = dict(node.dict_aliases)
        p_run, p_cp = self.compiled(partial_prog, i_sch, self.db.dicts,
                                    dict_aliases=aliases,
                                    partial_slots=True)
        f_run = f_cp = None
        if final_prog is not None:
            f_run, f_cp = self.compiled(
                final_prog, p_cp.out_schema, self.db.dicts,
                dict_aliases={**aliases,
                              **twophase.dict_aliases(partial_prog)})
        layout = p_cp.group_layout[0]
        use_slots = layout in ("dense_slots", "keyless")
        merge_kinds, rank_tables = merge_spec(
            partial_prog, p_cp.out_schema, self.db.dicts)
        out_sch = f_cp.out_schema if f_cp is not None else p_cp.out_schema

        def emit(inputs, aux, memo, totals, _ie=i_emit, _pr=p_run,
                 _fr=f_run):
            part = _pr(_ie(inputs, aux, memo, totals), aux)
            # mirror MeshScan.merge_final exactly (bit-identity with the
            # per-node mesh walk and, through it, the single-chip path)
            if _fr is None:
                return _gather_rows(part)
            if use_slots:
                # slot-aligned states: elementwise psum/pmin/pmax — the
                # gradient-allreduce shape (dist._merge_slots)
                merged = _merge_slots(part, merge_kinds, rank_tables)
                if layout == "dense_slots" and "__rows" in merged.columns:
                    from ydb_tpu.ssa import kernels

                    live = merged.columns["__rows"].data > 0
                    merged = kernels.compact(
                        merged, live & merged.row_mask())
            else:
                # generic layouts: all_gather compacted partial rows,
                # re-aggregate replicated (the UnionAll-final shape)
                merged = _gather_rows(part)
            return _fr(merged, aux)

        return emit, out_sch, i_cap


class MeshFusedPlan(FusedPlan):
    """FusedPlan whose run_all is a shard_map over the mesh: staged
    inputs arrive sharded P(shard), the result and totals come back
    replicated. The grow protocol covers BOTH capacity kinds: expand
    joins grow quantum-rounded (exact retry), shuffle buckets grow to
    the shape class of the observed worst destination count."""

    def __init__(self, sites, out_schema, aux, run_all, caps, cap_kinds,
                 fused_stages, donate, mesh, ndev, shuffle_rows=()):
        self.cap_kinds = list(cap_kinds)
        self.mesh = mesh
        self.ndev = ndev
        self.shuffle_grows = 0  # lifetime counter (obs reports deltas)
        self.shuffle_rows = list(shuffle_rows)
        super().__init__(sites, out_schema, aux, run_all, caps,
                         fused_stages, donate)

    def run(self, inputs):
        out = super().run(inputs)
        # host-side movement accounting per dispatch: each shuffle
        # exchanged ndev buckets of the slot's CURRENT capacity from
        # every device (static shapes — grown buckets report grown
        # bytes on later dispatches)
        from ydb_tpu.analysis import memsan
        from ydb_tpu.obs import timeline

        for slot, rb in self.shuffle_rows:
            per_dev = self.ndev * self.expand_caps[slot] * rb
            for d in range(self.ndev):
                timeline.add_bytes(f"shuffle_bytes_dev{d}", per_dev)
            if memsan.armed():
                memsan.charge(per_dev * self.ndev, "shuffle",
                              owner="mesh_fused_dispatch")
        return out

    def shuffle_capacity(self) -> int:
        caps = [c for c, k in zip(self.expand_caps, self.cap_kinds)
                if k == "shuffle"]
        return max(caps) if caps else 0

    def grow(self, idx: int, total: int) -> None:
        if self.cap_kinds[idx] == "shuffle":
            self.expand_caps[idx] = shape_class(int(total))
            self.shuffle_grows += 1
            self._traced = False
            self._jit = self._make_jit()
        else:
            super().grow(idx, total)


def build(sig: PlanSignature, db, mesh, stats=None) -> MeshFusedPlan:
    """Compile a mesh-fusible plan into one sharded MeshFusedPlan (one
    ``ssa.compile`` span covers the whole build, like plan_fuse.build)."""
    from ydb_tpu.obs import tracing

    with tracing.span("ssa.compile") as sp:
        fused = _build(sig, db, mesh, stats)
        sp.set(fused_stages=fused.fused_stages,
               cols=sum(len(s.read_cols) for s in sig.sites),
               mesh_devices=fused.ndev)
    return fused


def _build(sig: PlanSignature, db, mesh, stats=None) -> MeshFusedPlan:
    lo = MeshLowering(sig, db, mesh, stats=stats)
    root, out_schema, _ = lo.lower(sig.plan)
    caps = lo.caps

    def device_fn(inputs, aux):
        totals: list = [jnp.int64(0)] * len(caps)
        local = {k: _local(b) for k, b in inputs.items()}
        out = root(local, aux, {}, totals)
        return out, tuple(totals)

    # the whole plan is ONE shard_map: scans and joins run device-local
    # on the P(shard)-sharded stage, collectives (all_to_all repartition,
    # psum/gather merges) are the only cross-device edges, and the root
    # result is replicated (out_specs=P()) — one XLA executable, fused
    # collectives, no host hops between fragments
    run_all = shard_map(
        device_fn, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return MeshFusedPlan(
        sig.sites, out_schema, device_aux(lo.aux_np), run_all, caps,
        lo.cap_kinds, sig.fused_stages, plan_fuse._DONATE, mesh, lo.ndev,
        shuffle_rows=lo.shuffle_rows)
