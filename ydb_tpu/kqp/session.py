"""Query-processor sessions: SQL in, results out.

The compact analog of the reference's KQP session path (SURVEY.md §3.2):
gRPC request → session actor → compile (cached) → execute. Here:

  * ``Cluster`` owns storage (blob store + coordinator + sharded tables)
    and the schema catalog — the in-process stand-in for a node's service
    set (driver_lib/run analog); the API layer (ydb_tpu.api) serves it
    over the wire
  * ``Session.execute(sql)`` parses, consults the per-cluster plan cache
    (keyed on SQL text — the compile-service LRU shape,
    kqp_compile_service.cpp), plans against the catalog, and runs the
    plan executor at a consistent read snapshot

DDL (CREATE TABLE) and DML (INSERT) execute directly against the state
plane with coordinated commits.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.blobs import BlobStore, MemBlobStore
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql import ast
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, PlanError, plan_select
from ydb_tpu.tx import Coordinator, ShardedTable
from ydb_tpu.tx.coordinator import TxResult

_TYPE_MAP = {
    "int8": dtypes.INT8, "int16": dtypes.INT16, "int32": dtypes.INT32,
    "int": dtypes.INT32, "int64": dtypes.INT64, "bigint": dtypes.INT64,
    "uint64": dtypes.UINT64, "float": dtypes.FLOAT, "double": dtypes.DOUBLE,
    "bool": dtypes.BOOL, "date": dtypes.DATE, "timestamp": dtypes.TIMESTAMP,
    "string": dtypes.STRING, "utf8": dtypes.STRING, "text": dtypes.STRING,
}


def _parse_type(t: str) -> dtypes.LogicalType:
    t = t.lower()
    if t.startswith("decimal"):
        if "(" in t:
            s = int(t.split(",")[1].rstrip(")"))
        else:
            s = 0
        return dtypes.decimal(s)
    if t in _TYPE_MAP:
        return _TYPE_MAP[t]
    raise PlanError(f"unknown type {t}")


class Cluster:
    """Storage + catalog + plan cache: one in-process database."""

    def __init__(
        self,
        store: BlobStore | None = None,
        n_shards: int = 4,
        plan_cache_size: int = 128,
    ):
        self.store = store if store is not None else MemBlobStore()
        self.coordinator = Coordinator()
        self.n_shards = n_shards
        self.tables: dict[str, ShardedTable] = {}
        self.dicts = DictionarySet()  # cluster-wide, shared by all tables
        self._plan_cache: OrderedDict = OrderedDict()
        self._plan_cache_size = plan_cache_size

    # ---- DDL / DML ----

    def create_table(self, stmt: ast.CreateTable) -> None:
        if stmt.table in self.tables:
            raise PlanError(f"table {stmt.table} already exists")
        fields = []
        for name, typ, not_null in stmt.columns:
            fields.append(dtypes.Field(name, _parse_type(typ), not not_null))
        schema = dtypes.Schema(tuple(fields))
        pk = stmt.primary_key[0] if stmt.primary_key else fields[0].name
        t = ShardedTable(
            stmt.table, schema, self.store, self.coordinator,
            n_shards=self.n_shards, pk_column=pk,
        )
        t.dicts = self.dicts
        for s in t.shards:
            s.dicts = self.dicts
        self.tables[stmt.table] = t
        self._plan_cache.clear()

    def insert(self, stmt: ast.Insert) -> TxResult:
        t = self.tables.get(stmt.table)
        if t is None:
            raise PlanError(f"unknown table {stmt.table}")
        names = stmt.columns or t.schema.names
        cols: dict[str, list] = {n: [] for n in names}
        validity: dict[str, list] = {n: [] for n in names}
        for row in stmt.rows:
            if len(row) != len(names):
                raise PlanError("row arity mismatch")
            for n, e in zip(names, row):
                v, ok = _literal_value(e, t.schema.field(n).type)
                cols[n].append(v)
                validity[n].append(ok)
        missing = [n for n in t.schema.names if n not in cols]
        if missing:
            raise PlanError(f"INSERT must set all columns; missing {missing}")
        arrays = {}
        for n in names:
            f = t.schema.field(n)
            if f.type.is_string:
                arrays[n] = cols[n]
            else:
                arrays[n] = np.asarray(cols[n], dtype=f.type.physical)
        val = {n: np.asarray(v, dtype=bool) for n, v in validity.items()}
        res = t.insert(arrays, val)
        # new dictionary entries may invalidate cached plan aux tables
        self._plan_cache.clear()
        return res

    # ---- query path ----

    def catalog(self) -> Catalog:
        return Catalog(
            schemas={n: t.schema for n, t in self.tables.items()},
            primary_keys={
                n: (t.pk_column,) for n, t in self.tables.items()
            },
            dicts=self.dicts,
        )

    def snapshot_db(self, snap: int | None = None) -> Database:
        snap = self.coordinator.read_snapshot() if snap is None else snap
        sources = {}
        for name, t in self.tables.items():
            merged = _merge_shard_sources(t, snap)
            sources[name] = merged
        return Database(sources=sources, dicts=self.dicts)

    def plan(self, sql: str):
        hit = self._plan_cache.get(sql)
        if hit is not None:
            self._plan_cache.move_to_end(sql)
            return hit
        stmt = parse(sql)
        if not isinstance(stmt, ast.Select):
            return stmt
        p = plan_select(stmt, self.catalog())
        self._plan_cache[sql] = p
        while len(self._plan_cache) > self._plan_cache_size:
            self._plan_cache.popitem(last=False)
        return p

    def session(self) -> "Session":
        return Session(self)


def _merge_shard_sources(t: ShardedTable, snap: int) -> ColumnSource:
    parts = [s.source_at(snap) for s in t.shards]
    cols = {
        n: np.concatenate([p.columns[n] for p in parts])
        for n in t.schema.names
    }
    validity = {}
    for n in t.schema.names:
        vs = [
            p.validity[n] if p.validity and n in p.validity
            else np.ones(len(p.columns[n]), dtype=bool)
            for p in parts
        ]
        validity[n] = np.concatenate(vs)
    return ColumnSource(cols, t.schema, t.dicts, validity)


def _literal_value(e: ast.Expr, t: dtypes.LogicalType):
    """Evaluate an INSERT literal to (physical value, validity)."""
    if isinstance(e, ast.Literal):
        if e.kind == "null":
            return (b"" if t.is_string else 0), False
        if e.kind == "string":
            if t.is_string:
                return e.value.encode(), True
            raise PlanError(f"string literal for {t}")
        if e.kind == "decimal":
            import decimal as pydec

            return int(
                pydec.Decimal(e.value).scaleb(t.scale).to_integral_value()
            ), True
        if e.kind in ("int", "bool"):
            if t.is_decimal:
                return int(e.value) * 10 ** t.scale, True
            return e.value, True
    if isinstance(e, ast.UnOp) and e.op == "neg":
        v, ok = _literal_value(e.operand, t)
        return -v, ok
    if isinstance(e, ast.FuncCall) and e.name == "date":
        return int(np.datetime64(e.args[0].value, "D").astype(np.int32)), True
    raise PlanError(f"unsupported INSERT value {e}")


@dataclasses.dataclass
class Session:
    """One client session (kqp_session_actor analog)."""

    cluster: Cluster

    def execute(self, sql: str):
        """Returns OracleTable for SELECT, TxResult for INSERT, None DDL."""
        planned = self.cluster.plan(sql)
        if isinstance(planned, ast.CreateTable):
            self.cluster.create_table(planned)
            return None
        if isinstance(planned, ast.Insert):
            return self.cluster.insert(planned)
        db = self.cluster.snapshot_db()
        return to_host(execute_plan(planned, db))
