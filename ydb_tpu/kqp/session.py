"""Query-processor sessions: SQL in, results out.

The compact analog of the reference's KQP session path (SURVEY.md §3.2):
gRPC request → session actor → compile (cached) → execute. Here:

  * ``Cluster`` owns storage (blob store + coordinator + sharded tables)
    and the schema catalog — the in-process stand-in for a node's service
    set (driver_lib/run analog); the API layer (ydb_tpu.api) serves it
    over the wire
  * ``Session.execute(sql)`` parses, consults the per-cluster plan cache
    (keyed on SQL text — the compile-service LRU shape,
    kqp_compile_service.cpp), plans against the catalog, and runs the
    plan executor at a consistent read snapshot

DDL (CREATE TABLE) and DML (INSERT) execute directly against the state
plane with coordinated commits.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.blobs import BlobStore, MemBlobStore
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql import ast
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import (
    Catalog,
    PlanError,
    plan_select,
    plan_select_full,
)
from ydb_tpu.analysis import host_ok as _host_ok
from ydb_tpu.analysis import leaksan as _leaksan
from ydb_tpu.analysis import memsan as _memsan
from ydb_tpu.analysis import syncsan as _syncsan
from ydb_tpu.obs.probes import probe as _probe
from ydb_tpu.tx import Coordinator, ShardedTable
from ydb_tpu.tx.coordinator import TxResult

import time as _time

_P_PLAN_CACHE = _probe("kqp.plan_cache")
_P_SLOW = _probe("query.slow")

# conveyor queue-depth histogram buckets (task counts, not seconds)
_DEPTH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

_TYPE_MAP = {
    "int8": dtypes.INT8, "int16": dtypes.INT16, "int32": dtypes.INT32,
    "int": dtypes.INT32, "int64": dtypes.INT64, "bigint": dtypes.INT64,
    "uint64": dtypes.UINT64, "float": dtypes.FLOAT, "double": dtypes.DOUBLE,
    "bool": dtypes.BOOL, "date": dtypes.DATE, "timestamp": dtypes.TIMESTAMP,
    "string": dtypes.STRING, "utf8": dtypes.STRING, "text": dtypes.STRING,
    # Kind.value spellings, so scheme.model.type_to_str output
    # round-trips back through DDL (DescribeTable -> CreateTable)
    "uint8": dtypes.UINT8, "uint16": dtypes.UINT16,
    "uint32": dtypes.UINT32, "float32": dtypes.FLOAT,
    "float64": dtypes.DOUBLE,
}


def _parse_type(t: str) -> dtypes.LogicalType:
    t = t.lower()
    if t.startswith("decimal"):
        if "(" in t:
            args = t[t.index("(") + 1:].rstrip(")").split(",")
            # decimal(p) = scale 0 (SQL standard); decimal(p,s)
            s = int(args[1]) if len(args) == 2 else 0
        else:
            s = 0
        return dtypes.decimal(s)
    if t in _TYPE_MAP:
        return _TYPE_MAP[t]
    raise PlanError(f"unknown type {t}")


def _find_page_cache(store, depth: int = 4):
    """Locate a pressure-reactive page cache in a (possibly wrapped)
    store: walks common wrapper attributes (CachedBlobStore.base,
    tiered hot/cold, failpoint inner)."""
    if store is None or depth < 0:
        return None
    if hasattr(store, "react_to_pressure"):
        return store
    for attr in ("base", "hot", "cold", "inner", "store"):
        found = _find_page_cache(getattr(store, attr, None), depth - 1)
        if found is not None:
            return found
    return None


def _process_rss() -> int:
    """Current resident set size in bytes (Linux /proc; real page
    size). 0 when unreadable — pressure reaction then stays idle
    rather than acting on a lying number (ru_maxrss is PEAK, not
    current, and platform-dependent in units)."""
    try:
        import resource

        with open("/proc/self/statm") as f:
            return (int(f.read().split()[1])
                    * resource.getpagesize())
    except OSError:
        return 0


class _BoundedCompileCache(dict):
    """LRU-bounded, lock-guarded dict for the cluster compile cache.

    Compiled entries pin XLA executables + device-resident aux arrays,
    and ad-hoc workloads mint a fresh key per distinct statement — an
    unbounded dict is a leak (same reasoning as the shard scan cache
    and the plan cache beside this one). Dict-compatible ``get`` /
    ``[]=`` so the plan executor and DQ stage compiler use it
    unchanged; the lock serializes the LRU bookkeeping against
    concurrent sessions (touch vs evict is the PR 3 race shape)."""

    def __init__(self, capacity: int = 256):
        super().__init__()
        self.capacity = max(1, capacity)
        import threading

        self._lock = threading.Lock()
        self._order: OrderedDict = OrderedDict()

    def get(self, key, default=None):
        with self._lock:
            if key in self._order:
                self._order.move_to_end(key)
            return dict.get(self, key, default)

    def __setitem__(self, key, value):
        with self._lock:
            dict.__setitem__(self, key, value)
            self._order[key] = None
            self._order.move_to_end(key)
            while len(self._order) > self.capacity:
                old, _ = self._order.popitem(last=False)
                dict.pop(self, old, None)

    def clear(self):
        with self._lock:
            dict.clear(self)
            self._order.clear()


class Cluster:
    """Storage + schema tablet + plan cache: one in-process database.

    The schema catalog is a real SchemeShard (ydb_tpu.scheme.shard) over
    a tablet executor on the same blob store as the data shards, so the
    entire database — schema AND data — reboots from the store alone:
    ``Cluster(store=same_store)`` after process death recovers every
    table. String dictionaries are cluster-shared (ids must agree across
    tables for joins), so their growth is journaled cluster-wide and
    replayed before any shard boots.
    """

    def __init__(
        self,
        store: BlobStore | None = None,
        n_shards: int | None = None,
        plan_cache_size: int | None = None,
        config=None,
    ):
        from collections import deque

        from ydb_tpu.config import AppConfig, ControlBoard
        from ydb_tpu.obs.counters import CounterGroup
        from ydb_tpu.obs.tracing import Tracer
        from ydb_tpu.scheme.shard import SchemeShardCore
        from ydb_tpu.tablet.executor import TabletExecutor

        self.config = config if config is not None else AppConfig()
        self.flags = self.config.feature_flags
        self.store = store if store is not None else MemBlobStore()
        self.n_shards = (n_shards if n_shards is not None
                         else self.config.n_shards)
        self.tables: dict[str, ShardedTable] = {}
        self.topics: dict = {}
        self.counters = CounterGroup({"component": "kqp"})
        self.tracer = Tracer()
        self.query_log: deque = deque(maxlen=256)
        # audit trail of state-changing statements (audit log analog,
        # ydb/core/audit; exposed through the sys_audit view)
        self.audit_log: deque = deque(maxlen=1024)
        # optional request-unit quoter (rate-limiter / kesus analog):
        # when set, every statement consumes 1 unit from "kqp/requests"
        self.quoter = None
        # usage metering (ydb/core/metering analog): request units
        # booked per statement, aggregatable per tenant/interval
        from ydb_tpu.obs.metering import Metering

        self.metering = Metering()
        # optional admission planes (kqp rm_service/workload_service):
        # when set, every statement passes pool admission and books a
        # compute slot for its duration
        self.workload = None
        self.rm = None
        # multi-tenant front door (serving/admission.py): when
        # installed via serving.install(cluster), every statement
        # acquires a per-tenant admission seat before the workload
        # pool, and shedding happens per tenant instead of through the
        # global max_inflight_statements valve
        self.front_door = None
        # optional SPMD mesh execution (enable_mesh)
        self._mesh_exec = None
        # HBM device block cache shared by every statement's scans (the
        # shared-page-cache analog; statement Databases are transient,
        # the cache is node-scoped)
        from ydb_tpu.engine.blockcache import DeviceBlockCache

        self.scan_block_cache = DeviceBlockCache()
        self._prune_stamp = None  # last pruned (shard, meta_gen) set
        # cross-query micro-batching dispatcher (the serving tier, see
        # kqp/batch.py + kqp/README.md): disarmed unless
        # YDB_TPU_BATCH_WINDOW_MS > 0, in which case compatible
        # concurrent SELECTs share one fused device dispatch
        from ydb_tpu.kqp.batch import BatchDispatcher

        self.batcher = BatchDispatcher()
        self._query_seq = 0
        import threading

        self._qid_lock = threading.Lock()
        # load-shedding limit on concurrently in-flight statements
        # (0 = unlimited): past it Session.execute fails fast with
        # OverloadedError instead of queueing unboundedly
        import os as _os

        self.max_inflight_statements = int(
            _os.environ.get("YDB_TPU_MAX_INFLIGHT", "0") or 0)
        # registered scalar UDFs: name -> (vectorized fn, result type)
        self.udfs: dict[str, tuple] = {}
        # durable sequence allocator (sequenceshard analog), lazily
        # booted on first CREATE SEQUENCE / nextval
        self._sequences = None
        # live-tunable knobs (immediate control board)
        self.icb = ControlBoard()
        self.icb.register("rmw_retries", 5, 1, 100)
        self.icb.register("compact_portion_threshold",
                          self.config.compact_portion_threshold, 2, 1024)
        self.icb.register("split_rows_per_shard",
                          self.config.split_rows_per_shard,
                          0, 1 << 40)
        self.dicts = DictionarySet()  # cluster-wide, shared by all tables
        # StatisticsAggregator service (ydb/core/statistics analog):
        # merges per-shard column sketches into table-level NDV/null
        # stats on the run_background cadence; snapshot/restore rides a
        # tablet executor on the SAME blob store, so a rebooted node
        # plans with persisted statistics while the first refresh runs
        from ydb_tpu.stats.aggregator import StatisticsAggregator

        self.stats = StatisticsAggregator(store=self.store)
        self._plan_cache: OrderedDict = OrderedDict()
        self._plan_cache_size = (
            plan_cache_size if plan_cache_size is not None
            else self.config.plan_cache_size)
        # node-scoped compiled-program cache shared by every statement's
        # Database (the computation-pattern cache across sessions): a
        # second run of the same SELECT reuses its jitted executors
        # instead of retracing, which is what makes warm-vs-cold
        # (compile-cache hit/miss) a measurable per-query attribute.
        # Invalidated with the plan cache (dict growth bakes into aux);
        # LRU-bounded — compiled entries pin XLA executables.
        self._compile_cache: dict = _BoundedCompileCache()
        # bounded ring of recent query profiles feeding last_profile,
        # sys_top_queries / sys_query_log and /viewer/json/query_profile
        from ydb_tpu.obs.profile import ProfileRing

        self.profiles = ProfileRing()
        # in-flight statement registry (sys_active_queries + the
        # query.slow watchdog): token -> {sql, start, stage, ...};
        # sessions register before admission and unregister in a
        # finally, so a failed statement always clears
        from ydb_tpu.analysis import sanitizer as _san

        self._active_lock = _san.make_lock(f"kqp.{id(self):x}.active")
        self.active_queries = _san.share(
            {}, f"kqp.{id(self):x}.active_queries")
        self._active_seq = 0
        # leak-sanitizer handle per registry row (guarded by
        # _active_lock; kept OUT of the row dicts, which snapshot APIs
        # copy); empty whenever the sanitizer is off
        self._active_leaks: dict[int, object] = {}
        self._dict_seq = 0
        self._dict_durable: dict[str, int] = {}
        self._replay_dict_journal()
        self.scheme = SchemeShardCore(
            TabletExecutor.boot("schemeshard", self.store))
        # finish any DROP TABLE whose blob deletion a crash interrupted
        self._sweep_trash()
        # data shards boot before the coordinator so its plan-step clock
        # can resume past every snapshot the shards have seen
        self.coordinator = Coordinator()
        for desc in self.scheme.list_tables():
            self._instantiate(desc, boot=True)
        max_snap = max(
            (s.snap for t in self.tables.values() for s in t.shards),
            default=0,
        )
        # durable clock: plan-step reservations persist in the store, so
        # a coordinator reboot resumes past every step it may have issued
        # even if some shard never saw it (coordinator__plan_step analog)
        self.coordinator = Coordinator(self.store, start_step=max_snap)
        for t in self.tables.values():
            t.coordinator = self.coordinator
            for s in t.shards:
                if hasattr(s, "snap_source"):
                    s.snap_source = self.coordinator.background_plan
        # finish any DROP COLUMN strip a crash interrupted (marker set
        # durably before the scheme alter committed)
        for path in self.scheme.pending_strips():
            t = self.tables.get(path.strip("/"))
            if t is not None and hasattr(t, "post_boot_sweep"):
                t.post_boot_sweep()
            self.scheme.clear_strip(path)
        # sweep shard generations orphaned by a crash mid-reshard (the
        # scheme descriptor is the cutover truth; anything else is trash)
        for t in self.tables.values():
            if hasattr(t, "sweep_stale_generations"):
                t.sweep_stale_generations()
        # mesh-by-default: YDB_TPU_MESH=1 routes eligible SELECTs SPMD
        # over the device mesh from boot (the same executor enable_mesh
        # installs). Last in __init__ — enable_mesh invalidates the plan
        # cache, which must exist by now.
        import os as _os

        if _os.environ.get("YDB_TPU_MESH", "0") not in ("0", "", "off"):
            self.enable_mesh()

    def _invalidate_plans(self) -> None:
        """Drop cached plans AND compiled executors together: both bake
        dictionary contents / schema shape into plan-time state."""
        self._plan_cache.clear()
        self._compile_cache.clear()

    def stop(self, timeout: float = 30.0) -> None:
        """Orderly node teardown (the driver_lib shutdown analog): stop
        the statistics cadence thread, wait for queued background work
        (promotions, prefetch, compaction tasks) to drain off the
        shared conveyor, then — under YDB_TPU_LEAKSAN — prove every
        tracked resource handle in the process drained to zero
        (:class:`~ydb_tpu.analysis.leaksan.LeakError` names survivors).
        Added for lifecycle rule R005: the cluster held the stoppable
        ``StatisticsAggregator`` with no stop path reachable at all.
        The drain check is process-global, so call it with no other
        cluster mid-statement (tests; single-node serving)."""
        self.stats.stop()
        from ydb_tpu.runtime.conveyor import shared_conveyor

        shared_conveyor().wait_idle(timeout=timeout)
        _leaksan.assert_drained(where="Cluster.stop")

    # ---- dict durability (cluster-wide journal) ----

    def _replay_dict_journal(self) -> None:
        for blob_id in self.store.list("cluster/dicts/"):
            import json

            delta = json.loads(self.store.get(blob_id).decode())
            for col, values in delta.items():
                d = self.dicts.for_column(col)
                for v in values:
                    d.add(v.encode("latin1"))
            self._dict_seq += 1
        for col in self.dicts.columns():
            self._dict_durable[col] = len(self.dicts[col])

    def _journal_dicts(self) -> None:
        import json

        delta = {}
        for col in self.dicts.columns():
            d = self.dicts[col]
            n0 = self._dict_durable.get(col, 0)
            if len(d) > n0:
                delta[col] = [v.decode("latin1") for v in d.values[n0:]]
                self._dict_durable[col] = len(d)
        if delta:
            self.store.put(f"cluster/dicts/{self._dict_seq:010d}",
                           json.dumps(delta).encode())
            self._dict_seq += 1

    # ---- DDL / DML ----

    def _instantiate(self, desc, boot: bool = False):
        from ydb_tpu.datashard.table import RowTable

        from ydb_tpu.engine.shard import ShardConfig

        shard_config = ShardConfig(
            compact_portion_threshold=self.config
            .compact_portion_threshold,
            checkpoint_interval=self.config.checkpoint_interval,
            scan_block_rows=self.config.scan_block_rows,
        )
        name = desc.path.strip("/")
        if desc.store == "row":
            t = RowTable(
                name, desc.schema, self.store, self.coordinator,
                n_shards=desc.n_shards,
                pk_columns=tuple(desc.primary_key),
                ttl_column=desc.ttl_column, dicts=self.dicts, boot=boot,
                gen=desc.shard_gen,
            )
        else:
            t = ShardedTable(
                name, desc.schema, self.store, self.coordinator,
                n_shards=desc.n_shards, pk_column=desc.primary_key[0],
                ttl_column=desc.ttl_column, dicts=self.dicts, boot=boot,
                config=shard_config, upsert=desc.upsert,
                gen=desc.shard_gen,
            )
        t.alter_schema(desc.schema, desc.schema_version, desc.column_added)
        # dict ids must be durable BEFORE any shard WAL references them:
        # a crash between the two would otherwise leave dangling ids
        t.pre_commit = self._journal_dicts
        self.tables[name] = t
        if desc.changefeed:
            from ydb_tpu.topic.topic import Topic

            topic = Topic(f"{name}_changefeed", self.store,
                          n_partitions=desc.n_shards)
            self.topics[f"{name}_changefeed"] = topic
            t.enable_cdc()
            t.changefeed_topic = topic
        return t

    def create_table(self, stmt: ast.CreateTable) -> None:
        from ydb_tpu.scheme.model import TableDescription
        from ydb_tpu.scheme.shard import SchemeError

        if stmt.table in self.tables:
            raise PlanError(f"table {stmt.table} already exists")
        if stmt.table.startswith("sys_"):
            # reserved: user tables must not shadow system views (and
            # the ACL read exemption for sys views must not become a
            # writable escape hatch)
            raise PlanError("the sys_ name prefix is reserved")
        fields = []
        for name, typ, not_null in stmt.columns:
            fields.append(dtypes.Field(name, _parse_type(typ), not not_null))
        schema = dtypes.Schema(tuple(fields))
        pk = stmt.primary_key or (fields[0].name,)
        opts = dict(stmt.options)
        unknown = set(opts) - {"shards", "store", "ttl_column",
                               "changefeed", "upsert"}
        if unknown:
            raise PlanError(f"unknown WITH option(s): {sorted(unknown)}")
        try:
            n_shards = int(opts.get("shards", self.n_shards))
        except ValueError:
            raise PlanError(f"WITH shards must be an integer, got "
                            f"{opts['shards']!r}") from None
        if n_shards < 1:
            raise PlanError("WITH shards must be >= 1")
        store_kind = opts.get("store", "column")
        if store_kind not in ("column", "row"):
            raise PlanError(f"WITH store must be column|row, "
                            f"got {store_kind!r}")
        if store_kind == "row" and not self.flags.enable_row_tables:
            raise PlanError("row tables are disabled by feature flag")
        if "ttl_column" in opts and opts["ttl_column"] not in schema:
            raise PlanError(f"ttl_column {opts['ttl_column']!r} not in "
                            f"schema")
        upsert = opts.get("upsert", "off") in ("on", "true", "1")
        if upsert and store_kind != "column":
            raise PlanError("upsert semantics apply to column tables"
                            " (row tables always upsert by PK)")
        changefeed = opts.get("changefeed", "off") in ("on", "true", "1")
        if changefeed and store_kind != "row":
            raise PlanError("changefeed requires a row-store table")
        if changefeed and not self.flags.enable_changefeeds:
            raise PlanError("changefeeds are disabled by feature flag")
        desc = TableDescription(
            path="/" + stmt.table,
            schema=schema,
            primary_key=tuple(pk),
            n_shards=n_shards,
            store=store_kind,
            ttl_column=opts.get("ttl_column"),
            changefeed=changefeed,
            upsert=upsert,
        )
        try:
            self.scheme.create_table(desc)
        except SchemeError as e:
            raise PlanError(str(e)) from e
        self._instantiate(desc)
        self._invalidate_plans()

    def drop_table(self, stmt: ast.DropTable) -> None:
        from ydb_tpu.scheme.shard import SchemeError

        t = self.tables.get(stmt.table)
        prefixes = t.storage_prefixes() if t is not None else []
        topic = self.topics.pop(f"{stmt.table}_changefeed", None)
        if topic is not None:
            prefixes += topic.storage_prefixes()
        try:
            # prefixes are recorded durably in the drop tx itself; the
            # boot sweep finishes deletion if we crash before it
            self.scheme.drop_table("/" + stmt.table,
                                   trash_prefixes=prefixes)
        except SchemeError as e:
            raise PlanError(str(e)) from e
        self.tables.pop(stmt.table, None)
        self._sweep_trash()
        self.stats.forget(
            stmt.table,
            [sh.shard_id for sh in getattr(t, "shards", ())
             if hasattr(sh, "shard_id")])
        self._invalidate_plans()
        # a re-created same-name table reuses shard ids AND restarts
        # portion ids at 1, so stale entries would collide with the new
        # table's keys and serve the dropped table's rows
        self.scan_block_cache.clear()
        # same portion-id-reuse hazard for the HBM-resident tier; the
        # dropped shards are unreachable, but free their device arrays
        # now rather than at GC
        for sh in getattr(t, "shards", ()):
            store = getattr(sh, "resident", None)
            if store is not None:
                store.clear()

    def _sweep_trash(self) -> None:
        for op_id, prefixes in self.scheme.trash():
            for prefix in prefixes:
                for blob_id in self.store.list(prefix):
                    self.store.delete(blob_id)
            self.scheme.clear_trash(op_id)

    def alter_table(self, stmt: ast.AlterTable) -> None:
        from ydb_tpu.scheme.shard import SchemeError

        t = self.tables.get(stmt.table)
        if t is None:
            raise PlanError(f"unknown table {stmt.table}")
        add = [dtypes.Field(n, _parse_type(ty), True)
               for n, ty in stmt.add_columns]
        row_strip = stmt.drop_columns and hasattr(t, "post_boot_sweep")
        if row_strip:
            # marker precedes the schema commit: a crash anywhere before
            # clear_strip re-runs the strip on next boot
            self.scheme.mark_strip("/" + stmt.table)
        try:
            desc = self.scheme.alter_table(
                "/" + stmt.table, add_columns=add,
                drop_columns=list(stmt.drop_columns))
        except SchemeError as e:
            if row_strip:
                self.scheme.clear_strip("/" + stmt.table)
            raise PlanError(str(e)) from e
        t.alter_schema(desc.schema, desc.schema_version, desc.column_added)
        if row_strip:
            self.scheme.clear_strip("/" + stmt.table)
        self._invalidate_plans()

    def run_background(self) -> dict:
        """One maintenance pass: table compaction/TTL + CDC drains (the
        conveyor/background-task plane, driven by the hosting layer).
        ICB knobs apply here, so live tuning takes effect without a
        restart."""
        threshold = self.icb.get("compact_portion_threshold")
        stats = {"cdc_shipped": 0, "compacted": 0, "splits": 0,
                 "merges": 0}
        for name, t in self.tables.items():
            topic = getattr(t, "changefeed_topic", None)
            if topic is not None:
                stats["cdc_shipped"] += t.drain_changes_to(topic)
            for s in t.shards:
                if hasattr(s, "config"):
                    s.config.compact_portion_threshold = threshold
            if hasattr(t, "run_background"):
                s = t.run_background()
                stats["compacted"] += s.get("compacted", 0)
        # statistics refresh rides the maintenance cadence (and fires
        # right after the compaction/commit churn above, so fresh
        # portions are sketched while their chunks are page-cache-warm);
        # incremental — only never-seen portions cost chunk reads. A
        # failed refresh never blocks maintenance: scan paths simply
        # degrade to unpruned reads until the next pass.
        try:
            self.stats.refresh_cluster(self)
            stats["stats_tables"] = len(self.stats.all_stats())
        except Exception:  # noqa: BLE001 - stats are advisory
            pass
        self._auto_reshard(stats)
        # resident-tier aggregate counters ride the maintenance cadence
        # (the /counters surface; per-shard detail stays in
        # sys_resident_store)
        res = {"bytes": 0, "portions": 0, "promotions": 0,
               "evictions": 0, "spills": 0, "hits": 0}
        have_res = False
        for t in self.tables.values():
            for s in t.shards:
                store = getattr(s, "resident", None)
                if store is None:
                    continue
                have_res = True
                snap = store.snapshot()
                for k in res:
                    res[k] += snap[k]
        if have_res:
            g = self.counters.group(component="resident")
            for k, v in res.items():
                g.counter(k).set(v)
            stats["resident_bytes"] = res["bytes"]
        # memory pressure: when the store is (or wraps) a shared page
        # cache, shrink its budget as process RSS approaches the soft
        # limit and restore it when pressure clears
        cache = _find_page_cache(self.store)
        limit = getattr(self.config, "memory_soft_limit_bytes", 0)
        rss = _process_rss()
        if cache is not None and limit and rss:
            stats["cache_pressure"] = cache.react_to_pressure(
                rss / limit)
        # conveyor queue telemetry: lifetime totals plus the depth
        # high-water mark and per-queue wait samples accumulated since
        # the previous pass (queue_stats drains/resets those)
        from ydb_tpu.runtime.conveyor import shared_conveyor

        qs = shared_conveyor().queue_stats()
        g = self.counters.group(component="conveyor")
        for k in ("submitted", "completed", "rejected", "depth",
                  "active", "workers", "max_depth"):
            g.counter(k).set(qs[k])
        g.histogram("queue_depth",
                    bounds=_DEPTH_BOUNDS).observe(float(qs["max_depth"]))
        for q, waits in qs["waits"].items():
            h = self.counters.group(
                component="conveyor", queue=q).histogram(
                    "queue_wait_seconds")
            for w in waits:
                h.observe(w)
        stats["conveyor_depth"] = qs["depth"]
        # data-movement byte counters (always-on, obs.timeline): bytes
        # read from blobs, decoded, staged to device, served resident,
        # and shuffled per device — the /counters movement surface
        from ydb_tpu.obs import timeline as _tl

        mv = _tl.movement_snapshot()
        if mv:
            g = self.counters.group(component="movement")
            for k, v in mv.items():
                if k.startswith("shuffle_bytes_dev"):
                    self.counters.group(
                        component="movement",
                        device=k[len("shuffle_bytes_dev"):],
                    ).counter("shuffle_bytes").set(v)
                else:
                    g.counter(k).set(v)
        # chaos telemetry (only when a scenario is armed): per-site
        # hit/fired counts, fallbacks taken and retry totals, under
        # component="chaos" so injected faults are auditable on the
        # same /counters surface as everything else
        from ydb_tpu import chaos

        cs = chaos.counters_snapshot()
        if cs:
            for site, st in cs.get("sites", {}).items():
                g = self.counters.group(component="chaos", site=site)
                g.counter("hits").set(st["hits"])
                g.counter("fired").set(st["fired"])
            for site, n in cs.get("fallbacks", {}).items():
                self.counters.group(
                    component="chaos",
                    site=site).counter("fallbacks").set(n)
            for site, n in cs.get("retries", {}).items():
                self.counters.group(
                    component="chaos",
                    site=site).counter("retries").set(n)
        # batching dispatcher telemetry (serving tier): batch/solo
        # counts, dedup-vs-stacked dispatch split, scan-share attach
        # rates and open-group depth, under component="batching"
        bt = self.batcher
        if bt.armed() or bt.batches or bt.solo:
            g = self.counters.group(component="batching")
            for k, v in bt.snapshot().items():
                g.counter(k).set(v)
            stats["batches"] = bt.batches
        # front-door tenancy telemetry: per-pool inflight/queued/
        # admitted/shed gauges under component="serving" (the admitted/
        # shed counters themselves are bumped inline at admission)
        if self.front_door is not None:
            for tname, row in self.front_door.snapshot().items():
                g = self.counters.group(component="serving",
                                        tenant=tname)
                for k in ("inflight", "queued"):
                    g.counter(k).set(row[k])
        # device-memory ledger (only when the footprint sanitizer is
        # armed): per-component live/peak bytes plus the process-wide
        # peak gauge under component="devmem" — the /counters twin of
        # sys_device_memory
        if _memsan.armed():
            for comp, t in _memsan.component_totals().items():
                g = self.counters.group(component="devmem",
                                        pool=comp)
                g.counter("live_bytes").set(t["live"])
                g.counter("peak_bytes").set(t["peak"])
                g.counter("charges").set(t["charges"])
                g.counter("releases").set(t["releases"])
                g.counter("evictions").set(t["evictions"])
            self.counters.group(component="devmem").counter(
                "global_peak_bytes").set(_memsan.global_peak())
            stats["devmem_peak_bytes"] = _memsan.global_peak()
        # slow-query watchdog over the in-flight registry
        stats["slow_queries"] = self.check_slow_queries()
        return stats

    # ---- live query introspection ----

    def _register_active(self, sql: str, t0: float,
                         tenant: str = "") -> int:
        """Enter a statement into the in-flight registry (before
        admission, so queued statements are visible). Returns the token
        the caller must hand to _unregister_active in a finally."""
        with self._active_lock:
            self._active_seq += 1
            tok = self._active_seq
            pos = sum(1 for e in self.active_queries.values()
                      if e["stage"] == "queued")
            self.active_queries[tok] = {
                "sql": sql, "start": t0, "stage": "queued",
                "queue_position": pos, "trace_id": 0, "kind": "",
                "rows": 0, "slow_fired": False,
                "batch_id": 0, "batch_size": 0, "shared_scan": 0,
                "tenant": tenant,
            }
            lk = _leaksan.track("session.active", sql[:60], owner=tok)
            if lk is not None:
                self._active_leaks[tok] = lk
        return tok

    def _update_active(self, tok: int, **fields) -> None:
        with self._active_lock:
            e = self.active_queries.get(tok)
            if e is not None:
                e.update(fields)

    def _unregister_active(self, tok: int) -> None:
        with self._active_lock:
            self.active_queries.pop(tok, None)
            if self._active_leaks:
                _leaksan.close(self._active_leaks.pop(tok, None))

    def active_query_snapshot(self) -> list[dict]:
        """Point-in-time view of in-flight statements (the
        sys_active_queries source), longest-running first."""
        now = _time.monotonic()
        with self._active_lock:
            entries = [dict(e) for e in self.active_queries.values()]
        for e in entries:
            e["elapsed_seconds"] = now - e.pop("start")
            e.pop("slow_fired", None)
        entries.sort(key=lambda e: -e["elapsed_seconds"])
        return entries

    def check_slow_queries(self) -> int:
        """Fire the query.slow probe for any in-flight statement past
        the YDB_TPU_SLOW_QUERY_SECONDS threshold (once per statement).
        Rides the run_background cadence; callable directly too."""
        import os as _os

        try:
            threshold = float(
                _os.environ.get("YDB_TPU_SLOW_QUERY_SECONDS", "") or 1.0)
        except ValueError:
            threshold = 1.0
        now = _time.monotonic()
        fired = 0
        with self._active_lock:
            for e in self.active_queries.values():
                if e["slow_fired"] or now - e["start"] < threshold:
                    continue
                e["slow_fired"] = True
                _P_SLOW.fire(
                    elapsed=round(now - e["start"], 3),
                    stage=e["stage"], sql=e["sql"][:120])
                fired += 1
        return fired

    def _auto_reshard(self, stats: dict) -> None:
        """Load-driven splits/merges from table statistics (the
        schemeshard__table_stats.cpp policy, miniaturized): rows/shard
        above the split threshold doubles shards; below threshold/8
        (hysteresis) halves them. Generation-cutover resharding keeps
        every step durable and query-transparent."""
        split_at = self.icb.get("split_rows_per_shard")
        if not split_at:
            return
        from ydb_tpu.obs.sysview import table_stats

        for name, st in table_stats(self).items():
            t = self.tables.get(name)
            rows = st.get("rows")
            if t is None or rows is None or not hasattr(t, "reshard"):
                continue
            if getattr(t, "upsert", False):
                # cheap portion-metadata counts include superseded
                # versions on upsert tables: acting on them would split
                # on version churn, not logical size
                continue
            if rows == 0:
                # empty = likely pre-split ahead of a bulk load; never
                # collapse it (the reference guards the same case with
                # MinPartitionsCount)
                continue
            n = len(t.shards)
            per_shard = rows / n
            floor = max(self.config.min_auto_shards, 1)
            if per_shard > split_at and n < self.config.max_auto_shards:
                self.reshard_table(name, min(n * 2,
                                             self.config.max_auto_shards))
                stats["splits"] += 1
            elif n > floor and per_shard < split_at / 8:
                self.reshard_table(name, max(n // 2, floor))
                stats["merges"] += 1

    def health(self) -> dict:
        from ydb_tpu.obs.sysview import health_check

        return health_check(self)

    # ---- row-store DML (UPDATE / DELETE) ----

    def _row_table(self, name: str):
        from ydb_tpu.datashard.table import RowTable

        t = self.tables.get(name)
        if t is None:
            raise PlanError(f"unknown table {name}")
        if not isinstance(t, RowTable):
            raise PlanError(
                f"{name} is a column-store table; UPDATE/DELETE need a "
                f"row table (CREATE TABLE ... WITH (store = row))")
        return t

    @_host_ok("row DML readback: plans an uncached derived SELECT and"
              " fetches matching rows to host — the row store operates"
              " on host rows by design")
    def _select_rows(self, table, extra_items, where, snap):
        """Run SELECT pk..., extra... FROM table WHERE ... through the
        normal plan/execute path at the given snapshot."""
        items = [ast.SelectItem(ast.Name((c,)), f"__pk_{i}")
                 for i, c in enumerate(table.pk_columns)]
        items += extra_items
        sel = ast.Select(
            items=tuple(items),
            from_=ast.TableRef(table.name, None),
            where=where, group_by=(), having=None, order_by=(),
            limit=None,
        )
        p = plan_select(sel, self.catalog())
        out = to_host(execute_plan(p, self.snapshot_db(snap, mesh=False)))
        n = out.num_rows
        keys = [
            tuple(int(out.column(f"__pk_{i}")[r])
                  for i in range(len(table.pk_columns)))
            for r in range(n)
        ]
        return out, keys

    def update(self, stmt: ast.Update) -> TxResult:
        t = self._row_table(stmt.table)
        for name, _ in stmt.sets:
            if name not in t.schema:
                raise PlanError(f"no column {name}")
            if name in t.pk_columns:
                raise PlanError(f"cannot UPDATE key column {name}")
        # optimistic read-modify-write: lock, read at snapshot, write
        # under the lock; a conflicting commit in between breaks the
        # lock, prepare aborts the 2PC, and the whole RMW retries
        for _attempt in range(self.icb.get("rmw_retries")):
            locks = t.lock_all_shards()
            try:
                res = self._update_once(t, stmt, locks)
            finally:
                t.release_locks(locks)
            if res.committed or not (res.error or "").startswith(
                    "prepare"):
                return res
        raise PlanError(
            f"UPDATE {stmt.table} kept aborting on concurrent writes")

    def _update_once(self, t, stmt: ast.Update,
                     locks: dict[int, int]) -> TxResult:
        snap = self.coordinator.read_snapshot()
        ops = self.update_ops(t, stmt, snap)
        if not ops:
            return TxResult(0, snap, True)
        return t._commit_ops(ops, lock_ids=locks)

    @_host_ok("row DML read-modify-write: per-row SET application and"
              " dictionary re-encoding are host row work by design")
    def _update_rows(self, t, stmt: ast.Update, snap: int):
        """Rows with the SET effects applied, read at ``snap``."""
        # constant SET values evaluate directly (string literals cannot
        # ride the device plan — they'd be bare dict ids); computed
        # expressions run through the normal SELECT path
        const_sets: dict[str, tuple] = {}
        copy_sets: list[tuple[str, str]] = []  # target <- source column
        computed: list[tuple[str, ast.Expr]] = []
        for name, e in stmt.sets:
            lit = e
            f = t.schema.field(name)
            if isinstance(lit, (ast.Literal,)) or (
                    isinstance(lit, ast.UnOp) and lit.op == "neg" and
                    isinstance(lit.operand, ast.Literal)):
                v, ok = _literal_value(lit, f.type)
                if ok and f.type.is_string:
                    v = int(self.dicts.for_column(name).add(v))
                const_sets[name] = (v, ok)
            elif f.type.is_string:
                # dict ids are per-column: a cross-column copy must
                # decode in the source dictionary and re-encode in the
                # target's — raw id passthrough would alias wrong values
                if isinstance(e, ast.Name) and e.column in t.schema and \
                        t.schema.field(e.column).type.is_string:
                    copy_sets.append((name, e.column))
                else:
                    raise PlanError(
                        f"UPDATE SET {name} = <expr>: string columns "
                        f"support literals or another string column")
            else:
                computed.append((name, e))
        extra = [ast.SelectItem(e, f"__set_{i}")
                 for i, (_n, e) in enumerate(computed)]
        out, keys = self._select_rows(t, extra, stmt.where, snap)
        current = t.read_rows(keys, snap)  # one batched read per shard
        rows = []
        for r, key in enumerate(keys):
            row = current.get(key)
            if row is None:
                continue
            row = dict(row)
            for name, (v, ok) in const_sets.items():
                row[name] = v if ok else None
            for name, src in copy_sets:
                sid = row.get(src)
                if sid is None:
                    row[name] = None
                else:
                    value = self.dicts[src].decode(
                        np.asarray([sid], dtype=np.int32))[0]
                    row[name] = int(self.dicts.for_column(name).add(value))
            for i, (name, _e) in enumerate(computed):
                col = out.column(f"__set_{i}")
                ok = bool(out.validity(f"__set_{i}")[r])
                if not ok:
                    row[name] = None
                else:
                    row[name] = _coerce(
                        col[r], out.schema.field(f"__set_{i}").type,
                        t.schema.field(name).type)
            rows.append(row)
        return rows

    def update_ops(self, t, stmt: ast.Update, snap: int):
        """The UPDATE's row effects as RowOps, uncommitted (the
        interactive-transaction buffering seam)."""
        from ydb_tpu.datashard.shard import RowOp

        rows = self._update_rows(t, stmt, snap)
        return [RowOp(t._key_of(r), r) for r in rows]

    def delete_ops(self, t, stmt: ast.Delete, snap: int):
        from ydb_tpu.datashard.shard import RowOp

        _out, keys = self._select_rows(t, [], stmt.where, snap)
        return [RowOp(tuple(k), None) for k in keys]

    def delete(self, stmt: ast.Delete) -> TxResult:
        t = self._row_table(stmt.table)
        for _attempt in range(self.icb.get("rmw_retries")):
            locks = t.lock_all_shards()
            try:
                res = self._delete_once(t, stmt, locks)
            finally:
                t.release_locks(locks)
            if res.committed or not (res.error or "").startswith(
                    "prepare"):
                return res
        raise PlanError(
            f"DELETE {stmt.table} kept aborting on concurrent writes")

    def _delete_once(self, t, stmt: ast.Delete,
                     locks: dict[int, int]) -> TxResult:
        snap = self.coordinator.read_snapshot()
        ops = self.delete_ops(t, stmt, snap)
        if not ops:
            return TxResult(0, snap, True)
        return t._commit_ops(ops, lock_ids=locks)

    @property
    def sequences(self):
        if self._sequences is None:
            from ydb_tpu.tablet.kesus import SequenceShard

            with self._qid_lock:  # double-boot would fork the journal
                if self._sequences is None:
                    self._sequences = SequenceShard("cluster",
                                                    self.store)
        return self._sequences

    def insert(self, stmt: ast.Insert) -> TxResult:
        t, arrays, val = self._insert_arrays(stmt)
        res = t.insert(arrays, val)  # journals dict growth via pre_commit
        # new dictionary entries may invalidate cached plan aux tables
        self._invalidate_plans()
        return res

    def insert_ops(self, stmt: ast.Insert):
        """The INSERT's effects as (table, RowOps), uncommitted (the
        interactive-transaction buffering seam; row tables only)."""
        t, arrays, val = self._insert_arrays(stmt)
        if not hasattr(t, "insert_ops"):
            raise PlanError(
                f"interactive transactions support row tables; "
                f"{stmt.table} is a column table")
        self._invalidate_plans()
        return t, t.insert_ops(arrays, val)

    def _insert_arrays(self, stmt: ast.Insert):
        t = self.tables.get(stmt.table)
        if t is None:
            raise PlanError(f"unknown table {stmt.table}")
        names = stmt.columns or t.schema.names
        cols: dict[str, list] = {n: [] for n in names}
        validity: dict[str, list] = {n: [] for n in names}
        for row in stmt.rows:
            if len(row) != len(names):
                raise PlanError("row arity mismatch")
            for n, e in zip(names, row):
                if isinstance(e, ast.FuncCall) and \
                        e.name == "nextval":
                    # volatile per-row default from the durable
                    # sequence allocator (kqp sequencer analog)
                    if len(e.args) != 1 or not (
                            isinstance(e.args[0], ast.Literal)
                            and e.args[0].kind == "string"):
                        raise PlanError(
                            "nextval needs a sequence name literal")
                    arg = e.args[0]
                    cols[n].append(self.sequences.next_val(arg.value))
                    validity[n].append(True)
                    continue
                v, ok = _literal_value(e, t.schema.field(n).type)
                cols[n].append(v)
                validity[n].append(ok)
        missing = [n for n in t.schema.names if n not in cols]
        if missing:
            raise PlanError(f"INSERT must set all columns; missing {missing}")
        arrays = {}
        for n in names:
            f = t.schema.field(n)
            if f.type.is_string:
                arrays[n] = cols[n]
            else:
                arrays[n] = np.asarray(cols[n], dtype=f.type.physical)
        val = {n: np.asarray(v, dtype=bool) for n, v in validity.items()}
        return t, arrays, val

    def reshard_table(self, name: str, n_shards: int) -> int:
        """Split/merge a table (column OR row store) to ``n_shards``
        shards: stream-copy into a new shard generation, journal the
        cutover in the scheme (the durable commit point), then GC the
        old generation. Returns the new generation."""
        t = self.tables.get(name)
        if t is None:
            raise PlanError(f"unknown table {name}")
        if n_shards < 1:
            # validate BEFORE the destructive copy/swap, not after
            raise PlanError("n_shards must be >= 1")
        old_n = len(t.shards)
        old_gen = t.gen
        old_ids = [sh.shard_id for sh in getattr(t, "shards", ())
                   if hasattr(sh, "shard_id")]
        new_gen = t.reshard(n_shards)
        # durable cutover: after this journal entry a reboot sees the
        # new generation; before it, the new blobs are swept as orphans
        self.scheme.reshard_table("/" + name, n_shards, new_gen)
        t.drop_generation_storage(old_gen, old_n)
        # the old generation's per-portion sketches can never be read
        # again (generation-scoped shard ids); free them now and let
        # the next refresh rebuild the table's stats from gen+1
        self.stats.forget(name, old_ids)
        self._invalidate_plans()
        return new_gen

    # ---- query path ----

    def catalog(self) -> Catalog:
        from ydb_tpu.obs.sysview import SYS_SCHEMAS, table_stats

        schemas = {n: t.schema for n, t in self.tables.items()}
        pks = {n: (t.pk_column,) for n, t in self.tables.items()}
        if self.flags.enable_sys_views:
            for name, schema in SYS_SCHEMAS.items():
                schemas.setdefault(name, schema)
                pks.setdefault(name, (schema.names[0],))
        # statistics feed for CBO-lite join ordering (cheap: portion
        # metadata only, no scans)
        counts = {
            n: st["rows"] for n, st in table_stats(self).items()
            if st["rows"] is not None
        }
        return Catalog(schemas=schemas, primary_keys=pks,
                       dicts=self.dicts, row_counts=counts,
                       table_stats=self.stats.all_stats(),
                       udfs=dict(self.udfs))

    def _stmt_scalar_exec(self, stmt_db: list, snap: int | None = None,
                          access_check=None):
        """Scalar-subquery executor bound to ONE statement snapshot
        (lazily created into ``stmt_db[0]``): the KQP precompute-phase
        analog, shared by SELECT planning and EXPLAIN. ``snap`` pins
        the snapshot (interactive transactions pass their BEGIN
        snapshot so sub- and outer query read the same state);
        ``access_check`` gates each subquery plan before it reads."""
        def scalar_exec(plan_node, t):
            if access_check is not None:
                access_check(plan_node)
            if stmt_db[0] is None:
                stmt_db[0] = self.snapshot_db(
                    snap, include_sys=self.flags.enable_sys_views,
                    mesh=False)
            out = to_host(execute_plan(plan_node, stmt_db[0]))
            col = out.schema.names[0]
            v, ok = out.cols[col]
            if len(v) != 1:
                raise PlanError(
                    f"scalar subquery returned {len(v)} rows")
            return v[0].item(), bool(ok[0])

        return scalar_exec

    def enable_mesh(self, mesh=None) -> None:
        """Route eligible SELECTs SPMD over the device mesh: every
        statement's snapshot Database carries a MeshPlanExecutor whose
        per-device sources are the tables' shard streams grouped onto
        the mesh (parallel/mesh_exec.device_partitions). The executor
        (and its jit cache) persists across statements; per-statement
        state is only the snapshot source map."""
        from ydb_tpu.parallel.mesh_exec import (
            MeshDatabase,
            MeshPlanExecutor,
        )

        self._mesh_exec = MeshPlanExecutor(
            MeshDatabase({}, dicts=self.dicts), mesh)
        # per-device resident slices: each columnshard's HBM tier binds
        # to the mesh device that scans it, so mesh dispatches read
        # device-resident columns without a cross-device pull
        self._assign_resident_slices()
        self._invalidate_plans()

    def disable_mesh(self) -> None:
        if self._mesh_exec is not None:
            from ydb_tpu.engine import resident as resident_mod

            for t in self.tables.values():
                stores = [s.resident for s in getattr(t, "shards", ())
                          if getattr(s, "resident", None) is not None]
                resident_mod.clear_device_slices(stores)
        self._mesh_exec = None

    def _assign_resident_slices(self) -> None:
        """Round-robin each table's shard ResidentStores onto the mesh
        devices — the SAME grouping device_partitions applies to scan
        sources, so resident columns live where their rows compute."""
        from ydb_tpu.engine import resident as resident_mod

        mex = self._mesh_exec
        devices = [d[0] for d in mex.mesh.devices]  # (shard, pipe) grid
        for t in self.tables.values():
            stores = [s.resident for s in getattr(t, "shards", ())
                      if getattr(s, "resident", None) is not None]
            if stores:
                resident_mod.assign_device_slices(stores, mex.n,
                                                  devices=devices)

    def _mesh_snapshot(self, snap: int):
        """A PER-SNAPSHOT MeshPlanExecutor: fresh source bindings (so
        concurrent statements never read each other's snapshot) sharing
        the cluster executor's jit cache. Sources build lazily per table
        — a statement touching one table doesn't pay partitioning for
        the whole catalog."""
        from ydb_tpu.parallel.mesh_exec import (
            MeshDatabase,
            MeshPlanExecutor,
        )

        base = self._mesh_exec
        cluster = self
        # tables created since enable_mesh get their resident slices
        # here (idempotent re-binding for the rest)
        self._assign_resident_slices()

        class _Lazy(dict):
            def __missing__(self, key):
                from ydb_tpu.datashard.table import RowTable
                from ydb_tpu.engine.reader import PortionStreamSource
                from ydb_tpu.parallel.mesh_exec import device_partitions

                t = cluster.tables[key]
                if isinstance(t, RowTable):
                    shards = [t.source_at(snap)]
                else:
                    shards = [
                        PortionStreamSource(s, s.visible_portions(snap))
                        for s in t.shards
                    ]
                parts = device_partitions(shards, base.n, t.schema,
                                          cluster.dicts)
                self[key] = parts
                return parts

            def __contains__(self, key):  # eligibility probes ([] builds)
                return (dict.__contains__(self, key)
                        or key in cluster.tables)

        ex = MeshPlanExecutor(
            MeshDatabase(_Lazy(), dicts=self.dicts,
                         # aggregator stats size the stats-sized shuffle
                         # buckets (count-min heavy-hitter bound)
                         table_stats=self.stats.all_stats()),
            base.mesh)
        ex._jit_cache = base._jit_cache
        return ex

    def register_udf(self, name: str, fn, out_type) -> None:
        """Register a scalar UDF: ``fn`` takes numpy arrays (one per SQL
        argument) and returns an array; usable in any expression."""
        self.udfs[name.lower()] = (fn, out_type)
        self._invalidate_plans()

    def snapshot_db(self, snap: int | None = None,
                    include_sys: bool = False,
                    mesh: bool = True) -> Database:
        """``mesh=False`` keeps internal point reads (UPDATE/DELETE RMW
        pk-selects, scalar-subquery precompute) off the SPMD mesh path —
        a tiny lookup must not pay device collectives while holding
        shard locks."""
        from ydb_tpu.datashard.table import RowTable

        snap = self.coordinator.read_snapshot() if snap is None else snap
        self._prune_scan_cache()
        sources = {}
        for name, t in self.tables.items():
            if isinstance(t, RowTable):
                sources[name] = t.source_at(snap)
            else:
                sources[name] = _merge_shard_sources(t, snap)
        if include_sys:
            sources = _SysLazySources(self, sources)
        db = Database(sources=sources, dicts=self.dicts)
        db.block_cache = self.scan_block_cache
        # compiled programs persist across statements (the node-scoped
        # pattern cache): the second run of a SELECT is a compile-cache
        # hit — warm execute only, no retrace
        db._compile_cache = self._compile_cache
        # aggregator statistics ride into the executor for DQ join
        # sizing (fanout estimates); cached dict, no refresh on the
        # statement path
        db.table_stats = self.stats.all_stats()
        if mesh and self._mesh_exec is not None:
            db.mesh_executor = self._mesh_snapshot(snap)
        return db

    def _prune_scan_cache(self) -> None:
        """Free cluster-cache entries pinned by GC'd portions.

        ColumnShard.scan prunes its per-shard cache before every scan;
        the cluster-scoped ``scan_block_cache`` (keyed by
        MultiShardStreamSource.device_cache_key: per-shard visible
        portion-id tuples) had no such hook — under compaction/TTL
        churn, entries naming vanished portions could pin HBM until LRU
        pressure. Snapshotting a Database is the natural choke point:
        every statement passes through it, and an entry referencing a
        portion absent from the live portion maps can never be keyed
        again by any future snapshot."""
        if not len(self.scan_block_cache):
            return
        if self.scan_block_cache.budget() <= 0:
            # the operator's emergency valve (YDB_TPU_SCAN_CACHE_BYTES=0)
            # closed mid-process: entries cached under the earlier budget
            # can never be served again, so free the HBM outright
            self.scan_block_cache.clear()
            return
        # portions only vanish on GC (meta_gen bumps) or reshard (the
        # shard set changes): while the stamp is stable there is nothing
        # to prune, so the per-statement steady state stays O(shards)
        stamp = tuple(
            (s.shard_id, getattr(s, "meta_gen", 0))
            for t in self.tables.values()
            for s in getattr(t, "shards", ()))
        if stamp == self._prune_stamp:
            return
        live: dict[str, set] = {}
        for t in self.tables.values():
            for s in getattr(t, "shards", ()):
                portions = getattr(s, "portions", None)
                if portions is None:
                    continue
                lock = getattr(s, "_meta_lock", None)
                if lock is not None:
                    with lock:
                        pids = set(portions)
                else:
                    pids = set(portions)
                live.setdefault(s.shard_id, set()).update(pids)

        def alive(key) -> bool:
            try:
                return all(
                    sid in live and live[sid].issuperset(pids)
                    for sid, pids in key[0])
            except (TypeError, ValueError, IndexError):
                return True  # unknown key shape: never drop blindly
        self.scan_block_cache.prune(alive)
        self._prune_stamp = stamp

    def plan(self, sql: str, snap: int | None = None,
             access_check=None):
        """``snap`` pins the statement snapshot (an interactive
        transaction's BEGIN snapshot): scalar subqueries precompute
        against it, and such plans never enter the cache.
        ``access_check(plan_node)`` gates plan-time subquery execution
        (ACL enforcement happens BEFORE any table is read)."""
        from ydb_tpu.obs import tracing

        if snap is None and access_check is None:
            hit = self._plan_cache.get(sql)
            if hit is not None:
                if _P_PLAN_CACHE:
                    _P_PLAN_CACHE.fire(hit=True)
                tracing.annotate(plan_cache="hit")
                self._plan_cache.move_to_end(sql)
                return hit
            if _P_PLAN_CACHE:
                _P_PLAN_CACHE.fire(hit=False)
            tracing.annotate(plan_cache="miss")
        with tracing.span("parse"):
            stmt = parse(sql)
        if isinstance(stmt, ast.Explain):
            # EXPLAIN precomputes scalar subqueries exactly like
            # execution would (same guards, same single snapshot), so
            # the rendered plan is the plan the engine would run.
            # ANALYZE additionally executes it, so the statement db and
            # dict aliases ride along for the dispatch path.
            stmt_db: list = [None]
            pq = plan_select_full(
                stmt.select, self.catalog(),
                self._stmt_scalar_exec(stmt_db, snap, access_check))
            return ("explain", pq.plan, dict(pq.dict_aliases),
                    stmt_db[0], stmt.analyze)
        if not isinstance(stmt, (ast.Select, ast.UnionAll)):
            return stmt

        # one snapshot Database for the whole statement: scalar-subquery
        # precompute and (if any ran) the outer execution read the same
        # state, preserving statement-level read consistency
        stmt_db: list = [None]
        pq = plan_select_full(
            stmt, self.catalog(),
            self._stmt_scalar_exec(stmt_db, snap, access_check))
        entry = (pq.plan, dict(pq.dict_aliases), stmt_db[0])
        if not pq.used_scalar_exec and snap is None \
                and access_check is None:
            # plans with baked-in subquery results (or pinned to a tx
            # snapshot) are snapshot-bound: never serve from the cache
            self._plan_cache[sql] = entry
            while len(self._plan_cache) > self._plan_cache_size:
                self._plan_cache.popitem(last=False)
        return entry

    def result_dicts(self, out_schema, alias_map: dict) -> DictionarySet:
        """Per-result dictionary view: each output string column bound
        to its SOURCE column's dictionary (aliases included), so decode
        never guesses by output name."""
        view = DictionarySet()
        for f in out_schema.fields:
            if f.type.is_string:
                src = alias_map.get(f.name, f.name)
                if src in self.dicts:
                    view._dicts[f.name] = self.dicts[src]
        return view

    def session(self) -> "Session":
        return Session(self)


class _SysLazySources(dict):
    """Sys views materialize only when a query actually reads them —
    sys_partition_stats walks every shard, far too hot for the default
    SELECT path."""

    def __init__(self, cluster, base: dict):
        super().__init__(base)
        self._cluster = cluster

    def __missing__(self, key):
        from ydb_tpu.obs.sysview import SYS_SCHEMAS, sys_source

        if key not in SYS_SCHEMAS:
            raise KeyError(key)
        src = sys_source(self._cluster, key)
        self[key] = src
        return src


def _merge_shard_sources(t: ShardedTable, snap: int):
    """Streaming scan source over all shards at a snapshot: SELECTs read
    through the portion/blob/merge path (engine.reader), never a
    materialized table — dedup under upsert included."""
    from ydb_tpu.engine.reader import MultiShardStreamSource

    return MultiShardStreamSource(t.shards, t.schema, t.dicts, snap)


def _coerce(value, from_t: dtypes.LogicalType, to_t: dtypes.LogicalType):
    """Physical value conversion for UPDATE SET results."""
    v = value
    if to_t.is_decimal:
        if from_t.is_decimal:
            return int(v) * 10 ** (to_t.scale - from_t.scale) \
                if to_t.scale >= from_t.scale else \
                int(int(v) // 10 ** (from_t.scale - to_t.scale))
        if from_t.is_floating:
            return int(round(float(v) * 10 ** to_t.scale))
        return int(v) * 10 ** to_t.scale
    if to_t.is_floating:
        if from_t.is_decimal:
            return float(v) / 10 ** from_t.scale
        return float(v)
    if to_t.is_string:
        return int(v)  # dict id flows through unchanged
    return int(v)


def _literal_value(e: ast.Expr, t: dtypes.LogicalType):
    """Evaluate an INSERT literal to (physical value, validity)."""
    if isinstance(e, ast.Literal):
        if e.kind == "null":
            return (b"" if t.is_string else 0), False
        if e.kind == "string":
            if t.is_string:
                return e.value.encode(), True
            raise PlanError(f"string literal for {t}")
        if e.kind == "decimal":
            if t.is_floating:
                # fractional literal into a float/double column: the
                # decimal-scaling path would round 0.5 to integral 0
                return float(e.value), True
            import decimal as pydec

            return int(
                pydec.Decimal(e.value).scaleb(t.scale).to_integral_value()
            ), True
        if e.kind in ("int", "bool"):
            if t.is_decimal:
                return int(e.value) * 10 ** t.scale, True
            return e.value, True
    if isinstance(e, ast.UnOp) and e.op == "neg":
        v, ok = _literal_value(e.operand, t)
        return -v, ok
    if isinstance(e, ast.FuncCall) and e.name == "date":
        return int(np.datetime64(e.args[0].value, "D").astype(np.int32)), True
    raise PlanError(f"unsupported INSERT value {e}")


@dataclasses.dataclass
class Session:
    """One client session (kqp_session_actor analog).

    Interactive transactions (BEGIN/COMMIT/ROLLBACK): effects buffer
    on the session and apply in ONE atomic (cross-table) commit at
    COMMIT; statements inside the transaction read the BEGIN snapshot
    (the deferred-effect model — uncommitted effects are not visible,
    including to the transaction itself). Conflict detection is
    optimistic full-table locks taken at first touch of each written
    table: any concurrent commit to a touched table after that point
    breaks the lock and COMMIT aborts (the client retries)."""

    cluster: Cluster
    _tx: dict | None = None
    # authenticated principal (the auth token); None = internal
    # session, exempt from ACL checks
    principal: str | None = None
    # workload pool this session's statements admit under (serving/
    # tenants.py); None = resolve through the front door registry
    # (principal binding or the default pool)
    tenant: str | None = None
    # QueryProfile of the most recent statement (None with profiling
    # disabled — YDB_TPU_PROFILE=0)
    last_profile: object = None

    def execute(self, sql: str, trace_id: int | None = None,
                timeout: float | None = None):
        """Returns OracleTable for SELECT, TxResult for INSERT, None DDL.

        ``timeout`` is the statement deadline in seconds: it bounds the
        admission wait AND rides the dispatching thread (and every
        conveyor task submitted under it) as a
        :class:`~ydb_tpu.chaos.deadline.Deadline`, so scans, fused
        dispatches and DQ pumps cancel cooperatively at their block
        boundaries. Expiry raises ``StatementCancelled`` and the
        statement lands in ``sys_top_queries`` with ``error=1``,
        ``error_reason="cancelled"``.
        """
        import time as _time

        from ydb_tpu import chaos
        from ydb_tpu.chaos import deadline as _dl
        from ydb_tpu.kqp.rm import OverloadedError

        c = self.cluster
        if c.quoter is not None and not c.quoter.try_acquire(
                "kqp/requests"):
            from ydb_tpu.runtime.quoter import ThrottledError

            c.counters.group(kind="throttled").counter("queries").inc()
            raise ThrottledError("request rate limit exceeded")
        t0 = _time.monotonic()  # BEFORE admission: queue wait is part
        # of the latency operators observe
        # load shedding BEFORE the statement enters the registry: past
        # the configured in-flight limit the cluster fails fast with a
        # typed error instead of queueing unboundedly. The chaos
        # "session.admit" site injects the same overload. With a front
        # door installed the per-tenant caps are the shedding boundary
        # and this global valve is only a legacy backstop.
        limit = c.max_inflight_statements
        shed = limit > 0 and len(c.active_queries) >= limit
        fault = None if shed else chaos.hit("session.admit")
        if fault is not None:
            fault.sleep()
            shed = shed or fault.kind == "overload"
        if shed:
            c.counters.group(kind="overloaded").counter("queries").inc()
            self._record_rejected(sql, t0, "overloaded")
            raise OverloadedError(
                f"statement shed at admission "
                f"({len(c.active_queries)} in flight, limit {limit})"
                if limit else "statement shed at admission (injected)")
        statement_dl = _dl.Deadline(timeout) if timeout is not None \
            else None
        fd = c.front_door
        tenant = fd.registry.resolve(tenant=self.tenant,
                                     principal=self.principal) \
            if fd is not None else (self.tenant or "")
        # the statement enters the live registry BEFORE admission so
        # sys_active_queries shows queued statements too; the finally
        # guarantees it clears even when execution raises
        tok = c._register_active(sql, t0, tenant=tenant)
        seat = None
        try:
            qid = None
            if c.workload is not None or c.rm is not None:
                with c._qid_lock:
                    c._query_seq += 1
                    qid = f"q{c._query_seq}"
            deadline = t0 + 30.0
            if statement_dl is not None:
                # the statement deadline caps the admission wait too
                deadline = min(deadline, statement_dl.at)
            if fd is not None:
                # per-tenant seat: the front door queues (deadline-
                # ordered) against THIS tenant's cap and sheds with the
                # pool named, so one tenant's backlog never starves
                # another's admission
                try:
                    seat = fd.admit(
                        tenant,
                        deadline_at=(statement_dl.at
                                     if statement_dl is not None
                                     else None),
                        timeout=max(0.0, deadline - _time.monotonic()),
                        owner=tok)
                except OverloadedError:
                    c.counters.group(
                        kind="overloaded").counter("queries").inc()
                    self._record_rejected(sql, t0, "overloaded")
                    raise
            pool = tenant if fd is not None else "default"
            if c.workload is not None:
                # pool admission: run now or condition-wait our queued
                # turn
                if not c.workload.admit(qid, pool=pool) and not \
                        c.workload.wait_admitted(
                            qid, pool=pool,
                            timeout=deadline - _time.monotonic()):
                    c.workload.finish(qid, pool=pool)
                    from ydb_tpu.kqp.rm import PoolOverloaded

                    self._record_rejected(sql, t0, "overloaded")
                    raise PoolOverloaded("admission wait timed out")
            # from here the pool admission is HELD: a single try/finally
            # owns BOTH planes, so any exception between admission and
            # the compute-slot grant (not just the ResourceExhausted
            # retry timeout) releases the pool entry — an unexpected
            # error here used to strand qid in the pool's running set
            # forever, wedging its admission slot
            granted = False
            try:
                if c.rm is not None:
                    # the two planes' limits are independent: a
                    # pool-admitted query still waits (not fails) for a
                    # compute slot
                    from ydb_tpu.kqp.rm import ResourceExhausted

                    while True:
                        try:
                            c.rm.acquire(qid, slots=1)
                            granted = True
                            break
                        except ResourceExhausted:
                            if _time.monotonic() > deadline:
                                self._record_rejected(sql, t0,
                                                      "overloaded")
                                raise
                            _time.sleep(0.002)
                with _dl.activate(statement_dl):
                    return self._execute_admitted(sql, trace_id, t0,
                                                  active_tok=tok)
            finally:
                if granted:
                    c.rm.release(qid)
                if c.workload is not None:
                    c.workload.finish(qid, pool=pool)
        finally:
            if seat is not None:
                seat.release()
            c._unregister_active(tok)
            # statement-completion drain check: under YDB_TPU_LEAKSAN
            # every handle owned by this statement (its registry row,
            # its compute-slot grant) must be closed by now — one bool
            # test per hook when the sanitizer is off
            _leaksan.assert_drained(owner=tok,
                                    where="statement completion")
            if qid is not None:
                _leaksan.assert_drained(owner=qid,
                                        where="statement completion")

    def _record_rejected(self, sql: str, t0: float, reason: str) -> None:
        """Statements rejected BEFORE execution (shed/admission
        timeout) still surface in sys_top_queries as typed errors —
        operators diagnosing an overload need to see WHAT was shed."""
        import time as _time

        from ydb_tpu.obs import tracing

        if not tracing.profiling_enabled():
            return
        from ydb_tpu.obs.profile import QueryProfile

        p = QueryProfile(sql=sql, kind="error", query_class="error",
                         seconds=_time.monotonic() - t0, error=1,
                         error_reason=reason)
        self.last_profile = p
        self.cluster.profiles.add(p)

    def _execute_admitted(self, sql: str, trace_id: int | None = None,
                          t0: float | None = None,
                          active_tok: int | None = None):
        import contextlib
        import time as _time

        from ydb_tpu.obs import tracing

        c = self.cluster
        if t0 is None:
            t0 = _time.monotonic()
        # profiling on (default): the root span is ACTIVATED so every
        # layer below — planner, executor, scans, DQ tasks, conveyor
        # prefetch producers — threads children under this trace id.
        # YDB_TPU_PROFILE=0 keeps the root/plan/execute spans (the
        # pre-profile surface) but skips activation: no child spans, no
        # attribute computation anywhere below, no profile assembly.
        prof = tracing.profiling_enabled()

        def act(sp):
            return tracing.activate(sp) if prof \
                else contextlib.nullcontext()

        planned = None
        kind = "error"
        span = None
        _ss = None
        _ms = None
        # the batching dispatcher stamps batch_id/batch_size onto this
        # statement's registry row; sessions run one statement at a time
        self._active_tok = active_tok
        try:
            with c.tracer.trace("query", trace_id) as span:
                # syncsan window covers plan+execute+fetch: transfers,
                # blocking syncs and XLA compiles attribute to THIS
                # statement (conveyor workers resolve via the trace id)
                _ss = _syncsan.begin_statement(
                    sql, trace_id=span.trace_id, span=span)
                # memsan window rides the same bounds: device-byte
                # charges (staging/stack/dispatch/shuffle/resident)
                # attribute to THIS statement, and its warm budget
                # enforces on close just like syncsan's
                _ms = _memsan.begin_statement(
                    sql, trace_id=span.trace_id, span=span)
                c._update_active(active_tok, stage="plan",
                                 trace_id=span.trace_id)
                with act(span):
                    with span.child("plan") as plan_span:
                        with act(plan_span):
                            planned = c.plan(
                                sql,
                                snap=(self._tx["snap"]
                                      if self._tx else None),
                                access_check=(
                                    self._plan_access_check
                                    if self.principal is not None
                                    else None))
                        if not isinstance(planned, tuple):
                            kind = type(planned).__name__.lower()
                        elif planned[0] == "explain":
                            kind = "explain"
                        else:
                            kind = "select"
                        plan_span.set(kind=kind)
                    span.set(kind=kind)
                    c._update_active(active_tok, stage="execute",
                                     kind=kind)
                    with span.child("execute") as exec_span:
                        with act(exec_span):
                            out = self._dispatch(planned)
                # totals attach BEFORE the root span finishes: a
                # finished span is visible to exporter threads, whose
                # attrs iteration must never race a late set()
                seconds = _time.monotonic() - t0
                rows = out.num_rows if isinstance(out, OracleTable) \
                    else 0
                span.set(seconds=round(seconds, 6), rows=rows)
                # close BEFORE the root span finishes so the syncsan_*
                # attrs land on a live span (same exporter-race rule as
                # the totals above); a budget breach raises here and
                # surfaces as a statement error
                _syncsan.end_statement(_ss)
                _memsan.end_statement(_ms)
        except BaseException as e:
            _syncsan.discard(_ss)
            _memsan.discard(_ms)
            # statements that fail MID-EXECUTION still land in the
            # profile ring tagged error=1 plus a typed reason
            # ("cancelled" for deadline expiry, "overloaded" for
            # shedding, else the error type), so sys_top_queries and
            # the viewer show them instead of silently dropping the
            # evidence (the root span finished with its error attr
            # when the with-block unwound)
            seconds = _time.monotonic() - t0
            c.counters.group(kind="error").counter("queries").inc()
            if prof and span is not None:
                reason = getattr(type(e), "reason", "") \
                    or type(e).__name__
                self._finish_profile(planned, sql, kind, span, seconds,
                                     0, error=1, reason=reason)
            raise
        c._update_active(active_tok, stage="done", rows=rows)
        c.query_log.append({"sql": sql, "kind": kind,
                            "seconds": seconds, "rows": rows})
        if kind != "select":
            # DDL/DML are audited; reads are not (the reference's
            # audit_log records modifying operations by default)
            c.audit_log.append({
                "kind": kind, "sql": sql[:256], "status": "ok",
                "duration_us": int(seconds * 1e6),
            })
        g = c.counters.group(kind=kind)
        g.counter("queries").inc()
        g.histogram("latency_seconds").observe(seconds)
        if prof:
            self._finish_profile(planned, sql, kind, span, seconds,
                                 rows)
        if c.metering is not None:
            from ydb_tpu.obs.metering import request_units

            c.metering.record(f"kqp.{kind}",
                              request_units(kind, rows))
        return out

    def _finish_profile(self, planned, sql: str, kind: str, span,
                        seconds: float, rows: int,
                        error: int = 0, reason: str = "") -> None:
        """Assemble the statement's QueryProfile from its finished span
        tree; feed last_profile, the profile ring and the per-query-
        class latency histogram (with p50/p99 gauges beside it, the
        numbers the serving-tier bench reads off /counters)."""
        from ydb_tpu.obs.profile import build_profile, classify_plan, \
            subtree

        c = self.cluster
        qc = kind
        if isinstance(planned, tuple):
            if planned[0] == "explain":
                qc = "explain"
            else:
                qc = classify_plan(planned[0])
        # scope to THIS statement's span subtree: a client-propagated
        # trace_id is shared across statements, and folding the whole
        # trace would re-sum earlier statements' spans into this one
        trace = c.tracer.spans_for(span.trace_id)
        scoped = [span] + subtree(trace, span.span_id)
        profile = build_profile(
            scoped, sql=sql, kind=kind,
            query_class=qc, seconds=seconds, rows=rows)
        fd = c.front_door
        tenant = fd.registry.resolve(tenant=self.tenant,
                                     principal=self.principal) \
            if fd is not None else (self.tenant or "")
        profile.tenant = tenant
        profile.error = error
        profile.error_reason = reason
        self.last_profile = profile
        c.profiles.add(profile)
        if error:
            # failed statements stay out of the per-class latency
            # surface (their seconds measure the failure, not the
            # query class) — the ring entry is the record
            return
        if profile.compile_cache:
            c.counters.group(kind="compile_cache").counter(
                profile.compile_cache).inc()
        g = c.counters.group(query_class=qc)
        h = g.histogram("query_latency_seconds")
        h.observe(seconds)
        # percentile GAUGES beside the raw histogram: scrapers without
        # histogram_quantile support (and the bench) read these directly
        g.counter("query_latency_p50").set(round(h.percentile(0.5), 9))
        g.counter("query_latency_p99").set(round(h.percentile(0.99), 9))
        if tenant:
            # the per-tenant SLO surface: same histogram + percentile
            # gauges, labeled by pool, so /counters/prometheus exposes
            # each tenant's p50/p99 and the isolation tests read the
            # victim's percentiles directly
            tg = c.counters.group(tenant=tenant, query_class=qc)
            th = tg.histogram("query_latency_seconds")
            th.observe(seconds)
            tg.counter("query_latency_p50").set(
                round(th.percentile(0.5), 9))
            tg.counter("query_latency_p99").set(
                round(th.percentile(0.99), 9))

    def _check_access(self, perm: str, *paths: str) -> None:
        """ACL gate (scheme ACEs with subtree inheritance): enforced
        for authenticated principals once any ACE exists; internal
        (principal-less) sessions and ACL-less clusters pass."""
        if self.principal is None:
            return
        scheme = self.cluster.scheme
        if not scheme.acl_enabled():
            return
        for path in paths:
            if perm == "read" and path.lstrip("/").startswith("sys_"):
                continue  # sys VIEWS are readable; only reads exempt
            if not scheme.check_access(self.principal, path, perm):
                raise PlanError(
                    f"access denied: {self.principal!r} lacks "
                    f"{perm!r} on {path}")

    def _plan_access_check(self, plan_node) -> None:
        self._check_access(
            "read", *("/" + t for t in self._plan_tables(plan_node)))

    @staticmethod
    def _plan_tables(node) -> set[str]:
        """Table names referenced by a plan (TableScan leaves)."""
        from ydb_tpu.plan.nodes import TableScan

        out: set[str] = set()
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, TableScan):
                out.add(n.table)
                continue
            for f in getattr(n, "__dataclass_fields__", {}):
                v = getattr(n, f)
                if hasattr(v, "__dataclass_fields__"):
                    stack.append(v)
        return out

    def _dispatch(self, planned):
        if isinstance(planned, ast.Begin):
            if self._tx is not None:
                raise PlanError("a transaction is already open")
            self._tx = {
                "snap": self.cluster.coordinator.read_snapshot(),
                "locks": {},   # table name -> {shard idx: lock id}
                "ops": {},     # table name -> (table, [RowOp]) ordered
            }
            return None
        if isinstance(planned, ast.Commit):
            return self._tx_commit()
        if isinstance(planned, ast.Rollback):
            self._tx_release()
            return None
        if isinstance(planned, ast.CreateSequence):
            self._no_tx("DDL")
            self._check_access("ddl", "/" + planned.name)
            self.cluster.sequences.create_sequence(
                planned.name, start=planned.start,
                increment=planned.increment, cache=planned.cache)
            return None
        if isinstance(planned, ast.DropSequence):
            self._no_tx("DDL")
            self._check_access("ddl", "/" + planned.name)
            self.cluster.sequences.drop_sequence(planned.name)
            return None
        if isinstance(planned, ast.CreateTable):
            self._no_tx("DDL")
            self._check_access("ddl", "/" + planned.table)
            self.cluster.create_table(planned)
            return None
        if isinstance(planned, ast.DropTable):
            self._no_tx("DDL")
            self._check_access("ddl", "/" + planned.table)
            self.cluster.drop_table(planned)
            return None
        if isinstance(planned, ast.AlterTable):
            self._no_tx("DDL")
            self._check_access("ddl", "/" + planned.table)
            self.cluster.alter_table(planned)
            return None
        if isinstance(planned, ast.Insert):
            self._check_access("write", "/" + planned.table)
            if self._tx is not None:
                t, ops = self.cluster.insert_ops(planned)
                self._tx_buffer(planned.table, t, ops)
                return None
            return self.cluster.insert(planned)
        if isinstance(planned, ast.Update):
            self._check_access("write", "/" + planned.table)
            if self._tx is not None:
                t = self.cluster._row_table(planned.table)
                self._tx_lock(planned.table, t)
                ops = self.cluster.update_ops(t, planned,
                                              self._tx["snap"])
                self._tx_buffer(planned.table, t, ops)
                return None
            return self.cluster.update(planned)
        if isinstance(planned, ast.Delete):
            self._check_access("write", "/" + planned.table)
            if self._tx is not None:
                t = self.cluster._row_table(planned.table)
                self._tx_lock(planned.table, t)
                ops = self.cluster.delete_ops(t, planned,
                                              self._tx["snap"])
                self._tx_buffer(planned.table, t, ops)
                return None
            return self.cluster.delete(planned)
        if planned[0] == "explain":
            from ydb_tpu.plan.nodes import format_plan

            # EXPLAIN reveals schema/plan shape: same read gate as
            # executing the query would have
            self._plan_access_check(planned[1])
            if len(planned) > 4 and planned[4]:
                return self._explain_analyze(planned)
            return format_plan(planned[1])
        p, alias_map, plan_db = planned
        self._check_access(
            "read", *("/" + t for t in self._plan_tables(p)))
        db = self._statement_db(plan_db)
        from ydb_tpu.obs import tracing

        blk = self._execute_select(p, db)
        with tracing.span("fetch"):
            # device -> host result transfer is its own phase: on a
            # tunneled accelerator it can dominate small results
            out = to_host(blk)
        out.dicts = self.cluster.result_dicts(out.schema, alias_map)
        return out

    def _execute_select(self, p, db) -> "TableBlock":
        """Plan execution behind the batching dispatcher: when armed
        (YDB_TPU_BATCH_WINDOW_MS > 0), compatible concurrent statements
        ride ONE shared fused device dispatch (kqp/batch.py); None from
        the batcher — disarmed, unbatchable plan, or a window that
        closed with a single member — falls through to the unchanged
        serial path (mesh -> DQ -> fused -> walk)."""
        batcher = self.cluster.batcher
        if batcher.armed():
            blk = batcher.execute(
                p, db, cluster=self.cluster,
                active_tok=getattr(self, "_active_tok", None))
            if blk is not None:
                return blk
        return execute_plan(p, db)

    def _statement_db(self, plan_db) -> Database:
        """The Database a statement executes against — ONE set of
        snapshot rules shared by SELECT and EXPLAIN ANALYZE (which must
        measure under exactly the semantics the query would run with):
        reuse the plan-time snapshot when scalar subqueries precomputed
        against it (statement-level read consistency), else the BEGIN
        snapshot inside a transaction (repeatable read), else fresh."""
        if plan_db is not None:
            return plan_db
        if self._tx is not None:
            return self.cluster.snapshot_db(
                self._tx["snap"],
                include_sys=self.cluster.flags.enable_sys_views)
        return self.cluster.snapshot_db(
            include_sys=self.cluster.flags.enable_sys_views)

    def _explain_analyze(self, planned) -> str:
        """EXPLAIN ANALYZE: run the query for real (same snapshot rules
        as a SELECT), then render the plan annotated with the measured
        actuals — per-stage seconds, pruning/row counts and the
        compile-vs-execute split. Two consecutive runs separate the
        compile-cache miss (first) from warm execute (second)."""
        import time as _time

        from ydb_tpu.obs import tracing
        from ydb_tpu.obs.profile import build_profile, classify_plan, \
            format_plan_analyzed, subtree

        _, p, _aliases, plan_db, _an = planned
        db = self._statement_db(plan_db)
        t0 = _time.monotonic()
        snap = None
        msnap = None
        _ss = None
        _ms = None
        try:
            with tracing.span("analyze") as asp:
                # nested syncsan/memsan windows (thread-local
                # attribution only — the outer statement keeps the
                # trace-id registry entry) so the rendered actuals
                # carry THIS run's host-boundary and device-byte
                # counters; measurement never enforces the warm
                # budget, the outer statement window does
                _ss = _syncsan.begin_statement("<analyze>")
                _ms = _memsan.begin_statement("<analyze>")
                out = to_host(self._execute_select(p, db))
                snap = _syncsan.end_statement(_ss, enforce=False)
                _ss = None
                msnap = _memsan.end_statement(_ms, enforce=False)
                _ms = None
        finally:
            if _ss is not None:
                _syncsan.discard(_ss)
            if _ms is not None:
                _memsan.discard(_ms)
        seconds = _time.monotonic() - t0
        spans = []
        if asp.recording:
            spans = subtree(
                self.cluster.tracer.spans_for(asp.trace_id),
                asp.span_id)
        profile = build_profile(
            spans, kind="explain", query_class=classify_plan(p),
            seconds=seconds, rows=out.num_rows)
        if snap is not None:
            profile.syncsan = snap
        if msnap is not None:
            profile.memsan = msnap
        return format_plan_analyzed(p, profile)

    # -- interactive transaction plumbing --

    def _no_tx(self, what: str) -> None:
        if self._tx is not None:
            self._tx_release()
            raise PlanError(
                f"{what} inside a transaction aborts it (unsupported)")

    def _tx_lock(self, name: str, t) -> None:
        if name in self._tx["locks"]:
            return
        locks = t.lock_all_shards()
        # the lock starts protecting NOW, but the tx reads the BEGIN
        # snapshot: a commit that landed in between would be silently
        # clobbered by full-row buffered writes (lost update). Close
        # the window like the statement path's lock-before-read does:
        # abort if the table moved past the snapshot before the lock.
        if any(shard.last_step > self._tx["snap"]
               for shard in t.shards):
            t.release_locks(locks)
            self._tx_release()
            raise PlanError(
                f"transaction aborted: {name} changed after BEGIN "
                "(retry the transaction)")
        self._tx["locks"][name] = locks

    def _tx_buffer(self, name: str, t, ops) -> None:
        self._tx_lock(name, t)
        entry = self._tx["ops"].setdefault(name, (t, []))
        entry[1].extend(ops)

    def _tx_release(self) -> None:
        tx, self._tx = self._tx, None
        if tx is None:
            return
        for name, locks in tx["locks"].items():
            table = self.cluster.tables.get(name)
            if table is not None:
                table.release_locks(locks)

    def _tx_commit(self):
        tx = self._tx
        if tx is None:
            raise PlanError("no open transaction")
        try:
            participants, prepare_args = [], []
            try:
                for name, (t, ops) in tx["ops"].items():
                    p, a = t.propose_ops(ops,
                                         lock_ids=tx["locks"][name])
                    participants.extend(p)
                    prepare_args.extend(a)
            except Exception:
                # a later table's propose failed: earlier tables'
                # durably staged writes must not leak in pending
                for p, a in zip(participants, prepare_args):
                    try:
                        p.abort(a)
                    except Exception:
                        pass
                raise
            if not participants:
                return TxResult(0, tx["snap"], True)
            return self.cluster.coordinator.commit_volatile(
                participants, prepare_args)
        finally:
            self._tx_release()
