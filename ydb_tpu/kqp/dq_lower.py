"""Logical plan -> DQ stage graph: the distributed execution path for
SQL statements.

The reference builds a task graph from the physical plan — scan stages
feeding hash-partition channels into join/aggregate stages and a result
channel (kqp_tasks_graph.cpp:448,778; planner kqp_planner.cpp:116). This
module is the TPU build's equivalent lowering over the SAME plan nodes
the single-chip executor walks (ydb_tpu.plan.nodes):

  TableScan   -> N-task stage reading table partitions, pushdown program
  Lookup/Expand joins -> both inputs hash-repartition on their join keys
                 over the channels; each task joins its grace bucket
                 device-locally (join stages, dq/compute.py run_join)
  Transform   -> two-phase split: per-block partial program on the
                 stream, final merge program at the single result task

Compared to the in-process recursive executor, joins never materialize a
whole table in one place: each join task holds 1/N of each side (the
GraceJoin memory shape), streamed in through credit-flow channels with
spill-beyond-quota.

Device-side, each lowered stage runs as a single fused trace: the task
runner (dq/compute.py) jits the whole per-task program — scan pushdown,
grace-bucket join, partial aggregate — as one XLA computation, and the
in-process executor's whole-plan analogue (ssa/plan_fuse.py) does the
same for plans small enough to skip DQ entirely.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu.dq.graph import (
    HashPartition,
    JoinSpec,
    ResultOutput,
    SourceInput,
    StageSpec,
    UnionAll,
    UnionAllInput,
)
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan.nodes import ExpandJoin, LookupJoin, TableScan, Transform
from ydb_tpu.ssa import twophase


def _split_at_sort(program):
    """Order-preserving split of a group-less program: ORDER BY / LIMIT
    (SortStep) — or a ranking WindowStep, which needs EVERY row at
    once — and everything after it must run ONCE over the merged
    inputs, never per block — per-block evaluation + arrival-order
    concat would scramble the result. Steps before the barrier are
    row-wise (assign/filter/project) and stay in the per-block phase.
    When the barrier is a keyed top-k sort, the per-block phase ALSO
    pre-tops its block (global top-k of per-block top-ks is exact),
    bounding channel traffic the way the reference's TopSort does."""
    from ydb_tpu.ssa.program import Program, SortStep, WindowStep

    steps = program.steps
    si = next((i for i, s in enumerate(steps)
               if isinstance(s, (SortStep, WindowStep))), None)
    if si is None:
        return program, None
    head = list(steps[:si])
    sort = steps[si]
    if isinstance(sort, SortStep) and sort.keys \
            and sort.limit is not None:
        head.append(sort)  # deterministic per-block pre-top-k
    partial = Program(tuple(head)) if head else None
    return partial, Program(steps[si:])


def plan_to_stages(plan, n_tasks: int = 2, estimator=None,
                   allow_swap: bool = False) -> list[StageSpec]:
    """Lower a logical plan tree to DQ stages (root must be a Transform,
    which the SQL planner guarantees).

    ``estimator(node) -> float | None`` supplies statistics-based row
    estimates (stats.cost.estimate_plan_rows bound to the aggregator's
    TableStats). Two consumers:

      * expand-join output capacity — ``fanout_hint`` is sized from the
        estimated output/probe ratio instead of the fixed 4x guess, so
        skew neither over-allocates HBM nor walks the overflow-retry
        ladder (bit-identical: capacity only changes dead padding);
      * build-side selection (``allow_swap=True``) — an inner expand
        join whose "build" side is estimated much larger than its probe
        side swaps the two (a grace join should build on the SMALL
        side). Only taken when both payload column sets keep the exact
        same output names (no suffix on either role), so the stage's
        schema is unchanged; result ROW ORDER may differ, which is why
        the swap is opt-in for callers that sort or aggregate above.
    """
    stages: list[dict] = []  # mutable specs; frozen at the end

    def add(**kw) -> int:
        stages.append(kw)
        return len(stages) - 1

    def set_output(si: int, out) -> None:
        if stages[si]["output"] is None:
            stages[si]["output"] = out
            return
        raise ValueError(
            "stage feeds two consumers; duplicate the subtree instead")

    def est(node) -> float | None:
        if estimator is None:
            return None
        try:
            return estimator(node)
        except Exception:  # noqa: BLE001 - estimates must never fail a plan
            return None

    def lower(node) -> int:
        if isinstance(node, TableScan):
            return add(program=node.program,
                       inputs=(SourceInput(node.table),),
                       output=None, tasks=n_tasks)
        if isinstance(node, (LookupJoin, ExpandJoin)):
            probe, build = node.probe, node.build
            probe_keys = tuple(node.probe_keys)
            build_keys = tuple(node.build_keys)
            swapped = False
            p_rows, b_rows = est(probe), est(build)
            if (allow_swap and isinstance(node, ExpandJoin)
                    and node.kind == "inner" and not node.build_suffix
                    and p_rows is not None and b_rows is not None
                    and b_rows > 2 * p_rows):
                probe, build = build, probe
                probe_keys, build_keys = build_keys, probe_keys
                swapped = True
            pi = lower(probe)
            bi = lower(build)
            set_output(pi, HashPartition(probe_keys))
            set_output(bi, HashPartition(build_keys))
            if isinstance(node, LookupJoin):
                j = JoinSpec(probe_keys, build_keys,
                             payload=node.payload, kind=node.kind,
                             suffix=node.suffix)
            else:
                fanout = node.fanout_hint
                out_rows = est(node)
                base = b_rows if swapped else p_rows
                if out_rows is not None and base:
                    # estimated per-probe-row expansion, padded 2x and
                    # bounded: capacity sizing only, never semantics
                    fanout = min(64.0, max(1.0,
                                           2.0 * out_rows / base))
                pp = node.probe_payload
                bp = node.build_payload
                if swapped:
                    pp, bp = bp, pp
                j = JoinSpec(probe_keys, build_keys,
                             probe_payload=pp, build_payload=bp,
                             kind=node.kind, suffix=node.build_suffix,
                             expand=True, fanout_hint=fanout)
            return add(program=None,
                       inputs=(UnionAllInput(pi), UnionAllInput(bi)),
                       output=None, tasks=n_tasks, join=j)
        if isinstance(node, Transform):
            ii = lower(node.input)
            set_output(ii, UnionAll())
            partial, final = twophase.split(node.program)
            if final is None:
                partial, final = _split_at_sort(node.program)
            return add(program=partial, final_program=final,
                       inputs=(UnionAllInput(ii),), output=None, tasks=1,
                       dict_aliases=node.dict_aliases)
        raise NotImplementedError(node)

    from ydb_tpu.obs import tracing

    with tracing.span("dq.lower") as sp:
        root = lower(plan)
        set_output(root, ResultOutput())
        out = []
        for kw in stages:
            kw.setdefault("join", None)
            kw.setdefault("final_program", None)
            kw.setdefault("dict_aliases", ())
            out.append(StageSpec(**kw))
        sp.set(stages=len(out),
               joins=sum(1 for s in out if s.join is not None))
    return out


def partition_source(src: ColumnSource, k: int) -> list[ColumnSource]:
    """Round-robin row partitions of a host table (scan-task feeding)."""
    out = []
    for s in range(k):
        cols = {n: v[s::k] for n, v in src.columns.items()}
        validity = None
        if src.validity:
            validity = {n: v[s::k] for n, v in src.validity.items()}
        out.append(ColumnSource(cols, src.schema, src.dicts, validity))
    return out


def execute_plan_dq(
    plan,
    sources: dict[str, list[ColumnSource]],
    runtime,
    dicts=None,
    key_spaces=None,
    n_tasks: int = 2,
    estimator=None,
    allow_swap: bool = False,
    **graph_kw,
) -> OracleTable:
    """Run a logical plan through the DQ stage graph on ``runtime``
    (SimRuntime or a single ActorSystem). ``sources`` maps each table to
    its partition list (see partition_source); ``estimator`` /
    ``allow_swap`` feed statistics into join sizing and build-side
    selection (plan_to_stages)."""
    from ydb_tpu.dq.compute import run_stage_graph

    stages = plan_to_stages(plan, n_tasks=n_tasks, estimator=estimator,
                            allow_swap=allow_swap)
    return run_stage_graph(stages, sources, runtime, dicts, key_spaces,
                           **graph_kw)
