"""Logical plan -> DQ stage graph: the distributed execution path for
SQL statements.

The reference builds a task graph from the physical plan — scan stages
feeding hash-partition channels into join/aggregate stages and a result
channel (kqp_tasks_graph.cpp:448,778; planner kqp_planner.cpp:116). This
module is the TPU build's equivalent lowering over the SAME plan nodes
the single-chip executor walks (ydb_tpu.plan.nodes):

  TableScan   -> N-task stage reading table partitions, pushdown program
  Lookup/Expand joins -> both inputs hash-repartition on their join keys
                 over the channels; each task joins its grace bucket
                 device-locally (join stages, dq/compute.py run_join)
  Transform   -> two-phase split: per-block partial program on the
                 stream, final merge program at the single result task

Compared to the in-process recursive executor, joins never materialize a
whole table in one place: each join task holds 1/N of each side (the
GraceJoin memory shape), streamed in through credit-flow channels with
spill-beyond-quota.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu.dq.graph import (
    HashPartition,
    JoinSpec,
    ResultOutput,
    SourceInput,
    StageSpec,
    UnionAll,
    UnionAllInput,
)
from ydb_tpu.engine.oracle import OracleTable
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan.nodes import ExpandJoin, LookupJoin, TableScan, Transform
from ydb_tpu.ssa import twophase


def _split_at_sort(program):
    """Order-preserving split of a group-less program: ORDER BY / LIMIT
    (SortStep) — or a ranking WindowStep, which needs EVERY row at
    once — and everything after it must run ONCE over the merged
    inputs, never per block — per-block evaluation + arrival-order
    concat would scramble the result. Steps before the barrier are
    row-wise (assign/filter/project) and stay in the per-block phase.
    When the barrier is a keyed top-k sort, the per-block phase ALSO
    pre-tops its block (global top-k of per-block top-ks is exact),
    bounding channel traffic the way the reference's TopSort does."""
    from ydb_tpu.ssa.program import Program, SortStep, WindowStep

    steps = program.steps
    si = next((i for i, s in enumerate(steps)
               if isinstance(s, (SortStep, WindowStep))), None)
    if si is None:
        return program, None
    head = list(steps[:si])
    sort = steps[si]
    if isinstance(sort, SortStep) and sort.keys \
            and sort.limit is not None:
        head.append(sort)  # deterministic per-block pre-top-k
    partial = Program(tuple(head)) if head else None
    return partial, Program(steps[si:])


def plan_to_stages(plan, n_tasks: int = 2) -> list[StageSpec]:
    """Lower a logical plan tree to DQ stages (root must be a Transform,
    which the SQL planner guarantees)."""
    stages: list[dict] = []  # mutable specs; frozen at the end

    def add(**kw) -> int:
        stages.append(kw)
        return len(stages) - 1

    def set_output(si: int, out) -> None:
        if stages[si]["output"] is None:
            stages[si]["output"] = out
            return
        raise ValueError(
            "stage feeds two consumers; duplicate the subtree instead")

    def lower(node) -> int:
        if isinstance(node, TableScan):
            return add(program=node.program,
                       inputs=(SourceInput(node.table),),
                       output=None, tasks=n_tasks)
        if isinstance(node, (LookupJoin, ExpandJoin)):
            pi = lower(node.probe)
            bi = lower(node.build)
            set_output(pi, HashPartition(tuple(node.probe_keys)))
            set_output(bi, HashPartition(tuple(node.build_keys)))
            if isinstance(node, LookupJoin):
                j = JoinSpec(node.probe_keys, node.build_keys,
                             payload=node.payload, kind=node.kind,
                             suffix=node.suffix)
            else:
                j = JoinSpec(node.probe_keys, node.build_keys,
                             probe_payload=node.probe_payload,
                             build_payload=node.build_payload,
                             kind=node.kind, suffix=node.build_suffix,
                             expand=True, fanout_hint=node.fanout_hint)
            return add(program=None,
                       inputs=(UnionAllInput(pi), UnionAllInput(bi)),
                       output=None, tasks=n_tasks, join=j)
        if isinstance(node, Transform):
            ii = lower(node.input)
            set_output(ii, UnionAll())
            partial, final = twophase.split(node.program)
            if final is None:
                partial, final = _split_at_sort(node.program)
            return add(program=partial, final_program=final,
                       inputs=(UnionAllInput(ii),), output=None, tasks=1,
                       dict_aliases=node.dict_aliases)
        raise NotImplementedError(node)

    root = lower(plan)
    set_output(root, ResultOutput())
    out = []
    for kw in stages:
        kw.setdefault("join", None)
        kw.setdefault("final_program", None)
        kw.setdefault("dict_aliases", ())
        out.append(StageSpec(**kw))
    return out


def partition_source(src: ColumnSource, k: int) -> list[ColumnSource]:
    """Round-robin row partitions of a host table (scan-task feeding)."""
    out = []
    for s in range(k):
        cols = {n: v[s::k] for n, v in src.columns.items()}
        validity = None
        if src.validity:
            validity = {n: v[s::k] for n, v in src.validity.items()}
        out.append(ColumnSource(cols, src.schema, src.dicts, validity))
    return out


def execute_plan_dq(
    plan,
    sources: dict[str, list[ColumnSource]],
    runtime,
    dicts=None,
    key_spaces=None,
    n_tasks: int = 2,
    **graph_kw,
) -> OracleTable:
    """Run a logical plan through the DQ stage graph on ``runtime``
    (SimRuntime or a single ActorSystem). ``sources`` maps each table to
    its partition list (see partition_source)."""
    from ydb_tpu.dq.compute import run_stage_graph

    stages = plan_to_stages(plan, n_tasks=n_tasks)
    return run_stage_graph(stages, sources, runtime, dicts, key_spaces,
                           **graph_kw)
