"""Cross-query micro-batching dispatcher (the serving tier's core).

Fused plans are keyed by (plan fingerprint, shape-class vector) —
compatible queued statements are *literally the same executable*
(``plan_fuse.PlanSignature.cache_key``). Under concurrency this
dispatcher holds admitted SELECT statements for a bounded window
(``YDB_TPU_BATCH_WINDOW_MS``, default 0 → disarmed, the serial path is
untouched), groups arrivals by that cache key, and serves the whole
group with ONE device dispatch instead of N:

* **Dedup (the common serving case).** N statements over the same
  snapshot stage the same input blocks — the batch stages each distinct
  scan identity once (attaching to in-flight stagings via
  ``engine.scanshare.ScanShare``) and, when every member's staged
  inputs are identical, runs the plan ONCE via the non-donating
  ``FusedPlan.run_shared``; every member's result is the same block.
  This is where the >=2x QPS win lives: the window turns N identical
  dispatches into 1.
* **Stacked (distinct inputs).** Members whose staged inputs differ
  (different snapshots / tables mutated between arrivals) stack along a
  leading batch axis into one vmapped dispatch
  (``FusedPlan.run_stacked``), each member slicing its own row off the
  batched result (``plan_fuse.slice_member``). One trace per batch
  size; ``jnp.stack`` copies, so the per-member staged blocks (possibly
  shared with concurrent statements) are never donated.

Protocol: the first arrival for a key becomes the **leader** — it waits
out the window (early close when ``YDB_TPU_BATCH_MAX`` members gather,
capped by its own deadline budget), closes the group, stages, dispatches
and distributes. Later arrivals are **followers**: they enqueue and wait
on a per-member event with deadline-capped timed waits. Fairness is
inherited, not reinvented: batching sits AFTER workload-pool admission
and resource-manager slot acquisition, so a statement only ever waits in
a batch it was already admitted to run.

Isolation: the leader executes under a cleared deadline
(``deadline.activate(None)``) and re-checks its OWN budget only after
distributing — a deadline cancel of one member (leader included) never
cancels or corrupts its batchmates. Real execution errors (staging
faults, compile failures) are genuinely shared — one dispatch served
everyone — and propagate to every member.

A group of one is not a batch: the leader returns the statement to the
caller's serial path unchanged (same spans, same donation, same walk
fallbacks), so an idle server pays only the window wait.
"""

from __future__ import annotations

import os
import threading
import time

from ydb_tpu.analysis import leaksan, sanitizer
from ydb_tpu.chaos import deadline as statement_deadline
from ydb_tpu.engine.scanshare import ScanShare
from ydb_tpu.obs import tracing
from ydb_tpu.plan.nodes import TableScan

#: follower safety re-check period — bounds every event wait (the
#: concurrency analyzer's C003 discipline) and lets a deadline that
#: fires mid-batch cancel the waiter promptly
MEMBER_WAIT_TICK_SECONDS = 1.0


def _env_window_ms() -> float:
    try:
        return float(os.environ.get("YDB_TPU_BATCH_WINDOW_MS", "0") or 0)
    except ValueError:
        return 0.0


def _env_max_batch() -> int:
    try:
        return max(2, int(os.environ.get("YDB_TPU_BATCH_MAX", "32")))
    except ValueError:
        return 32


class _Member:
    """One queued statement's seat in a batch group."""

    __slots__ = ("db", "identity", "uindex", "event", "result", "error",
                 "shared_scan", "t_enq", "tok")

    def __init__(self, db, identity, tok):
        self.db = db
        self.identity = identity   # per-site staging identity vector
        self.uindex = 0            # index into the group's unique inputs
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.shared_scan = 0       # sites served by a shared staging
        self.t_enq = time.perf_counter()
        self.tok = tok


class _Group:
    """An open batch: members gather until the window closes."""

    __slots__ = ("key", "sig", "members", "closed", "full", "batch_id",
                 "t_closed", "execute_seconds")

    def __init__(self, key, sig):
        self.key = key
        self.sig = sig
        self.members: list[_Member] = []
        self.closed = False
        self.full = False
        self.batch_id = 0
        self.t_closed = 0.0
        self.execute_seconds = 0.0


class BatchDispatcher:
    """Window-batched fused dispatch across concurrent sessions.

    ``execute`` returns the member's device result block, or ``None``
    when the statement should run the ordinary serial path (dispatcher
    disarmed, plan not batchable, or the group closed with one member).
    """

    def __init__(self, window_ms: float | None = None,
                 max_batch: int | None = None):
        self.window_ms = (_env_window_ms() if window_ms is None
                          else float(window_ms))
        self.max_batch = (_env_max_batch() if max_batch is None
                          else max(2, int(max_batch)))
        self._cv = sanitizer.make_condition(f"batch.{id(self):x}")
        self._open = sanitizer.share(
            {}, f"batch.{id(self):x}.open")  # key -> _Group
        self.share = ScanShare()
        self._batch_seq = 0
        # counters (mutated under _cv's lock; read by run_background)
        self.batches = 0             # closed groups with >= 2 members
        self.solo = 0                # groups that closed with 1 member
        self.batched_statements = 0  # members served by a batch
        self.dedup_dispatches = 0    # batches served by ONE run_shared
        self.stacked_dispatches = 0  # batches served by run_stacked
        self.max_batch_size = 0

    def armed(self) -> bool:
        return self.window_ms > 0

    # -- admission ----------------------------------------------------

    def execute(self, plan, db, cluster=None, active_tok=None):
        """Batch-execute ``plan`` if a compatible group forms; ``None``
        sends the caller down the unchanged serial path."""
        if not self.armed():
            return None
        if getattr(db, "mesh_executor", None) is not None:
            # mesh dispatch already amortizes across devices; batching
            # targets the single-chip fused path
            return None
        from ydb_tpu.ssa import plan_fuse

        if not plan_fuse.fusion_enabled() or isinstance(plan, TableScan):
            return None
        sig = plan_fuse.plan_signature_cached(plan, db)
        if sig is None or not sig.sites:
            return None
        key = sig.cache_key(db)
        member = _Member(db, self._identity_vector(sig, db), active_tok)
        lk = leaksan.track("batch.member", f"m{id(member):x}",
                           owner=active_tok)
        try:
            with tracing.span("dispatch.batch") as sp:
                with self._cv:
                    g = self._open.get(key)
                    leader = g is None or g.closed or g.full
                    if leader:
                        g = _Group(key, sig)
                        self._open[key] = g
                    g.members.append(member)
                    if len(g.members) >= self.max_batch:
                        g.full = True
                        self._cv.notify_all()
                if leader:
                    out = self._lead(g, cluster)
                else:
                    out = self._follow(g, member)
                if sp.recording:
                    sp.set(batch_id=g.batch_id,
                           batch_size=len(g.members),
                           shared_scan=member.shared_scan,
                           wait_seconds=round(
                               max(0.0, g.t_closed - member.t_enq), 6),
                           execute_seconds=round(g.execute_seconds, 6))
                if cluster is not None and active_tok is not None:
                    cluster._update_active(
                        active_tok, batch_id=g.batch_id,
                        batch_size=len(g.members),
                        shared_scan=member.shared_scan)
            return out
        finally:
            leaksan.close(lk)

    # -- staging identity ---------------------------------------------

    @staticmethod
    def _identity_vector(sig, db) -> tuple:
        """Per-site identity of the block this member would stage.

        Two members with equal vectors stage byte-identical inputs, so
        the batch stages once and dispatches once (run_shared). The
        pushdown program is part of the identity — pruning derives from
        it — alongside the shape-class capacity and the source's device
        cache key (per-shard visible portion ids: commits mint new keys,
        so identity never aliases across snapshots). Host ColumnSources
        have no content key; object identity stands in — members hold
        their db (hence source) refs for the batch's whole lifetime, so
        ids are stable and unique among live members, but such entries
        are marked unshareable across batches (ids recycle after GC).
        """
        vec = []
        for site in sig.sites:
            src = db.sources.get(site.table)
            key_of = getattr(src, "device_cache_key", None)
            if key_of is not None:
                vec.append(("dev", site.table, site.node.program,
                            site.read_cols, site.capacity,
                            key_of(site.read_cols, 1 << 22)))
            else:
                vec.append(("src", site.table, site.node.program,
                            site.read_cols, site.capacity, id(src)))
        return tuple(vec)

    # -- leader -------------------------------------------------------

    def _lead(self, g: _Group, cluster):
        window = self.window_ms / 1000.0
        dl = statement_deadline.current()
        if dl is not None:
            window = max(0.0, min(window, dl.remaining()))
        end = time.monotonic() + window
        with self._cv:
            while not g.full:
                rem = end - time.monotonic()
                if rem <= 0:
                    break
                self._cv.wait(rem)
            g.closed = True
            if self._open.get(g.key) is g:
                del self._open[g.key]
            self._batch_seq += 1
            g.batch_id = self._batch_seq
            g.t_closed = time.perf_counter()
            members = list(g.members)
            if len(members) == 1:
                self.solo += 1
            else:
                self.batches += 1
                self.batched_statements += len(members)
                self.max_batch_size = max(self.max_batch_size,
                                          len(members))
        if len(members) == 1:
            # not a batch — the caller runs the ordinary serial path
            # (same spans, donation, walk fallbacks); the window wait is
            # the only cost, and it is attributed on the batch span
            return None
        try:
            # the leader executes on behalf of the whole group: its OWN
            # deadline must not cancel batchmates mid-dispatch, so it
            # runs with the deadline cleared and settles its budget
            # after distributing (below)
            with statement_deadline.activate(None):
                self._run_batch(g, members, cluster)
        except BaseException as e:
            for m in members:
                m.error = e
                m.event.set()
            raise
        leader = members[0]
        for m in members[1:]:
            m.event.set()
        statement_deadline.check_current("batched dispatch")
        return leader.result

    def _run_batch(self, g: _Group, members: list[_Member], cluster):
        from ydb_tpu.plan.executor import _stage_fused_site
        from ydb_tpu.ssa import plan_fuse

        db = members[0].db
        fused = db._compile_cache.get(g.key)
        fresh = fused is None
        with tracing.span("plan.fuse") as fsp:
            if fresh:
                try:
                    fused = plan_fuse.build(g.sig, db)
                except plan_fuse.Unfusible:
                    # fusibility was probed before enqueue; build-time
                    # rejection means an unfusible detail surfaced late.
                    # Serve each member by the serial executor instead.
                    self._run_unbatched(g, members)
                    return
                db._compile_cache[g.key] = fused
            ft0 = fused.first_trace_seconds or 0.0

            # stage each distinct scan identity ONCE; concurrent
            # batches/statements staging the same identity attach to the
            # in-flight staging through the ScanShare
            staged: dict[tuple, object] = {}
            attached0 = self.share.attached
            ident_users: dict[tuple, int] = {}
            for m in members:
                for ident in m.identity:
                    ident_users[ident] = ident_users.get(ident, 0) + 1
            for m in members:
                # sites whose staged block serves >1 member — the
                # stager counts too; sharing is symmetric
                m.shared_scan = sum(1 for ident in m.identity
                                    if ident_users[ident] > 1)
                for site, ident in zip(g.sig.sites, m.identity):
                    if ident in staged:
                        continue
                    share_key = ident if ident[0] == "dev" else None
                    mdb = m.db

                    def stage(site=site, mdb=mdb):
                        with tracing.span("scan") as sp:
                            blk, _pruning = _stage_fused_site(
                                site, mdb, None, donate=False)
                            if sp.recording:
                                sp.set(table=site.table,
                                       rows=int(blk.length))
                        return blk

                    staged[ident] = self.share.get_or_stage(share_key,
                                                            stage)

            # unique input vectors, in first-appearance order
            uniq: dict[tuple, int] = {}
            inputs_list: list[dict] = []
            for m in members:
                u = uniq.get(m.identity)
                if u is None:
                    u = len(inputs_list)
                    uniq[m.identity] = u
                    inputs_list.append(
                        {site.key: staged[ident]
                         for site, ident in zip(g.sig.sites, m.identity)})
                m.uindex = u

            t0 = time.perf_counter()
            while True:
                # neither path donates the per-member staged blocks
                # (run_shared never donates; run_stacked donates only
                # its jnp.stack copy), so an expand-join overflow grows
                # and re-dispatches over the SAME staged inputs
                if len(inputs_list) == 1:
                    out, totals = fused.run_shared(inputs_list[0])
                else:
                    out, totals = fused.run_stacked(inputs_list)
                over = fused.overflowed(totals)
                if not over:
                    break
                for j in over:
                    fused.grow(j, totals[j])
            g.execute_seconds = time.perf_counter() - t0

            if len(inputs_list) == 1:
                for m in members:
                    m.result = out
            else:
                for m in members:
                    m.result = plan_fuse.slice_member(out, m.uindex)

            with self._cv:
                if len(inputs_list) == 1:
                    self.dedup_dispatches += 1
                else:
                    self.stacked_dispatches += 1

            if fsp.recording:
                fsp.set(fused_stages=fused.fused_stages,
                        fragments_elided=fused.fused_stages - 1,
                        compile_cache=("miss" if fresh else "hit"),
                        batch_size=len(members),
                        scan_attached=self.share.attached - attached0)
                ft = (fused.first_trace_seconds or 0.0) - ft0
                if ft:
                    fsp.set(first_trace_seconds=round(ft, 6))

    def _run_unbatched(self, g: _Group, members: list[_Member]) -> None:
        # late Unfusible: fall back to one serial execution per member
        # (each against its own snapshot db) so the group still answers
        from ydb_tpu.plan.executor import execute_plan

        t0 = time.perf_counter()
        for m in members:
            m.result = execute_plan(g.sig.plan, m.db)
        g.execute_seconds = time.perf_counter() - t0

    # -- follower -----------------------------------------------------

    @staticmethod
    def _follow(g: _Group, member: _Member):
        while not member.event.wait(MEMBER_WAIT_TICK_SECONDS):
            # a deadline firing mid-batch cancels THIS waiter only; the
            # leader later completes the abandoned seat harmlessly
            statement_deadline.check_current("batched dispatch wait")
        if member.error is not None:
            raise member.error
        statement_deadline.check_current("batched dispatch")
        return member.result

    # -- telemetry ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._cv:
            snap = {
                "batches": self.batches,
                "solo": self.solo,
                "batched_statements": self.batched_statements,
                "dedup_dispatches": self.dedup_dispatches,
                "stacked_dispatches": self.stacked_dispatches,
                "max_batch_size": self.max_batch_size,
                "open_groups": len(self._open),
            }
        snap.update({f"scan_{k}": v for k, v in
                     self.share.snapshot().items()})
        return snap
