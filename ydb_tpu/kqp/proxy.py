"""KQP proxy: session pooling and request routing.

Mirror of the reference's kqp_proxy_service (ydb/core/kqp/proxy_service;
SURVEY §2.8 KQP-proxy row): clients do not own session lifecycles — the
proxy creates, pools, balances and expires sessions, enforcing a
ceiling, and routes each request to an idle session. Collapsed to one
process here, the contract is the same: bounded concurrent sessions,
reuse over churn, busy rejection past the ceiling.
"""

from __future__ import annotations

import collections
import threading


class ProxyBusyError(Exception):
    """All sessions busy and the pool is at its ceiling (the reference
    replies OVERLOADED)."""


class SessionPool:
    def __init__(self, cluster, max_sessions: int = 16):
        self.cluster = cluster
        self.max_sessions = max_sessions
        self._idle: collections.deque = collections.deque()
        self._created = 0
        self._lock = threading.Lock()
        self.stats = {"created": 0, "reused": 0, "busy_rejects": 0}

    def acquire(self):
        with self._lock:
            if self._idle:
                self.stats["reused"] += 1
                return self._idle.popleft()
            if self._created >= self.max_sessions:
                self.stats["busy_rejects"] += 1
                raise ProxyBusyError(
                    f"{self.max_sessions} sessions busy")
            self._created += 1
            self.stats["created"] += 1
        return self.cluster.session()

    def release(self, session) -> None:
        with self._lock:
            self._idle.append(session)

    def execute(self, sql: str):
        """Route one statement through a pooled session."""
        s = self.acquire()
        try:
            return s.execute(sql)
        finally:
            self.release(s)

    @property
    def idle(self) -> int:
        return len(self._idle)

    @property
    def live(self) -> int:
        return self._created
