from ydb_tpu.kqp.session import Cluster, Session  # noqa: F401
