"""KQP resource manager + workload service (admission control).

Mirror of the reference's per-node resource accounting and query
admission planes (ydb/core/kqp/rm_service/kqp_rm_service.h:82 — memory
/compute-slot budgets acquired per task and returned on completion,
with a cluster snapshot feeding the planner; ydb/core/kqp/
workload_service/kqp_workload_service.cpp:37 — named resource pools
with concurrent-query limits and bounded admission queues; SURVEY.md
§2.8 rows "resource manager" / "workload service").

ResourceManager: hard budgets; acquire either grants immediately or
fails (the caller queues/retries — the reference's task starts are
rejected the same way). Grants are tracked per query so release is
idempotent and crash-safe at the accounting level.

WorkloadService: admission by pool — running < limit admits; past the
limit requests wait in a bounded FIFO; past the queue bound they are
rejected (OVERLOADED). finish() promotes the queue head. Pools are
config-reloadable (Console dynamic-config shape).
"""

from __future__ import annotations

import collections
import threading

from ydb_tpu.analysis import leaksan


class ResourceExhausted(Exception):
    #: sys_top_queries error_reason tag (admission-plane rejection)
    reason = "overloaded"


class ResourceManager:
    """Per-node memory/slot budgets (kqp_rm_service analog)."""

    def __init__(self, memory_bytes: int = 1 << 30,
                 compute_slots: int = 8):
        self.memory_bytes = memory_bytes
        self.compute_slots = compute_slots
        self._lock = threading.Lock()
        self._grants: dict[str, tuple[int, int]] = {}
        # leak-sanitizer handle per granted query (guarded by _lock);
        # empty whenever the sanitizer is off
        self._leaks: dict[str, object] = {}

    def used(self) -> tuple[int, int]:
        with self._lock:
            mem = sum(m for m, _s in self._grants.values())
            slots = sum(s for _m, s in self._grants.values())
            return mem, slots

    def acquire(self, query_id: str, memory: int = 0,
                slots: int = 1) -> None:
        with self._lock:
            cur_m, cur_s = 0, 0
            for m, s in self._grants.values():
                cur_m += m
                cur_s += s
            old = self._grants.get(query_id, (0, 0))
            new_m = cur_m - old[0] + memory
            new_s = cur_s - old[1] + slots
            if new_m > self.memory_bytes:
                raise ResourceExhausted(
                    f"memory: want {memory}, "
                    f"free {self.memory_bytes - cur_m + old[0]}")
            if new_s > self.compute_slots:
                raise ResourceExhausted(
                    f"slots: want {slots}, "
                    f"free {self.compute_slots - cur_s + old[1]}")
            first = query_id not in self._grants
            self._grants[query_id] = (memory, slots)
            if first:
                lk = leaksan.track("rm.slot", query_id, owner=query_id)
                if lk is not None:
                    self._leaks[query_id] = lk

    def release(self, query_id: str) -> None:
        with self._lock:
            self._grants.pop(query_id, None)
            if self._leaks:
                leaksan.close(self._leaks.pop(query_id, None))

    def snapshot(self) -> dict:
        """Planner feed (resource info exchange analog)."""
        mem, slots = self.used()
        return {
            "memory_bytes": self.memory_bytes,
            "memory_used": mem,
            "compute_slots": self.compute_slots,
            "slots_used": slots,
            "queries": len(self._grants),
        }


class PoolOverloaded(Exception):
    #: sys_top_queries error_reason tag
    reason = "overloaded"


class OverloadedError(Exception):
    """The cluster shed this statement at admission: past the
    configured in-flight limit the session layer fails fast with this
    typed error instead of queueing unboundedly (load shedding — the
    serving tier's backpressure signal to clients)."""

    reason = "overloaded"


class _Pool:
    def __init__(self, name: str, concurrent_limit: int,
                 queue_size: int):
        self.name = name
        self.limit = concurrent_limit
        self.queue_size = queue_size
        self.running: set[str] = set()
        self.queue: collections.deque = collections.deque()
        self.stats = {"admitted": 0, "queued": 0, "rejected": 0}


class WorkloadService:
    """Named admission pools (kqp_workload_service analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._freed = threading.Condition(self._lock)
        self._pools: dict[str, _Pool] = {}
        self.configure("default", concurrent_limit=16, queue_size=64)

    def configure(self, pool: str, concurrent_limit: int,
                  queue_size: int = 64) -> None:
        with self._lock:
            p = self._pools.get(pool)
            if p is None:
                self._pools[pool] = _Pool(pool, concurrent_limit,
                                          queue_size)
            else:
                p.limit = concurrent_limit
                p.queue_size = queue_size

    def _pool(self, pool: str) -> _Pool:
        p = self._pools.get(pool)
        if p is None:
            raise KeyError(f"no resource pool {pool}")
        return p

    def admit(self, query_id: str, pool: str = "default") -> bool:
        """True = running now; False = queued (caller waits for its
        turn via poll()). Raises PoolOverloaded past the queue bound."""
        with self._lock:
            p = self._pool(pool)
            if query_id in p.running:
                return True
            if len(p.running) < p.limit and not p.queue:
                p.running.add(query_id)
                p.stats["admitted"] += 1
                return True
            if len(p.queue) >= p.queue_size:
                p.stats["rejected"] += 1
                raise PoolOverloaded(
                    f"pool {pool}: {len(p.running)} running, "
                    f"queue full ({p.queue_size})")
            p.queue.append(query_id)
            p.stats["queued"] += 1
            return False

    def poll(self, query_id: str, pool: str = "default") -> bool:
        """True once the queued query reaches the front and a slot is
        free (it is then admitted)."""
        with self._lock:
            p = self._pool(pool)
            if query_id in p.running:
                return True
            if (p.queue and p.queue[0] == query_id
                    and len(p.running) < p.limit):
                p.queue.popleft()
                p.running.add(query_id)
                p.stats["admitted"] += 1
                return True
            return False

    def wait_admitted(self, query_id: str, pool: str = "default",
                      timeout: float = 30.0) -> bool:
        """Block (condition-waited, not busy-polled) until the queued
        query is admitted; False on timeout (caller must finish())."""
        import time

        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                p = self._pool(pool)
                if query_id in p.running:
                    return True
                if (p.queue and p.queue[0] == query_id
                        and len(p.running) < p.limit):
                    p.queue.popleft()
                    p.running.add(query_id)
                    p.stats["admitted"] += 1
                    self._freed.notify_all()
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._freed.wait(remaining)

    def finish(self, query_id: str, pool: str = "default") -> None:
        with self._lock:
            p = self._pool(pool)
            p.running.discard(query_id)
            try:
                p.queue.remove(query_id)  # cancelled while queued
            except ValueError:
                pass
            self._freed.notify_all()

    def stats(self, pool: str = "default") -> dict:
        with self._lock:
            p = self._pool(pool)
            return dict(p.stats, running=len(p.running),
                        queued=len(p.queue), limit=p.limit)

    def pools(self) -> dict[str, dict]:
        """All pools' stats in one locked pass (the front door's
        ``sys_tenant_pools`` view joins these against its seat
        counters)."""
        with self._lock:
            return {name: dict(p.stats, running=len(p.running),
                               queued=len(p.queue), limit=p.limit)
                    for name, p in self._pools.items()}
