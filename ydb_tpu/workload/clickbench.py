"""ClickBench workload: hits-table generator, query set, reference
answers (BASELINE configs 3/5; reference
ydb/library/workload/clickbench/click_bench_queries.sql and the
canondata under ydb/tests/functional/clickbench/).

The hits schema here is the subset of ClickBench's 105 columns that the
implemented queries touch; distributions are synthetic-but-skewed
(zipf-ish region/phrase popularity, mostly-empty search phrases) so the
queries exercise the same shapes: wide scans, high-cardinality group-by,
COUNT(DISTINCT) — including Q9's mix of distinct and plain aggregates —
and top-N by aggregate. Canonical answers come from
``reference_answers`` — an independent numpy implementation the engine
results must match exactly (the canondata pattern). The dict below covers 27 of the
official 43 queries (q0-q22, q24-q27).
"""

from __future__ import annotations

import collections

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet

HITS_SCHEMA = dtypes.schema(
    ("WatchID", dtypes.INT64, False),
    ("UserID", dtypes.INT64, False),
    ("EventDate", dtypes.DATE, False),
    ("EventTime", dtypes.TIMESTAMP, False),
    ("CounterID", dtypes.INT32, False),
    ("RegionID", dtypes.INT32, False),
    ("AdvEngineID", dtypes.INT32, False),
    ("SearchEngineID", dtypes.INT32, False),
    ("ResolutionWidth", dtypes.INT32, False),
    ("MobilePhone", dtypes.INT32, False),
    ("MobilePhoneModel", dtypes.STRING, False),
    ("SearchPhrase", dtypes.STRING, False),
    ("URL", dtypes.STRING, False),
    ("Title", dtypes.STRING, False),
)

_PHONE_MODELS = [b"", b"iPhone 2", b"iPhone 4", b"Nokia 3310",
                 b"Galaxy S", b"Pixel", b"Xperia Z", b"Moto G"]
_PHRASE_WORDS = [b"weather", b"news", b"cats", b"tpu", b"database",
                 b"flights", b"pizza", b"maps", b"music", b"jobs"]


def _zipf_choice(rng, n_values: int, size: int) -> np.ndarray:
    """Skewed (zipf-ish) ids in [0, n_values): few heavy hitters."""
    z = rng.zipf(1.5, size=size)
    return np.minimum(z - 1, n_values - 1).astype(np.int64)


class ClickBenchData:
    """Generated hits table + shared dictionaries."""

    def __init__(self, rows: int = 100_000, seed: int = 42):
        rng = np.random.default_rng(seed)
        self.dicts = DictionarySet()
        n = rows
        d0 = int(np.datetime64("2013-07-01", "D").astype(np.int32))
        n_users = max(n // 20, 10)

        phrase_pool = [b""] + [
            b" ".join(rng.choice(_PHRASE_WORDS,
                                 size=rng.integers(1, 4), replace=True))
            for _ in range(999)
        ]
        phrase_d = self.dicts.for_column("SearchPhrase")
        phrase_ids = np.array([phrase_d.add(p) for p in phrase_pool],
                              dtype=np.int32)
        # ~77% of hits have no search phrase (ClickBench-like sparsity)
        phrase_pick = np.where(
            rng.random(n) < 0.77, 0,
            1 + _zipf_choice(rng, len(phrase_pool) - 1, n))

        model_d = self.dicts.for_column("MobilePhoneModel")
        model_ids = np.array([model_d.add(m) for m in _PHONE_MODELS],
                             dtype=np.int32)
        model_pick = np.where(
            rng.random(n) < 0.9, 0,
            1 + _zipf_choice(rng, len(_PHONE_MODELS) - 1, n))

        # URLs: a skewed pool of synthetic paths; 2 of 7 hosts are
        # google.* so ~29% of rows match the LIKE '%google%' queries
        hosts = [b"example.com", b"news.site", b"google.com",
                 b"shop.io", b"google.de", b"docs.org", b"blog.net"]
        url_pool = [
            b"http://%s/%s/%d" % (rng.choice(hosts),
                                  rng.choice(_PHRASE_WORDS),
                                  rng.integers(0, 100))
            for _ in range(2000)
        ]
        url_d = self.dicts.for_column("URL")
        url_ids = np.array([url_d.add(u) for u in url_pool],
                           dtype=np.int32)
        title_pool = [b"" ] + [
            b"%s - page %d" % (rng.choice(_PHRASE_WORDS),
                               rng.integers(0, 50))
            for _ in range(499)
        ]
        title_d = self.dicts.for_column("Title")
        title_ids = np.array([title_d.add(t) for t in title_pool],
                             dtype=np.int32)

        dates = (d0 + rng.integers(0, 31, n)).astype(np.int32)
        self.hits: dict[str, np.ndarray] = {
            "WatchID": rng.integers(1, 1 << 62, n, dtype=np.int64),
            "UserID": (_zipf_choice(rng, n_users, n) + 1),
            "EventDate": dates,
            "EventTime": (dates.astype(np.int64) * 86_400_000_000
                          + rng.integers(0, 86_400, n) * 1_000_000),
            "CounterID": rng.integers(1, 10_000, n, dtype=np.int32),
            "RegionID": _zipf_choice(rng, 5000, n).astype(np.int32),
            "AdvEngineID": np.where(
                rng.random(n) < 0.95, 0,
                rng.integers(1, 20, n)).astype(np.int32),
            "SearchEngineID": np.where(
                rng.random(n) < 0.7, 0,
                rng.integers(1, 8, n)).astype(np.int32),
            "ResolutionWidth": rng.choice(
                np.array([1024, 1280, 1366, 1440, 1536, 1600, 1920],
                         dtype=np.int32), size=n),
            "MobilePhone": rng.integers(0, 8, n, dtype=np.int32),
            "MobilePhoneModel": model_ids[model_pick],
            "SearchPhrase": phrase_ids[phrase_pick],
            "URL": url_ids[_zipf_choice(rng, len(url_pool), n)],
            "Title": title_ids[np.where(
                rng.random(n) < 0.3, 0,
                1 + _zipf_choice(rng, len(title_pool) - 1, n))],
        }

    def schema(self, table: str = "hits") -> dtypes.Schema:
        assert table == "hits"
        return HITS_SCHEMA


QUERIES = {
    "q0": "select count(*) as c from hits",
    "q1": "select count(*) as c from hits where AdvEngineID <> 0",
    "q2": ("select sum(AdvEngineID) as s, count(*) as c, "
           "avg(ResolutionWidth) as w from hits"),
    "q3": "select avg(UserID) as u from hits",
    "q4": "select count(distinct UserID) as u from hits",
    "q5": "select count(distinct SearchPhrase) as p from hits",
    "q6": ("select min(EventDate) as lo, max(EventDate) as hi "
           "from hits"),
    "q7": ("select AdvEngineID, count(*) as c from hits "
           "where AdvEngineID <> 0 group by AdvEngineID "
           "order by count(*) desc, AdvEngineID"),
    "q8": ("select RegionID, count(distinct UserID) as u from hits "
           "group by RegionID order by u desc, RegionID limit 10"),
    "q9": ("select RegionID, sum(AdvEngineID) as s, count(*) as c, "
           "avg(ResolutionWidth) as w, count(distinct UserID) as u "
           "from hits group by RegionID order by c desc, RegionID "
           "limit 10"),
    "q10": ("select MobilePhoneModel, count(distinct UserID) as u "
            "from hits where MobilePhoneModel <> '' "
            "group by MobilePhoneModel "
            "order by u desc, MobilePhoneModel limit 10"),
    "q11": ("select MobilePhone, MobilePhoneModel, "
            "count(distinct UserID) as u from hits "
            "where MobilePhoneModel <> '' "
            "group by MobilePhone, MobilePhoneModel "
            "order by u desc, MobilePhone, MobilePhoneModel limit 10"),
    "q12": ("select SearchPhrase, count(*) as c from hits "
            "where SearchPhrase <> '' group by SearchPhrase "
            "order by c desc, SearchPhrase limit 10"),
    "q13": ("select SearchPhrase, count(distinct UserID) as u from hits "
            "where SearchPhrase <> '' group by SearchPhrase "
            "order by u desc, SearchPhrase limit 10"),
    "q14": ("select SearchEngineID, SearchPhrase, count(*) as c "
            "from hits where SearchPhrase <> '' "
            "group by SearchEngineID, SearchPhrase "
            "order by c desc, SearchEngineID, SearchPhrase limit 10"),
    "q15": ("select UserID, count(*) as c from hits group by UserID "
            "order by c desc, UserID limit 10"),
    "q16": ("select UserID, SearchPhrase, count(*) as c from hits "
            "group by UserID, SearchPhrase "
            "order by c desc, UserID, SearchPhrase limit 10"),
    "q17": ("select UserID, extract(minute from EventTime) as m, "
            "SearchPhrase, count(*) as c from hits "
            "group by UserID, extract(minute from EventTime), "
            "SearchPhrase order by c desc, UserID, m, SearchPhrase "
            "limit 10"),
    "q18": "select UserID from hits where UserID = 43509093289964",
    "q19": ("select count(*) as c from hits "
            "where URL like '%google%'"),
    "q20": ("select SearchPhrase, min(URL) as u, count(*) as c "
            "from hits where URL like '%google%' "
            "and SearchPhrase <> '' group by SearchPhrase "
            "order by c desc, SearchPhrase limit 10"),
    "q21": ("select Title, count(*) as c from hits "
            "where Title <> '' and URL like '%google%' "
            "group by Title order by c desc, Title limit 10"),
    "q22": ("select SearchPhrase, min(URL) as u, min(Title) as t, "
            "count(*) as c, count(distinct UserID) as uu from hits "
            "where Title like '%news%' "
            "and URL not like '%.google.%' "
            "and SearchPhrase <> '' group by SearchPhrase "
            "order by c desc, SearchPhrase limit 10"),
    "q24": ("select SearchPhrase, EventTime from hits "
            "where SearchPhrase <> '' order by EventTime limit 10"),
    "q25": ("select SearchPhrase from hits where SearchPhrase <> '' "
            "order by SearchPhrase limit 10"),
    "q26": ("select SearchPhrase from hits where SearchPhrase <> '' "
            "order by EventTime, SearchPhrase limit 10"),
    "q27": ("select CounterID, avg(length(URL)) as l, count(*) as c "
            "from hits where URL <> '' group by CounterID "
            "having count(*) > 4 order by l desc, CounterID "
            "limit 25"),
}


def reference_answers(data: ClickBenchData) -> dict[str, object]:
    """Independent numpy reference results (the canondata)."""
    h = data.hits
    n = len(h["WatchID"])
    phrases = np.array(
        data.dicts["SearchPhrase"].values + [b""], dtype=object
    )[h["SearchPhrase"]]
    models = np.array(
        data.dicts["MobilePhoneModel"].values + [b""], dtype=object
    )[h["MobilePhoneModel"]]
    adv = h["AdvEngineID"]
    out: dict[str, object] = {}
    out["q0"] = n
    out["q1"] = int((adv != 0).sum())
    out["q2"] = (int(adv.sum()), n,
                 float(h["ResolutionWidth"].astype(np.float64).mean()))
    out["q3"] = float(h["UserID"].astype(np.float64).mean())
    out["q4"] = len(set(h["UserID"].tolist()))
    out["q5"] = len(set(h["SearchPhrase"].tolist()))
    out["q6"] = (int(h["EventDate"].min()), int(h["EventDate"].max()))
    c7 = collections.Counter(adv[adv != 0].tolist())
    out["q7"] = sorted(c7.items(), key=lambda kv: (-kv[1], kv[0]))
    u8: dict = collections.defaultdict(set)
    for r, u in zip(h["RegionID"].tolist(), h["UserID"].tolist()):
        u8[r].add(u)
    out["q8"] = sorted(((k, len(v)) for k, v in u8.items()),
                       key=lambda kv: (-kv[1], kv[0]))[:10]
    g9: dict = {}
    for r, a, w, u in zip(h["RegionID"].tolist(), adv.tolist(),
                          h["ResolutionWidth"].tolist(),
                          h["UserID"].tolist()):
        st = g9.setdefault(r, [0, 0, 0, set()])
        st[0] += a
        st[1] += 1
        st[2] += w
        st[3].add(u)
    out["q9"] = [
        (r, st[0], st[1], st[2] / st[1], len(st[3]))
        for r, st in sorted(g9.items(),
                            key=lambda kv: (-kv[1][1], kv[0]))[:10]
    ]
    u10: dict = collections.defaultdict(set)
    u11: dict = collections.defaultdict(set)
    for m, ph, u in zip(models, h["MobilePhone"].tolist(),
                        h["UserID"].tolist()):
        if m != b"":
            u10[m].add(u)
            u11[(ph, m)].add(u)
    out["q10"] = sorted(((k, len(v)) for k, v in u10.items()),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    out["q11"] = sorted(((k, len(v)) for k, v in u11.items()),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    c12 = collections.Counter(p for p in phrases if p != b"")
    out["q12"] = sorted(c12.items(), key=lambda kv: (-kv[1], kv[0]))[:10]
    u13: dict = collections.defaultdict(set)
    for p, u in zip(phrases, h["UserID"].tolist()):
        if p != b"":
            u13[p].add(u)
    out["q13"] = sorted(((k, len(v)) for k, v in u13.items()),
                        key=lambda kv: (-kv[1], kv[0]))[:10]

    urls = np.array(data.dicts["URL"].values + [b""],
                    dtype=object)[h["URL"]]
    titles = np.array(data.dicts["Title"].values + [b""],
                      dtype=object)[h["Title"]]
    c14 = collections.Counter(
        (e, p) for e, p in zip(h["SearchEngineID"].tolist(), phrases)
        if p != b"")
    out["q14"] = sorted(
        ((k, v) for k, v in c14.items()),
        key=lambda kv: (-kv[1], kv[0][0], kv[0][1]))[:10]
    c15 = collections.Counter(h["UserID"].tolist())
    out["q15"] = sorted(c15.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    c16 = collections.Counter(zip(h["UserID"].tolist(), phrases))
    out["q16"] = sorted(c16.items(),
                        key=lambda kv: (-kv[1], kv[0][0], kv[0][1]))[:10]
    minutes = ((h["EventTime"] // 60_000_000) % 60).tolist()
    c17 = collections.Counter(
        zip(h["UserID"].tolist(), minutes, phrases))
    out["q17"] = sorted(
        c17.items(),
        key=lambda kv: (-kv[1], kv[0][0], kv[0][1], kv[0][2]))[:10]
    out["q18"] = [u for u in h["UserID"].tolist()
                  if u == 43509093289964]
    googley = np.array([b"google" in u for u in urls])
    out["q19"] = int(googley.sum())
    g20: dict = {}
    for u, p, g in zip(urls, phrases, googley):
        if g and p != b"":
            st = g20.setdefault(p, [u, 0])
            st[0] = min(st[0], u)
            st[1] += 1
    out["q20"] = sorted(((k, v[0], v[1]) for k, v in g20.items()),
                        key=lambda kv: (-kv[2], kv[0]))[:10]
    c21 = collections.Counter(
        t for t, g in zip(titles, googley) if g and t != b"")
    out["q21"] = sorted(c21.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]

    g22: dict = {}
    for u, t, p, uid in zip(urls, titles, phrases,
                            h["UserID"].tolist()):
        if p == b"" or b"news" not in t or b".google." in u:
            continue
        st = g22.setdefault(p, [u, t, 0, set()])
        st[0] = min(st[0], u)
        st[1] = min(st[1], t)
        st[2] += 1
        st[3].add(uid)
    out["q22"] = sorted(
        ((k, v[0], v[1], v[2], len(v[3])) for k, v in g22.items()),
        key=lambda r: (-r[3], r[0]))[:10]

    ev = h["EventTime"].tolist()
    nonempty = [(e, p) for e, p in zip(ev, phrases) if p != b""]
    # q24 orders by EventTime only: verify the (time, phrase)
    # MULTISET of the first 10 — ties make the exact order free
    out["q24"] = sorted(nonempty)[:10]
    out["q25"] = sorted((p for _e, p in nonempty))[:10]
    out["q26"] = [p for _e, p in sorted(nonempty)[:10]]

    g27: dict = {}
    for cid, u in zip(h["CounterID"].tolist(), urls):
        if u == b"":
            continue
        st = g27.setdefault(cid, [0, 0])
        st[0] += len(u)
        st[1] += 1
    out["q27"] = sorted(
        ((cid, s / n, n) for cid, (s, n) in g27.items() if n > 4),
        key=lambda r: (-r[1], r[0]))[:25]
    return out


def run_clickbench(rows: int = 100_000, queries=None, iterations: int = 1,
                   seed: int = 42, verify: bool = True):
    """Plan+execute the query set; optionally verify vs the reference.
    Returns [(name, best_seconds, result_rows)]."""
    import time

    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan import Database, execute_plan, to_host
    from ydb_tpu.sql.parser import parse
    from ydb_tpu.sql.planner import Catalog, plan_select_full

    data = ClickBenchData(rows=rows, seed=seed)
    db = Database(
        sources={"hits": ColumnSource(data.hits, HITS_SCHEMA, data.dicts)},
        dicts=data.dicts,
    )
    catalog = Catalog(schemas={"hits": HITS_SCHEMA},
                      primary_keys={"hits": ("WatchID",)},
                      dicts=data.dicts)
    want = reference_answers(data) if verify else {}
    names = queries or sorted(QUERIES, key=lambda q: int(q[1:]))
    results = []
    for name in names:
        pq = plan_select_full(parse(QUERIES[name]), catalog)
        plan = pq.plan
        out = to_host(execute_plan(plan, db))  # warmup/compile
        if verify:
            _verify(name, out, want[name], data, pq)
        best = float("inf")
        for _ in range(max(1, iterations)):
            t0 = time.monotonic()
            out = to_host(execute_plan(plan, db))
            best = min(best, time.monotonic() - t0)
        results.append((name, best, out.num_rows))
    return results


def _verify(name: str, out, want, data, pq=None) -> None:
    def ints(col):
        return [int(v) for v in np.asarray(out.cols[col][0])]

    def strs(col):
        src = pq.dict_aliases.get(col, col) if pq is not None else col
        return data.dicts[src].decode(np.asarray(out.cols[col][0]))

    if name in ("q0", "q1"):
        assert ints("c")[0] == want, (name, ints("c"), want)
    elif name == "q2":
        s, c, w = want
        assert ints("s")[0] == s and ints("c")[0] == c
        assert abs(float(out.cols["w"][0][0]) - w) < 1e-9
    elif name == "q3":
        assert abs(float(out.cols["u"][0][0]) - want) < 1e-9
    elif name in ("q4", "q5"):
        col = "u" if name == "q4" else "p"
        assert ints(col)[0] == want
    elif name == "q6":
        assert (ints("lo")[0], ints("hi")[0]) == want
    elif name == "q7":
        got = list(zip(ints("AdvEngineID"), ints("c")))
        assert got == want, (name, got[:5], want[:5])
    elif name == "q8":
        got = list(zip(ints("RegionID"), ints("u")))
        assert got == want, (name, got[:5], want[:5])
    elif name == "q9":
        got = list(zip(ints("RegionID"), ints("s"), ints("c"),
                       [float(v) for v in np.asarray(out.cols["w"][0])],
                       ints("u")))
        assert len(got) == len(want)
        for (gr, gs, gc, gw, gu), (wr, ws, wc, ww, wu) in zip(got, want):
            assert (gr, gs, gc, gu) == (wr, ws, wc, wu)
            assert abs(gw - ww) < 1e-9
    elif name == "q10":
        got = list(zip(strs("MobilePhoneModel"), ints("u")))
        assert got == want
    elif name == "q11":
        got = list(zip(
            zip(ints("MobilePhone"), strs("MobilePhoneModel")),
            ints("u")))
        got = [((a, b), u) for (a, b), u in got]
        assert got == want
    elif name in ("q12", "q13"):
        col = "c" if name == "q12" else "u"
        got = list(zip(strs("SearchPhrase"), ints(col)))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q14":
        got = [((e, p), c) for e, p, c in zip(
            ints("SearchEngineID"), strs("SearchPhrase"), ints("c"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q15":
        got = list(zip(ints("UserID"), ints("c")))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q16":
        got = [((u, p), c) for u, p, c in zip(
            ints("UserID"), strs("SearchPhrase"), ints("c"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q17":
        got = [((u, m, p), c) for u, m, p, c in zip(
            ints("UserID"), ints("m"), strs("SearchPhrase"),
            ints("c"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q18":
        assert ints("UserID") == want if out.num_rows else want == []
    elif name == "q19":
        assert ints("c")[0] == want, (name, ints("c"), want)
    elif name == "q20":
        got = list(zip(strs("SearchPhrase"), strs("u"), ints("c")))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q21":
        got = list(zip(strs("Title"), ints("c")))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q22":
        got = list(zip(strs("SearchPhrase"), strs("u"), strs("t"),
                       ints("c"), ints("uu")))
        assert got == want, (name, got[:2], want[:2])
    elif name == "q24":
        got = sorted(zip(ints("EventTime"), strs("SearchPhrase")))
        # tie-tolerant: same multiset of (time, phrase), time-ordered
        assert [e for e, _ in got] == [e for e, _ in want] and \
            sorted(got) == sorted(want), (name, got[:3], want[:3])
    elif name in ("q25", "q26"):
        got = strs("SearchPhrase")
        assert got == want, (name, got[:3], want[:3])
    elif name == "q27":
        got = list(zip(ints("CounterID"),
                       [float(v) for v in
                        np.asarray(out.cols["l"][0])],
                       ints("c")))
        assert len(got) == len(want)
        for (gc, gl, gn), (wc, wl, wn) in zip(got, want):
            assert (gc, gn) == (wc, wn), (name, gc, wc)
            assert abs(gl - wl) < 1e-9, (name, gl, wl)
    else:
        raise KeyError(name)
