"""ClickBench workload: hits-table generator, query set, reference
answers (BASELINE configs 3/5; reference
ydb/library/workload/clickbench/click_bench_queries.sql and the
canondata under ydb/tests/functional/clickbench/).

The hits schema here is the subset of ClickBench's 105 columns that the
implemented queries touch; distributions are synthetic-but-skewed
(zipf-ish region/phrase popularity, mostly-empty search phrases) so the
queries exercise the same shapes: wide scans, high-cardinality group-by,
COUNT(DISTINCT) — including Q9's mix of distinct and plain aggregates —
and top-N by aggregate. Canonical answers come from
``reference_answers`` — an independent numpy implementation the engine
results must match exactly (the canondata pattern). The dict below
covers ALL 43 official queries (q0-q42), numbered as in
click_bench_queries.sql; scale-sensitive HAVING thresholds (q27/q28)
adapt 100000 -> 4 for synthetic row counts, and top-N queries add
deterministic ORDER BY tiebreakers so verification is exact.
"""

from __future__ import annotations

import collections

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet

HITS_SCHEMA = dtypes.schema(
    ("WatchID", dtypes.INT64, False),
    ("UserID", dtypes.INT64, False),
    ("EventDate", dtypes.DATE, False),
    ("EventTime", dtypes.TIMESTAMP, False),
    ("CounterID", dtypes.INT32, False),
    ("RegionID", dtypes.INT32, False),
    ("AdvEngineID", dtypes.INT32, False),
    ("SearchEngineID", dtypes.INT32, False),
    ("ResolutionWidth", dtypes.INT32, False),
    ("MobilePhone", dtypes.INT32, False),
    ("MobilePhoneModel", dtypes.STRING, False),
    ("SearchPhrase", dtypes.STRING, False),
    ("URL", dtypes.STRING, False),
    ("Title", dtypes.STRING, False),
    ("Referer", dtypes.STRING, False),
    ("ClientIP", dtypes.INT64, False),
    ("IsRefresh", dtypes.INT32, False),
    ("DontCountHits", dtypes.INT32, False),
    ("IsLink", dtypes.INT32, False),
    ("IsDownload", dtypes.INT32, False),
    ("TraficSourceID", dtypes.INT32, False),
    ("URLHash", dtypes.INT64, False),
    ("RefererHash", dtypes.INT64, False),
    ("WindowClientWidth", dtypes.INT32, False),
    ("WindowClientHeight", dtypes.INT32, False),
)

# spec constants the point-filter queries (q40/q41) probe for; the
# generator plants them so synthetic runs return rows
URLHASH_HOT = 2868770270353813622
REFERERHASH_HOT = 3594120000172545465

_PHONE_MODELS = [b"", b"iPhone 2", b"iPhone 4", b"Nokia 3310",
                 b"Galaxy S", b"Pixel", b"Xperia Z", b"Moto G"]
_PHRASE_WORDS = [b"weather", b"news", b"cats", b"tpu", b"database",
                 b"flights", b"pizza", b"maps", b"music", b"jobs"]


def _zipf_choice(rng, n_values: int, size: int) -> np.ndarray:
    """Skewed (zipf-ish) ids in [0, n_values): few heavy hitters."""
    z = rng.zipf(1.5, size=size)
    return np.minimum(z - 1, n_values - 1).astype(np.int64)


class ClickBenchData:
    """Generated hits table + shared dictionaries."""

    def __init__(self, rows: int = 100_000, seed: int = 42):
        rng = np.random.default_rng(seed)
        self.dicts = DictionarySet()
        n = rows
        d0 = int(np.datetime64("2013-07-01", "D").astype(np.int32))
        n_users = max(n // 20, 10)

        phrase_pool = [b""] + [
            b" ".join(rng.choice(_PHRASE_WORDS,
                                 size=rng.integers(1, 4), replace=True))
            for _ in range(999)
        ]
        phrase_d = self.dicts.for_column("SearchPhrase")
        phrase_ids = np.array([phrase_d.add(p) for p in phrase_pool],
                              dtype=np.int32)
        # ~77% of hits have no search phrase (ClickBench-like sparsity)
        phrase_pick = np.where(
            rng.random(n) < 0.77, 0,
            1 + _zipf_choice(rng, len(phrase_pool) - 1, n))

        model_d = self.dicts.for_column("MobilePhoneModel")
        model_ids = np.array([model_d.add(m) for m in _PHONE_MODELS],
                             dtype=np.int32)
        model_pick = np.where(
            rng.random(n) < 0.9, 0,
            1 + _zipf_choice(rng, len(_PHONE_MODELS) - 1, n))

        # URLs: a skewed pool of synthetic paths; 2 of 7 hosts are
        # google.* so ~29% of rows match the LIKE '%google%' queries
        hosts = [b"example.com", b"news.site", b"google.com",
                 b"shop.io", b"google.de", b"docs.org", b"blog.net"]
        url_pool = [
            b"http://%s/%s/%d" % (rng.choice(hosts),
                                  rng.choice(_PHRASE_WORDS),
                                  rng.integers(0, 100))
            for _ in range(2000)
        ]
        url_d = self.dicts.for_column("URL")
        url_ids = np.array([url_d.add(u) for u in url_pool],
                           dtype=np.int32)
        title_pool = [b""] + [
            (b"Google %s - page %d" if i % 5 == 0
             else b"%s - page %d") % (rng.choice(_PHRASE_WORDS),
                                      rng.integers(0, 50))
            for i in range(499)
        ]
        title_d = self.dicts.for_column("Title")
        title_ids = np.array([title_d.add(t) for t in title_pool],
                             dtype=np.int32)

        # referers: skewed pool over hosts incl. www.-prefixed ones
        # (q28 groups by CutWWW(GetHost(Referer))); ~35% empty
        ref_hosts = [b"www.google.com", b"news.site", b"google.de",
                     b"www.shop.io", b"blog.net", b"example.com"]
        referer_pool = [b""] + [
            b"http://%s/%s/%d" % (rng.choice(ref_hosts),
                                  rng.choice(_PHRASE_WORDS),
                                  rng.integers(0, 40))
            for _ in range(499)
        ]
        referer_d = self.dicts.for_column("Referer")
        referer_ids = np.array([referer_d.add(r) for r in referer_pool],
                               dtype=np.int32)
        referer_pick = np.where(
            rng.random(n) < 0.35, 0,
            1 + _zipf_choice(rng, len(referer_pool) - 1, n))

        # hash columns: skewed pools seeded with the spec's hot
        # constants so q40/q41 point filters hit rows
        urlhash_pool = np.concatenate([
            np.array([URLHASH_HOT], dtype=np.int64),
            rng.integers(1, 1 << 62, 199, dtype=np.int64)])
        refhash_pool = np.concatenate([
            np.array([REFERERHASH_HOT], dtype=np.int64),
            rng.integers(1, 1 << 62, 199, dtype=np.int64)])

        dates = (d0 + rng.integers(0, 31, n)).astype(np.int32)
        self.hits: dict[str, np.ndarray] = {
            "WatchID": rng.integers(1, 1 << 62, n, dtype=np.int64),
            "UserID": (_zipf_choice(rng, n_users, n) + 1),
            "EventDate": dates,
            "EventTime": (dates.astype(np.int64) * 86_400_000_000
                          + rng.integers(0, 86_400, n) * 1_000_000),
            # CounterID 62 is a heavy hitter (~10%): the q36-q42 site
            # analytics queries all filter CounterID = 62
            "CounterID": np.where(
                rng.random(n) < 0.10, 62,
                rng.integers(1, 10_000, n)).astype(np.int32),
            "RegionID": _zipf_choice(rng, 5000, n).astype(np.int32),
            "AdvEngineID": np.where(
                rng.random(n) < 0.95, 0,
                rng.integers(1, 20, n)).astype(np.int32),
            "SearchEngineID": np.where(
                rng.random(n) < 0.7, 0,
                rng.integers(1, 8, n)).astype(np.int32),
            "ResolutionWidth": rng.choice(
                np.array([1024, 1280, 1366, 1440, 1536, 1600, 1920],
                         dtype=np.int32), size=n),
            "MobilePhone": rng.integers(0, 8, n, dtype=np.int32),
            "MobilePhoneModel": model_ids[model_pick],
            "SearchPhrase": phrase_ids[phrase_pick],
            "URL": url_ids[_zipf_choice(rng, len(url_pool), n)],
            "Title": title_ids[np.where(
                rng.random(n) < 0.3, 0,
                1 + _zipf_choice(rng, len(title_pool) - 1, n))],
            "Referer": referer_ids[referer_pick],
            "ClientIP": (0x0A000000
                         + _zipf_choice(rng, max(n // 30, 10), n)),
            "IsRefresh": (rng.random(n) < 0.12).astype(np.int32),
            "DontCountHits": (rng.random(n) < 0.05).astype(np.int32),
            "IsLink": (rng.random(n) < 0.15).astype(np.int32),
            "IsDownload": (rng.random(n) < 0.03).astype(np.int32),
            "TraficSourceID": rng.choice(
                np.array([-1, 0, 1, 2, 3, 6], dtype=np.int32), size=n,
                p=[0.1, 0.35, 0.2, 0.15, 0.1, 0.1]),
            "URLHash": urlhash_pool[_zipf_choice(
                rng, len(urlhash_pool), n)],
            "RefererHash": refhash_pool[_zipf_choice(
                rng, len(refhash_pool), n)],
            "WindowClientWidth": rng.choice(
                np.array([0, 1024, 1280, 1366, 1920], dtype=np.int32),
                size=n),
            "WindowClientHeight": rng.choice(
                np.array([0, 600, 720, 768, 1080], dtype=np.int32),
                size=n),
        }

    def schema(self, table: str = "hits") -> dtypes.Schema:
        assert table == "hits"
        return HITS_SCHEMA


QUERIES = {
    "q0": "select count(*) as c from hits",
    "q1": "select count(*) as c from hits where AdvEngineID <> 0",
    "q2": ("select sum(AdvEngineID) as s, count(*) as c, "
           "avg(ResolutionWidth) as w from hits"),
    "q3": "select avg(UserID) as u from hits",
    "q4": "select count(distinct UserID) as u from hits",
    "q5": "select count(distinct SearchPhrase) as p from hits",
    "q6": ("select min(EventDate) as lo, max(EventDate) as hi "
           "from hits"),
    "q7": ("select AdvEngineID, count(*) as c from hits "
           "where AdvEngineID <> 0 group by AdvEngineID "
           "order by count(*) desc, AdvEngineID"),
    "q8": ("select RegionID, count(distinct UserID) as u from hits "
           "group by RegionID order by u desc, RegionID limit 10"),
    "q9": ("select RegionID, sum(AdvEngineID) as s, count(*) as c, "
           "avg(ResolutionWidth) as w, count(distinct UserID) as u "
           "from hits group by RegionID order by c desc, RegionID "
           "limit 10"),
    "q10": ("select MobilePhoneModel, count(distinct UserID) as u "
            "from hits where MobilePhoneModel <> '' "
            "group by MobilePhoneModel "
            "order by u desc, MobilePhoneModel limit 10"),
    "q11": ("select MobilePhone, MobilePhoneModel, "
            "count(distinct UserID) as u from hits "
            "where MobilePhoneModel <> '' "
            "group by MobilePhone, MobilePhoneModel "
            "order by u desc, MobilePhone, MobilePhoneModel limit 10"),
    "q12": ("select SearchPhrase, count(*) as c from hits "
            "where SearchPhrase <> '' group by SearchPhrase "
            "order by c desc, SearchPhrase limit 10"),
    "q13": ("select SearchPhrase, count(distinct UserID) as u from hits "
            "where SearchPhrase <> '' group by SearchPhrase "
            "order by u desc, SearchPhrase limit 10"),
    "q14": ("select SearchEngineID, SearchPhrase, count(*) as c "
            "from hits where SearchPhrase <> '' "
            "group by SearchEngineID, SearchPhrase "
            "order by c desc, SearchEngineID, SearchPhrase limit 10"),
    "q15": ("select UserID, count(*) as c from hits group by UserID "
            "order by c desc, UserID limit 10"),
    "q16": ("select UserID, SearchPhrase, count(*) as c from hits "
            "group by UserID, SearchPhrase "
            "order by c desc, UserID, SearchPhrase limit 10"),
    "q17": ("select UserID, SearchPhrase, count(*) as c from hits "
            "group by UserID, SearchPhrase limit 10"),
    "q18": ("select UserID, extract(minute from EventTime) as m, "
            "SearchPhrase, count(*) as c from hits "
            "group by UserID, extract(minute from EventTime), "
            "SearchPhrase order by c desc, UserID, m, SearchPhrase "
            "limit 10"),
    "q19": "select UserID from hits where UserID = 435090932899640449",
    "q20": ("select count(*) as c from hits "
            "where URL like '%google%'"),
    "q21": ("select SearchPhrase, min(URL) as u, count(*) as c "
            "from hits where URL like '%google%' "
            "and SearchPhrase <> '' group by SearchPhrase "
            "order by c desc, SearchPhrase limit 10"),
    "q22": ("select SearchPhrase, min(URL) as u, min(Title) as t, "
            "count(*) as c, count(distinct UserID) as uu from hits "
            "where Title like '%Google%' "
            "and URL not like '%.google.%' "
            "and SearchPhrase <> '' group by SearchPhrase "
            "order by c desc, SearchPhrase limit 10"),
    "q23": ("select * from hits where URL like '%google%' "
            "order by EventTime limit 10"),
    "q24": ("select SearchPhrase, EventTime from hits "
            "where SearchPhrase <> '' order by EventTime limit 10"),
    "q25": ("select SearchPhrase from hits where SearchPhrase <> '' "
            "order by SearchPhrase limit 10"),
    "q26": ("select SearchPhrase, EventTime from hits "
            "where SearchPhrase <> '' "
            "order by EventTime, SearchPhrase limit 10"),
    "q27": ("select CounterID, avg(length(URL)) as l, count(*) as c "
            "from hits where URL <> '' group by CounterID "
            "having count(*) > 4 order by l desc, CounterID "
            "limit 25"),
    # q28: official groups by Url::CutWWW(Url::GetHost(Referer)); the
    # HAVING threshold adapts 100000 -> 4 for synthetic scale (as q27)
    "q28": ("select cutwww(gethost(Referer)) as hkey, "
            "avg(length(Referer)) as l, count(*) as c, "
            "min(Referer) as m from hits where Referer <> '' "
            "group by hkey having count(*) > 4 "
            "order by l desc, hkey limit 25"),
    "q29": ("select sum(ResolutionWidth) as s0, " + ", ".join(
        f"sum(ResolutionWidth + {k}) as s{k}" for k in range(1, 90))
        + " from hits"),
    "q30": ("select SearchEngineID, ClientIP, count(*) as c, "
            "sum(IsRefresh) as r, avg(ResolutionWidth) as w from hits "
            "where SearchPhrase <> '' "
            "group by SearchEngineID, ClientIP "
            "order by c desc, SearchEngineID, ClientIP limit 10"),
    "q31": ("select WatchID, ClientIP, count(*) as c, "
            "sum(IsRefresh) as r, avg(ResolutionWidth) as w from hits "
            "where SearchPhrase <> '' group by WatchID, ClientIP "
            "order by c desc, WatchID, ClientIP limit 10"),
    "q32": ("select WatchID, ClientIP, count(*) as c, "
            "sum(IsRefresh) as r, avg(ResolutionWidth) as w from hits "
            "group by WatchID, ClientIP "
            "order by c desc, WatchID, ClientIP limit 10"),
    "q33": ("select URL, count(*) as c from hits group by URL "
            "order by c desc, URL limit 10"),
    "q34": ("select UserID, URL, count(*) as c from hits "
            "group by UserID, URL order by c desc, UserID, URL "
            "limit 10"),
    "q35": ("select ClientIP, ClientIP - 1 as c1, ClientIP - 2 as c2, "
            "ClientIP - 3 as c3, count(*) as c from hits "
            "group by ClientIP, c1, c2, c3 "
            "order by c desc, ClientIP limit 10"),
    "q36": ("select URL, count(*) as pv from hits "
            "where CounterID = 62 "
            "and EventDate >= date '2013-07-01' "
            "and EventDate <= date '2013-07-31' "
            "and DontCountHits = 0 and IsRefresh = 0 and URL <> '' "
            "group by URL order by pv desc, URL limit 10"),
    "q37": ("select Title, count(*) as pv from hits "
            "where CounterID = 62 "
            "and EventDate >= date '2013-07-01' "
            "and EventDate <= date '2013-07-31' "
            "and DontCountHits = 0 and IsRefresh = 0 and Title <> '' "
            "group by Title order by pv desc, Title limit 10"),
    "q38": ("select URL, count(*) as pv from hits "
            "where CounterID = 62 "
            "and EventDate >= date '2013-07-01' "
            "and EventDate <= date '2013-07-31' "
            "and IsRefresh = 0 and IsLink <> 0 and IsDownload = 0 "
            "group by URL order by pv desc, URL limit 10"),
    "q39": ("select TraficSourceID, SearchEngineID, AdvEngineID, "
            "case when SearchEngineID = 0 and AdvEngineID = 0 "
            "then Referer else '' end as src, URL as dst, "
            "count(*) as pv from hits where CounterID = 62 "
            "and EventDate >= date '2013-07-01' "
            "and EventDate <= date '2013-07-31' and IsRefresh = 0 "
            "group by TraficSourceID, SearchEngineID, AdvEngineID, "
            "src, dst order by pv desc, TraficSourceID, "
            "SearchEngineID, AdvEngineID, src, dst limit 10"),
    "q40": ("select URLHash, EventDate, count(*) as pv from hits "
            "where CounterID = 62 "
            "and EventDate >= date '2013-07-01' "
            "and EventDate <= date '2013-07-31' and IsRefresh = 0 "
            "and TraficSourceID in (-1, 6) "
            f"and RefererHash = {REFERERHASH_HOT} "
            "group by URLHash, EventDate "
            "order by pv desc, URLHash, EventDate limit 10"),
    "q41": ("select WindowClientWidth, WindowClientHeight, "
            "count(*) as pv from hits where CounterID = 62 "
            "and EventDate >= date '2013-07-01' "
            "and EventDate <= date '2013-07-31' and IsRefresh = 0 "
            f"and DontCountHits = 0 and URLHash = {URLHASH_HOT} "
            "group by WindowClientWidth, WindowClientHeight "
            "order by pv desc, WindowClientWidth, WindowClientHeight "
            "limit 10"),
    "q42": ("select EventTime / 60000000 as minute, count(*) as pv "
            "from hits where CounterID = 62 "
            "and EventDate >= date '2013-07-14' "
            "and EventDate <= date '2013-07-15' and IsRefresh = 0 "
            "and DontCountHits = 0 group by minute "
            "order by minute limit 10"),
}


def reference_answers(data: ClickBenchData) -> dict[str, object]:
    """Independent numpy reference results (the canondata)."""
    h = data.hits
    n = len(h["WatchID"])
    phrases = np.array(
        data.dicts["SearchPhrase"].values + [b""], dtype=object
    )[h["SearchPhrase"]]
    models = np.array(
        data.dicts["MobilePhoneModel"].values + [b""], dtype=object
    )[h["MobilePhoneModel"]]
    adv = h["AdvEngineID"]
    out: dict[str, object] = {}
    out["q0"] = n
    out["q1"] = int((adv != 0).sum())
    out["q2"] = (int(adv.sum()), n,
                 float(h["ResolutionWidth"].astype(np.float64).mean()))
    out["q3"] = float(h["UserID"].astype(np.float64).mean())
    out["q4"] = len(set(h["UserID"].tolist()))
    out["q5"] = len(set(h["SearchPhrase"].tolist()))
    out["q6"] = (int(h["EventDate"].min()), int(h["EventDate"].max()))
    c7 = collections.Counter(adv[adv != 0].tolist())
    out["q7"] = sorted(c7.items(), key=lambda kv: (-kv[1], kv[0]))
    u8: dict = collections.defaultdict(set)
    for r, u in zip(h["RegionID"].tolist(), h["UserID"].tolist()):
        u8[r].add(u)
    out["q8"] = sorted(((k, len(v)) for k, v in u8.items()),
                       key=lambda kv: (-kv[1], kv[0]))[:10]
    g9: dict = {}
    for r, a, w, u in zip(h["RegionID"].tolist(), adv.tolist(),
                          h["ResolutionWidth"].tolist(),
                          h["UserID"].tolist()):
        st = g9.setdefault(r, [0, 0, 0, set()])
        st[0] += a
        st[1] += 1
        st[2] += w
        st[3].add(u)
    out["q9"] = [
        (r, st[0], st[1], st[2] / st[1], len(st[3]))
        for r, st in sorted(g9.items(),
                            key=lambda kv: (-kv[1][1], kv[0]))[:10]
    ]
    u10: dict = collections.defaultdict(set)
    u11: dict = collections.defaultdict(set)
    for m, ph, u in zip(models, h["MobilePhone"].tolist(),
                        h["UserID"].tolist()):
        if m != b"":
            u10[m].add(u)
            u11[(ph, m)].add(u)
    out["q10"] = sorted(((k, len(v)) for k, v in u10.items()),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    out["q11"] = sorted(((k, len(v)) for k, v in u11.items()),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    c12 = collections.Counter(p for p in phrases if p != b"")
    out["q12"] = sorted(c12.items(), key=lambda kv: (-kv[1], kv[0]))[:10]
    u13: dict = collections.defaultdict(set)
    for p, u in zip(phrases, h["UserID"].tolist()):
        if p != b"":
            u13[p].add(u)
    out["q13"] = sorted(((k, len(v)) for k, v in u13.items()),
                        key=lambda kv: (-kv[1], kv[0]))[:10]

    urls = np.array(data.dicts["URL"].values + [b""],
                    dtype=object)[h["URL"]]
    titles = np.array(data.dicts["Title"].values + [b""],
                      dtype=object)[h["Title"]]
    c14 = collections.Counter(
        (e, p) for e, p in zip(h["SearchEngineID"].tolist(), phrases)
        if p != b"")
    out["q14"] = sorted(
        ((k, v) for k, v in c14.items()),
        key=lambda kv: (-kv[1], kv[0][0], kv[0][1]))[:10]
    c15 = collections.Counter(h["UserID"].tolist())
    out["q15"] = sorted(c15.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    c16 = collections.Counter(zip(h["UserID"].tolist(), phrases))
    out["q16"] = sorted(c16.items(),
                        key=lambda kv: (-kv[1], kv[0][0], kv[0][1]))[:10]
    # q17: LIMIT without ORDER BY — the full group->count map; the
    # verifier checks the returned rows are a correct subset
    out["q17"] = dict(c16)
    minutes = ((h["EventTime"] // 60_000_000) % 60).tolist()
    c18 = collections.Counter(
        zip(h["UserID"].tolist(), minutes, phrases))
    out["q18"] = sorted(
        c18.items(),
        key=lambda kv: (-kv[1], kv[0][0], kv[0][1], kv[0][2]))[:10]
    out["q19"] = [u for u in h["UserID"].tolist()
                  if u == 435090932899640449]
    googley = np.array([b"google" in u for u in urls])
    out["q20"] = int(googley.sum())
    g21: dict = {}
    for u, p, g in zip(urls, phrases, googley):
        if g and p != b"":
            st = g21.setdefault(p, [u, 0])
            st[0] = min(st[0], u)
            st[1] += 1
    out["q21"] = sorted(((k, v[0], v[1]) for k, v in g21.items()),
                        key=lambda kv: (-kv[2], kv[0]))[:10]

    g22: dict = {}
    for u, t, p, uid in zip(urls, titles, phrases,
                            h["UserID"].tolist()):
        if p == b"" or b"Google" not in t or b".google." in u:
            continue
        st = g22.setdefault(p, [u, t, 0, set()])
        st[0] = min(st[0], u)
        st[1] = min(st[1], t)
        st[2] += 1
        st[3].add(uid)
    out["q22"] = sorted(
        ((k, v[0], v[1], v[2], len(v[3])) for k, v in g22.items()),
        key=lambda r: (-r[3], r[0]))[:10]

    ev = h["EventTime"].tolist()
    # q23 (SELECT * ... ORDER BY EventTime LIMIT 10): the verifier needs
    # the time-ordered prefix boundary + the matching rows' WatchIDs
    # per time (ties make exact row order free)
    wl = h["WatchID"].tolist()
    g23 = sorted((e, w) for e, w, g in zip(ev, wl, googley) if g)[:10]
    t23 = {e for e, _w in g23}
    by_time: dict = {e: set() for e in t23}
    for e, w, g in zip(ev, wl, googley):  # one pass over match rows
        if g and e in t23:
            by_time[e].add(w)
    out["q23"] = {"times": [e for e, _w in g23],
                  "rows_by_time": by_time}
    nonempty = [(e, p) for e, p in zip(ev, phrases) if p != b""]
    # q24 orders by EventTime only: verify the (time, phrase)
    # MULTISET of the first 10 — ties make the exact order free
    out["q24"] = sorted(nonempty)[:10]
    out["q25"] = sorted((p for _e, p in nonempty))[:10]
    out["q26"] = sorted(nonempty)[:10]

    g27: dict = {}
    for cid, u in zip(h["CounterID"].tolist(), urls):
        if u == b"":
            continue
        st = g27.setdefault(cid, [0, 0])
        st[0] += len(u)
        st[1] += 1
    out["q27"] = sorted(
        ((cid, s / n, n) for cid, (s, n) in g27.items() if n > 4),
        key=lambda r: (-r[1], r[0]))[:25]

    referers = np.array(
        data.dicts["Referer"].values + [b""], dtype=object
    )[h["Referer"]]

    def _host_cutwww(v: bytes) -> bytes:
        s = v.split(b"://", 1)[-1]
        s = s.split(b"/", 1)[0].split(b"?", 1)[0]
        return s[4:] if s.startswith(b"www.") else s

    g28: dict = {}
    for r in referers:
        if r == b"":
            continue
        st = g28.setdefault(_host_cutwww(r), [0, 0, None])
        st[0] += len(r)
        st[1] += 1
        st[2] = r if st[2] is None else min(st[2], r)
    out["q28"] = sorted(
        ((k, s / c, c, m) for k, (s, c, m) in g28.items() if c > 4),
        key=lambda r: (-r[1], r[0]))[:25]

    rw = h["ResolutionWidth"].astype(np.int64)
    out["q29"] = [int((rw + k).sum()) for k in range(90)]

    mask30 = np.array([p != b"" for p in phrases])
    g30: dict = {}
    for e, ip, rfr, w in zip(h["SearchEngineID"][mask30].tolist(),
                             h["ClientIP"][mask30].tolist(),
                             h["IsRefresh"][mask30].tolist(),
                             h["ResolutionWidth"][mask30].tolist()):
        st = g30.setdefault((e, ip), [0, 0, 0])
        st[0] += 1
        st[1] += rfr
        st[2] += w
    out["q30"] = sorted(
        ((k, c, r, s / c) for k, (c, r, s) in g30.items()),
        key=lambda r: (-r[1], r[0][0], r[0][1]))[:10]

    def _watch_ip(masked: np.ndarray):
        g: dict = {}
        for wid, ip, rfr, w in zip(
                h["WatchID"][masked].tolist(),
                h["ClientIP"][masked].tolist(),
                h["IsRefresh"][masked].tolist(),
                h["ResolutionWidth"][masked].tolist()):
            st = g.setdefault((wid, ip), [0, 0, 0])
            st[0] += 1
            st[1] += rfr
            st[2] += w
        return sorted(
            ((k, c, r, s / c) for k, (c, r, s) in g.items()),
            key=lambda r: (-r[1], r[0][0], r[0][1]))[:10]

    out["q31"] = _watch_ip(mask30)
    out["q32"] = _watch_ip(np.ones(n, dtype=bool))

    c33 = collections.Counter(u for u in urls)
    out["q33"] = sorted(c33.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    c34 = collections.Counter(zip(h["UserID"].tolist(), urls))
    out["q34"] = sorted(
        c34.items(), key=lambda kv: (-kv[1], kv[0][0], kv[0][1]))[:10]
    c35 = collections.Counter(h["ClientIP"].tolist())
    out["q35"] = sorted(c35.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]

    d_lo = int(np.datetime64("2013-07-01", "D").astype(np.int32))
    d_hi = int(np.datetime64("2013-07-31", "D").astype(np.int32))
    site = ((h["CounterID"] == 62) & (h["EventDate"] >= d_lo)
            & (h["EventDate"] <= d_hi))
    m36 = (site & (h["DontCountHits"] == 0) & (h["IsRefresh"] == 0)
           & np.array([u != b"" for u in urls]))
    c36 = collections.Counter(u for u in urls[m36])
    out["q36"] = sorted(c36.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    m37 = (site & (h["DontCountHits"] == 0) & (h["IsRefresh"] == 0)
           & np.array([t != b"" for t in titles]))
    c37 = collections.Counter(t for t in titles[m37])
    out["q37"] = sorted(c37.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]
    m38 = (site & (h["IsRefresh"] == 0) & (h["IsLink"] != 0)
           & (h["IsDownload"] == 0))
    c38 = collections.Counter(u for u in urls[m38])
    out["q38"] = sorted(c38.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:10]

    m39 = site & (h["IsRefresh"] == 0)
    c39 = collections.Counter(
        (int(ts), int(se), int(ad),
         r if (se == 0 and ad == 0) else b"", u)
        for ts, se, ad, r, u in zip(
            h["TraficSourceID"][m39].tolist(),
            h["SearchEngineID"][m39].tolist(),
            h["AdvEngineID"][m39].tolist(),
            referers[m39], urls[m39]))
    out["q39"] = sorted(
        c39.items(),
        key=lambda kv: (-kv[1],) + kv[0][:3] + (kv[0][3], kv[0][4])
    )[:10]

    m40 = (site & (h["IsRefresh"] == 0)
           & np.isin(h["TraficSourceID"], (-1, 6))
           & (h["RefererHash"] == REFERERHASH_HOT))
    c40 = collections.Counter(
        zip(h["URLHash"][m40].tolist(), h["EventDate"][m40].tolist()))
    out["q40"] = sorted(
        c40.items(), key=lambda kv: (-kv[1], kv[0][0], kv[0][1]))[:10]

    m41 = (site & (h["IsRefresh"] == 0) & (h["DontCountHits"] == 0)
           & (h["URLHash"] == URLHASH_HOT))
    c41 = collections.Counter(
        zip(h["WindowClientWidth"][m41].tolist(),
            h["WindowClientHeight"][m41].tolist()))
    out["q41"] = sorted(
        c41.items(), key=lambda kv: (-kv[1], kv[0][0], kv[0][1]))[:10]

    d14 = int(np.datetime64("2013-07-14", "D").astype(np.int32))
    d15 = int(np.datetime64("2013-07-15", "D").astype(np.int32))
    m42 = ((h["CounterID"] == 62) & (h["EventDate"] >= d14)
           & (h["EventDate"] <= d15) & (h["IsRefresh"] == 0)
           & (h["DontCountHits"] == 0))
    c42 = collections.Counter(
        (h["EventTime"][m42] // 60_000_000).tolist())
    out["q42"] = sorted(c42.items())[:10]
    return out


def run_clickbench(rows: int = 100_000, queries=None, iterations: int = 1,
                   seed: int = 42, verify: bool = True):
    """Plan+execute the query set; optionally verify vs the reference.
    Returns [(name, best_seconds, result_rows)]."""
    import time

    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan import Database, execute_plan, to_host
    from ydb_tpu.sql.parser import parse
    from ydb_tpu.sql.planner import Catalog, plan_select_full

    data = ClickBenchData(rows=rows, seed=seed)
    db = Database(
        sources={"hits": ColumnSource(data.hits, HITS_SCHEMA, data.dicts)},
        dicts=data.dicts,
    )
    catalog = Catalog(schemas={"hits": HITS_SCHEMA},
                      primary_keys={"hits": ("WatchID",)},
                      dicts=data.dicts)
    want = reference_answers(data) if verify else {}
    names = queries or sorted(QUERIES, key=lambda q: int(q[1:]))
    results = []
    for name in names:
        pq = plan_select_full(parse(QUERIES[name]), catalog)
        plan = pq.plan
        out = to_host(execute_plan(plan, db))  # warmup/compile
        if verify:
            _verify(name, out, want[name], data, pq)
        best = float("inf")
        for _ in range(max(1, iterations)):
            t0 = time.monotonic()
            out = to_host(execute_plan(plan, db))
            best = min(best, time.monotonic() - t0)
        results.append((name, best, out.num_rows))
    return results


def _verify(name: str, out, want, data, pq=None) -> None:
    def ints(col):
        return [int(v) for v in np.asarray(out.cols[col][0])]

    def strs(col):
        src = pq.dict_aliases.get(col, col) if pq is not None else col
        return data.dicts[src].decode(np.asarray(out.cols[col][0]))

    if name in ("q0", "q1"):
        assert ints("c")[0] == want, (name, ints("c"), want)
    elif name == "q2":
        s, c, w = want
        assert ints("s")[0] == s and ints("c")[0] == c
        assert abs(float(out.cols["w"][0][0]) - w) < 1e-9
    elif name == "q3":
        assert abs(float(out.cols["u"][0][0]) - want) < 1e-9
    elif name in ("q4", "q5"):
        col = "u" if name == "q4" else "p"
        assert ints(col)[0] == want
    elif name == "q6":
        assert (ints("lo")[0], ints("hi")[0]) == want
    elif name == "q7":
        got = list(zip(ints("AdvEngineID"), ints("c")))
        assert got == want, (name, got[:5], want[:5])
    elif name == "q8":
        got = list(zip(ints("RegionID"), ints("u")))
        assert got == want, (name, got[:5], want[:5])
    elif name == "q9":
        got = list(zip(ints("RegionID"), ints("s"), ints("c"),
                       [float(v) for v in np.asarray(out.cols["w"][0])],
                       ints("u")))
        assert len(got) == len(want)
        for (gr, gs, gc, gw, gu), (wr, ws, wc, ww, wu) in zip(got, want):
            assert (gr, gs, gc, gu) == (wr, ws, wc, wu)
            assert abs(gw - ww) < 1e-9
    elif name == "q10":
        got = list(zip(strs("MobilePhoneModel"), ints("u")))
        assert got == want
    elif name == "q11":
        got = list(zip(
            zip(ints("MobilePhone"), strs("MobilePhoneModel")),
            ints("u")))
        got = [((a, b), u) for (a, b), u in got]
        assert got == want
    elif name in ("q12", "q13"):
        col = "c" if name == "q12" else "u"
        got = list(zip(strs("SearchPhrase"), ints(col)))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q14":
        got = [((e, p), c) for e, p, c in zip(
            ints("SearchEngineID"), strs("SearchPhrase"), ints("c"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q15":
        got = list(zip(ints("UserID"), ints("c")))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q16":
        got = [((u, p), c) for u, p, c in zip(
            ints("UserID"), strs("SearchPhrase"), ints("c"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q17":
        # LIMIT without ORDER BY: any 10 (group, count) rows are valid
        # as long as each is a REAL group with the right count
        got = [((u, p), c) for u, p, c in zip(
            ints("UserID"), strs("SearchPhrase"), ints("c"))]
        assert len(got) == min(10, len(want))
        assert len({k for k, _c in got}) == len(got), "dup groups"
        for k, c in got:
            assert want.get(k) == c, (name, k, c, want.get(k))
    elif name == "q18":
        got = [((u, m, p), c) for u, m, p, c in zip(
            ints("UserID"), ints("m"), strs("SearchPhrase"),
            ints("c"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q19":
        assert ints("UserID") == want if out.num_rows else want == []
    elif name == "q20":
        assert ints("c")[0] == want, (name, ints("c"), want)
    elif name == "q21":
        got = list(zip(strs("SearchPhrase"), strs("u"), ints("c")))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q22":
        got = list(zip(strs("SearchPhrase"), strs("u"), strs("t"),
                       ints("c"), ints("uu")))
        assert got == want, (name, got[:2], want[:2])
    elif name == "q23":
        # SELECT * ordered by EventTime with free ties: the times must
        # be the true first-10, each row a real matching row
        got_times = ints("EventTime")
        assert got_times == want["times"], (name, got_times)
        for e, w in zip(got_times, ints("WatchID")):
            assert w in want["rows_by_time"][e], (name, e, w)
    elif name == "q24":
        got = sorted(zip(ints("EventTime"), strs("SearchPhrase")))
        # tie-tolerant: same multiset of (time, phrase), time-ordered
        assert [e for e, _ in got] == [e for e, _ in want] and \
            sorted(got) == sorted(want), (name, got[:3], want[:3])
    elif name == "q25":
        got = strs("SearchPhrase")
        assert got == want, (name, got[:3], want[:3])
    elif name == "q26":
        got = list(zip(ints("EventTime"), strs("SearchPhrase")))
        assert got == want, (name, got[:3], want[:3])
    elif name in ("q27", "q28"):
        kcol = "CounterID" if name == "q27" else "hkey"
        keys = ints(kcol) if name == "q27" else strs(kcol)
        got = list(zip(keys,
                       [float(v) for v in
                        np.asarray(out.cols["l"][0])],
                       ints("c")))
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert (g[0], g[2]) == (w[0], w[2]), (name, g, w)
            assert abs(g[1] - w[1]) < 1e-9, (name, g[1], w[1])
        if name == "q28":
            assert strs("m") == [w[3] for w in want]
    elif name == "q29":
        got = [ints(f"s{k}")[0] for k in range(90)]
        assert got == want, (name, got[:4], want[:4])
    elif name in ("q30", "q31", "q32"):
        kcol = "SearchEngineID" if name == "q30" else "WatchID"
        got = list(zip(zip(ints(kcol), ints("ClientIP")),
                       ints("c"), ints("r"),
                       [float(v) for v in np.asarray(out.cols["w"][0])]))
        assert len(got) == len(want)
        for (gk, gc, gr, gw), (wk, wc, wr, ww) in zip(got, want):
            assert (gk, gc, gr) == (wk, wc, wr), (name, gk, wk)
            assert abs(gw - ww) < 1e-9, (name, gw, ww)
    elif name == "q33":
        got = list(zip(strs("URL"), ints("c")))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q34":
        got = [((u, l), c) for u, l, c in zip(
            ints("UserID"), strs("URL"), ints("c"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q35":
        got = list(zip(ints("ClientIP"), ints("c")))
        assert got == want, (name, got[:3], want[:3])
        assert ints("c1") == [ip - 1 for ip, _c in want]
        assert ints("c2") == [ip - 2 for ip, _c in want]
        assert ints("c3") == [ip - 3 for ip, _c in want]
    elif name in ("q36", "q37", "q38"):
        col = "Title" if name == "q37" else "URL"
        got = list(zip(strs(col), ints("pv")))
        assert got == want, (name, got[:3], want[:3])
    elif name == "q39":
        got = [((ts, se, ad, s, d), c) for ts, se, ad, s, d, c in zip(
            ints("TraficSourceID"), ints("SearchEngineID"),
            ints("AdvEngineID"), strs("src"), strs("dst"),
            ints("pv"))]
        assert got == want, (name, got[:2], want[:2])
    elif name == "q40":
        got = [((u, d), c) for u, d, c in zip(
            ints("URLHash"), ints("EventDate"), ints("pv"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q41":
        got = [((w_, h_), c) for w_, h_, c in zip(
            ints("WindowClientWidth"), ints("WindowClientHeight"),
            ints("pv"))]
        assert got == want, (name, got[:3], want[:3])
    elif name == "q42":
        got = list(zip(ints("minute"), ints("pv")))
        assert got == want, (name, got[:3], want[:3])
    else:
        raise KeyError(name)
