"""TPC-DS workload: deterministic generator + query set + canonical
answers (BASELINE config 5; reference ships the dsdgen-compatible
generator and queries under ydb/library/workload/tpcds/ and
ydb/library/benchmarks/queries/tpcds/, run via `ydb workload tpcds` —
ydb_cli/commands/ydb_benchmark.cpp).

The schema is the subset of TPC-DS's 24 tables that the implemented
queries touch: the store_sales / catalog_sales / web_sales / inventory
fact tables plus the date_dim, item, store, time_dim, promotion,
customer, customer_address, customer_demographics,
household_demographics, warehouse, ship_mode and call_center
dimensions, with dsdgen's column domains (julian-numbered date
surrogate keys, brand/manufact naming, syllable store names,
gender x marital x education demographics cross product). Money
columns are decimal(2) scaled int64 like the TPC-H generator.

Queries follow 70 official templates (q1, q2, q3, q4, q6, q7, q9,
q10, q11, q12, q13, q15, q16, q17, q18, q19, q20, q21, q22, q25, q26,
q27, q29, q30, q31, q32, q33, q34, q35, q36, q37, q38, q39, q40, q42,
q43, q44, q45, q46, q48, q50, q52, q53, q55, q56, q60, q61, q62, q63,
q65, q67, q68, q69, q70, q71, q73, q74, q79, q81, q82, q86, q88, q89,
q91, q92, q93, q94, q96, q98, q99). q10/q35 run EXISTS plus an OR of
EXISTS (counting decorrelation). q44/q67/q70 run REAL ranking window functions
(rank / row_number over partitions). q17/q39
exercise the stddev_samp aggregate; ROLLUPs (q18/q27) restate flat at
their finest grouping; q9 picks buckets by CASE over scalar
subqueries; q74/q11/q4 restate the official UNION ALL year_total CTE
as one CTE per channel; q38's INTERSECT restates as a 1:1 join of
distinct triples; q89 restates AVG() OVER as a per-partition average
CTE; q2 ratios each week against the same week a year later. The
channel-union family (q33/q56/q60/q71) runs through real UNION ALL
planning; the returns chains (q1/q25/q29/q30/q40/q50/q81/q91/q93) join
the store/catalog/web returns tables; q16/q94 run EXISTS with a <>
correlation plus NOT EXISTS, with COUNT(DISTINCT order) restated
exactly as a per-order derived aggregate; q61/q88 restate the official
cross-joins of single-row derived tables exactly as CASE-filtered sums
in one pass.
All are restated in the framework
dialect: q13/q48 hoist the join
equalities shared by every OR branch (an exact identity); q34/q73
rewrite the dep/vehicle ratio as a multiply (exact under the
vehicle > 0 guard); q98 restates the window partition sum as a
class-total self-join; q65's month window adapts to our date epoch;
tie-prone ORDER BYs gain deterministic tiebreakers. Each is verified
against ``reference_answers`` — an independent numpy implementation
computed straight off the generated tables (the canondata pattern,
ydb/tests/functional/tpc).
"""

from __future__ import annotations

import collections

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet

DEC2 = dtypes.decimal(2)

# dsdgen numbers date_dim surrogate keys as julian day numbers;
# 2415022 == 1900-01-01.  Our slice covers 1998-01-01..2002-12-31.
_D0_SK = 2450815
_D0 = np.datetime64("1998-01-01", "D")
_N_DATES = int((np.datetime64("2003-01-01", "D") - _D0).astype(int))

_DAY_NAMES = [b"Monday", b"Tuesday", b"Wednesday", b"Thursday",
              b"Friday", b"Saturday", b"Sunday"]
_CATEGORIES = [b"Books", b"Children", b"Electronics", b"Home",
               b"Jewelry", b"Men", b"Music", b"Shoes", b"Sports",
               b"Women"]
# dsdgen store names are spelled-out digit syllables
_STORE_NAMES = [b"ought", b"able", b"pri", b"ese", b"anti",
                b"cally", b"ation", b"eing", b"bar"]
_GENDERS = [b"M", b"F"]
# pools cover the spec queries' literal constants (q34/q46/q68/q73/q79
# counties and cities) so they always select rows at synthetic scale
_CITIES = [b"Five Forks", b"Oakland", b"Fairview", b"Winchester",
           b"Farmington", b"Pleasant Hill", b"Bethel", b"Midway",
           b"Union", b"Salem"]
_COUNTIES = [b"Salem County", b"Terrell County", b"Arthur County",
             b"Oglethorpe County", b"Lunenburg County", b"Perry County",
             b"Halifax County", b"Sumner County", b"Lea County",
             b"Furnas County", b"Pennington County", b"Bronx County",
             b"Mobile County", b"Ziebach County"]
_BUY_POTENTIAL = [b"0-500", b"501-1000", b"1001-5000", b"5001-10000",
                  b">10000", b"Unknown"]
_FIRST_NAMES = [b"James", b"Mary", b"John", b"Linda", b"Robert",
                b"Susan", b"Michael", b"Karen", b"William", b"Nancy",
                b"David", b"Lisa", b"Richard", b"Betty", b"Joseph"]
_LAST_NAMES = [b"Smith", b"Johnson", b"Williams", b"Brown", b"Jones",
               b"Garcia", b"Miller", b"Davis", b"Wilson", b"Moore",
               b"Taylor", b"Anderson", b"Thomas", b"Jackson", b"White"]
_SALUTATIONS = [b"Mr.", b"Mrs.", b"Ms.", b"Dr.", b"Miss", b"Sir"]
_CREDIT_RATINGS = [b"Low Risk", b"Good", b"High Risk", b"Unknown"]
_SHIP_TYPES = [b"EXPRESS", b"OVERNIGHT", b"REGULAR", b"TWO DAY",
               b"LIBRARY"]
_CC_NAMES = [b"NY Metro", b"Mid Atlantic", b"North Midwest",
             b"Pacific Northwest", b"Central", b"California"]
_MARITAL = [b"M", b"S", b"D", b"W", b"U"]
# dsdgen color domain subset covering the q56/q60 literal constants
_COLORS = [b"slate", b"blanched", b"cornsilk", b"chiffon", b"lace",
           b"lawn", b"orchid", b"salmon", b"powder", b"peru",
           b"sienna", b"drab", b"grey", b"rosy", b"metallic", b"navy"]
_REASONS = [b"Package was damaged", b"Stopped working",
            b"Did not fit", b"Found a better price", b"Not the product",
            b"Gift exchange", b"Duplicate purchase", b"Parts missing",
            b"Did not like the color", b"Did not like the model",
            b"Unauthorized purchase", b"Lost my job",
            b"reason 13", b"reason 14", b"reason 15"]
_EDUCATION = [b"Primary", b"Secondary", b"College", b"2 yr Degree",
              b"4 yr Degree", b"Advanced Degree", b"Unknown"]

DATE_DIM_SCHEMA = dtypes.schema(
    ("d_date_sk", dtypes.INT64, False),
    ("d_date", dtypes.DATE, False),
    ("d_year", dtypes.INT32, False),
    ("d_moy", dtypes.INT32, False),
    ("d_dom", dtypes.INT32, False),
    ("d_month_seq", dtypes.INT32, False),
    ("d_day_name", dtypes.STRING, False),
    ("d_dow", dtypes.INT32, False),
    ("d_qoy", dtypes.INT32, False),
    ("d_week_seq", dtypes.INT32, False),
)

ITEM_SCHEMA = dtypes.schema(
    ("i_item_sk", dtypes.INT64, False),
    ("i_item_id", dtypes.STRING, False),
    ("i_brand_id", dtypes.INT32, False),
    ("i_brand", dtypes.STRING, False),
    ("i_category_id", dtypes.INT32, False),
    ("i_category", dtypes.STRING, False),
    ("i_manufact_id", dtypes.INT32, False),
    ("i_manufact", dtypes.STRING, False),
    ("i_manager_id", dtypes.INT32, False),
    ("i_current_price", DEC2, False),
    ("i_class_id", dtypes.INT32, False),
    ("i_class", dtypes.STRING, False),
    ("i_item_desc", dtypes.STRING, False),
    ("i_wholesale_cost", DEC2, False),
    ("i_color", dtypes.STRING, False),
)

STORE_SCHEMA = dtypes.schema(
    ("s_store_sk", dtypes.INT64, False),
    ("s_store_id", dtypes.STRING, False),
    ("s_store_name", dtypes.STRING, False),
    ("s_gmt_offset", dtypes.INT32, False),
    ("s_zip", dtypes.STRING, False),
    ("s_city", dtypes.STRING, False),
    ("s_county", dtypes.STRING, False),
    ("s_number_employees", dtypes.INT32, False),
    ("s_state", dtypes.STRING, False),
)

TIME_DIM_SCHEMA = dtypes.schema(
    ("t_time_sk", dtypes.INT64, False),
    ("t_hour", dtypes.INT32, False),
    ("t_minute", dtypes.INT32, False),
    ("t_meal_time", dtypes.STRING, False),
)

PROMOTION_SCHEMA = dtypes.schema(
    ("p_promo_sk", dtypes.INT64, False),
    ("p_channel_email", dtypes.STRING, False),
    ("p_channel_event", dtypes.STRING, False),
    ("p_channel_dmail", dtypes.STRING, False),
    ("p_channel_tv", dtypes.STRING, False),
)

CUSTOMER_SCHEMA = dtypes.schema(
    ("c_customer_sk", dtypes.INT64, False),
    ("c_current_addr_sk", dtypes.INT64, False),
    ("c_first_name", dtypes.STRING, False),
    ("c_last_name", dtypes.STRING, False),
    ("c_salutation", dtypes.STRING, False),
    ("c_preferred_cust_flag", dtypes.STRING, False),
    ("c_current_cdemo_sk", dtypes.INT64, False),
    ("c_customer_id", dtypes.STRING, False),
    ("c_current_hdemo_sk", dtypes.INT64, False),
    ("c_birth_month", dtypes.INT32, False),
    ("c_birth_year", dtypes.INT32, False),
)

CUSTOMER_ADDRESS_SCHEMA = dtypes.schema(
    ("ca_address_sk", dtypes.INT64, False),
    ("ca_zip", dtypes.STRING, False),
    ("ca_state", dtypes.STRING, False),
    ("ca_country", dtypes.STRING, False),
    ("ca_city", dtypes.STRING, False),
    ("ca_county", dtypes.STRING, False),
    ("ca_gmt_offset", dtypes.INT32, False),
)

CUSTOMER_DEMOGRAPHICS_SCHEMA = dtypes.schema(
    ("cd_demo_sk", dtypes.INT64, False),
    ("cd_gender", dtypes.STRING, False),
    ("cd_marital_status", dtypes.STRING, False),
    ("cd_education_status", dtypes.STRING, False),
    ("cd_purchase_estimate", dtypes.INT32, False),
    ("cd_credit_rating", dtypes.STRING, False),
    ("cd_dep_count", dtypes.INT32, False),
)

HOUSEHOLD_DEMOGRAPHICS_SCHEMA = dtypes.schema(
    ("hd_demo_sk", dtypes.INT64, False),
    ("hd_dep_count", dtypes.INT32, False),
    ("hd_buy_potential", dtypes.STRING, False),
    ("hd_vehicle_count", dtypes.INT32, False),
)

STORE_SALES_SCHEMA = dtypes.schema(
    ("ss_sold_date_sk", dtypes.INT64, False),
    ("ss_sold_time_sk", dtypes.INT64, False),
    ("ss_item_sk", dtypes.INT64, False),
    ("ss_customer_sk", dtypes.INT64, False),
    ("ss_cdemo_sk", dtypes.INT64, False),
    ("ss_hdemo_sk", dtypes.INT64, False),
    ("ss_store_sk", dtypes.INT64, False),
    ("ss_promo_sk", dtypes.INT64, False),
    ("ss_addr_sk", dtypes.INT64, False),
    ("ss_quantity", dtypes.INT32, False),
    ("ss_list_price", DEC2, False),
    ("ss_sales_price", DEC2, False),
    ("ss_ext_sales_price", DEC2, False),
    ("ss_ext_wholesale_cost", DEC2, False),
    ("ss_coupon_amt", DEC2, False),
    ("ss_net_profit", DEC2, False),
    ("ss_ticket_number", dtypes.INT64, False),
    ("ss_ext_list_price", DEC2, False),
    ("ss_ext_tax", DEC2, False),
    ("ss_ext_discount_amt", DEC2, False),
    ("ss_net_paid", DEC2, False),
)

WEB_SALES_SCHEMA = dtypes.schema(
    ("ws_sold_date_sk", dtypes.INT64, False),
    ("ws_item_sk", dtypes.INT64, False),
    ("ws_bill_customer_sk", dtypes.INT64, False),
    ("ws_quantity", dtypes.INT32, False),
    ("ws_sales_price", DEC2, False),
    ("ws_ext_sales_price", DEC2, False),
    ("ws_ext_discount_amt", DEC2, False),
    ("ws_bill_addr_sk", dtypes.INT64, False),
    ("ws_sold_time_sk", dtypes.INT64, False),
    ("ws_net_profit", DEC2, False),
    ("ws_order_number", dtypes.INT64, False),
    ("ws_warehouse_sk", dtypes.INT64, False),
    ("ws_ship_mode_sk", dtypes.INT64, False),
    ("ws_web_site_sk", dtypes.INT64, False),
    ("ws_ship_addr_sk", dtypes.INT64, False),
    ("ws_ext_ship_cost", DEC2, False),
    ("ws_ship_date_sk", dtypes.INT64, False),
    ("ws_net_paid", DEC2, False),
    ("ws_ext_list_price", DEC2, False),
    ("ws_ext_wholesale_cost", DEC2, False),
)

INVENTORY_SCHEMA = dtypes.schema(
    ("inv_date_sk", dtypes.INT64, False),
    ("inv_item_sk", dtypes.INT64, False),
    ("inv_warehouse_sk", dtypes.INT64, False),
    ("inv_quantity_on_hand", dtypes.INT32, False),
)

WAREHOUSE_SCHEMA = dtypes.schema(
    ("w_warehouse_sk", dtypes.INT64, False),
    ("w_warehouse_name", dtypes.STRING, False),
    ("w_state", dtypes.STRING, False),
)

SHIP_MODE_SCHEMA = dtypes.schema(
    ("sm_ship_mode_sk", dtypes.INT64, False),
    ("sm_type", dtypes.STRING, False),
)

CALL_CENTER_SCHEMA = dtypes.schema(
    ("cc_call_center_sk", dtypes.INT64, False),
    ("cc_name", dtypes.STRING, False),
    ("cc_county", dtypes.STRING, False),
)

CATALOG_SALES_SCHEMA = dtypes.schema(
    ("cs_sold_date_sk", dtypes.INT64, False),
    ("cs_item_sk", dtypes.INT64, False),
    ("cs_bill_cdemo_sk", dtypes.INT64, False),
    ("cs_promo_sk", dtypes.INT64, False),
    ("cs_quantity", dtypes.INT32, False),
    ("cs_list_price", DEC2, False),
    ("cs_sales_price", DEC2, False),
    ("cs_ext_sales_price", DEC2, False),
    ("cs_coupon_amt", DEC2, False),
    ("cs_bill_customer_sk", dtypes.INT64, False),
    ("cs_ext_discount_amt", DEC2, False),
    ("cs_ship_date_sk", dtypes.INT64, False),
    ("cs_warehouse_sk", dtypes.INT64, False),
    ("cs_ship_mode_sk", dtypes.INT64, False),
    ("cs_call_center_sk", dtypes.INT64, False),
    ("cs_bill_addr_sk", dtypes.INT64, False),
    ("cs_ship_addr_sk", dtypes.INT64, False),
    ("cs_sold_time_sk", dtypes.INT64, False),
    ("cs_order_number", dtypes.INT64, False),
    ("cs_net_profit", DEC2, False),
    ("cs_ext_ship_cost", DEC2, False),
    ("cs_ext_list_price", DEC2, False),
    ("cs_ext_wholesale_cost", DEC2, False),
)
REASON_SCHEMA = dtypes.schema(
    ("r_reason_sk", dtypes.INT64, False),
    ("r_reason_desc", dtypes.STRING, False),
)
STORE_RETURNS_SCHEMA = dtypes.schema(
    ("sr_returned_date_sk", dtypes.INT64, False),
    ("sr_item_sk", dtypes.INT64, False),
    ("sr_customer_sk", dtypes.INT64, False),
    ("sr_ticket_number", dtypes.INT64, False),
    ("sr_store_sk", dtypes.INT64, False),
    ("sr_reason_sk", dtypes.INT64, False),
    ("sr_return_quantity", dtypes.INT32, False),
    ("sr_return_amt", DEC2, False),
    ("sr_net_loss", DEC2, False),
)
WEB_SITE_SCHEMA = dtypes.schema(
    ("web_site_sk", dtypes.INT64, False),
    ("web_name", dtypes.STRING, False),
    ("web_company_name", dtypes.STRING, False),
)
WEB_RETURNS_SCHEMA = dtypes.schema(
    ("wr_returned_date_sk", dtypes.INT64, False),
    ("wr_item_sk", dtypes.INT64, False),
    ("wr_order_number", dtypes.INT64, False),
    ("wr_returning_customer_sk", dtypes.INT64, False),
    ("wr_returning_addr_sk", dtypes.INT64, False),
    ("wr_return_quantity", dtypes.INT32, False),
    ("wr_return_amt", DEC2, False),
    ("wr_net_loss", DEC2, False),
)
CATALOG_RETURNS_SCHEMA = dtypes.schema(
    ("cr_returned_date_sk", dtypes.INT64, False),
    ("cr_item_sk", dtypes.INT64, False),
    ("cr_order_number", dtypes.INT64, False),
    ("cr_returning_customer_sk", dtypes.INT64, False),
    ("cr_returning_addr_sk", dtypes.INT64, False),
    ("cr_call_center_sk", dtypes.INT64, False),
    ("cr_return_quantity", dtypes.INT32, False),
    ("cr_return_amount", DEC2, False),
    ("cr_refunded_cash", DEC2, False),
    ("cr_net_loss", DEC2, False),
)

SCHEMAS = {
    "date_dim": DATE_DIM_SCHEMA,
    "item": ITEM_SCHEMA,
    "store": STORE_SCHEMA,
    "time_dim": TIME_DIM_SCHEMA,
    "promotion": PROMOTION_SCHEMA,
    "customer": CUSTOMER_SCHEMA,
    "customer_address": CUSTOMER_ADDRESS_SCHEMA,
    "customer_demographics": CUSTOMER_DEMOGRAPHICS_SCHEMA,
    "household_demographics": HOUSEHOLD_DEMOGRAPHICS_SCHEMA,
    "store_sales": STORE_SALES_SCHEMA,
    "catalog_sales": CATALOG_SALES_SCHEMA,
    "web_sales": WEB_SALES_SCHEMA,
    "inventory": INVENTORY_SCHEMA,
    "warehouse": WAREHOUSE_SCHEMA,
    "ship_mode": SHIP_MODE_SCHEMA,
    "call_center": CALL_CENTER_SCHEMA,
    "reason": REASON_SCHEMA,
    "store_returns": STORE_RETURNS_SCHEMA,
    "catalog_returns": CATALOG_RETURNS_SCHEMA,
    "web_site": WEB_SITE_SCHEMA,
    "web_returns": WEB_RETURNS_SCHEMA,
}

PRIMARY_KEYS = {
    "date_dim": ("d_date_sk",),
    "item": ("i_item_sk",),
    "store": ("s_store_sk",),
    "time_dim": ("t_time_sk",),
    "promotion": ("p_promo_sk",),
    "customer": ("c_customer_sk",),
    "customer_address": ("ca_address_sk",),
    "customer_demographics": ("cd_demo_sk",),
    "household_demographics": ("hd_demo_sk",),
    "store_sales": ("ss_item_sk", "ss_sold_date_sk", "ss_sold_time_sk"),
    "catalog_sales": ("cs_item_sk", "cs_sold_date_sk"),
    "web_sales": ("ws_item_sk", "ws_sold_date_sk"),
    "inventory": ("inv_date_sk", "inv_item_sk", "inv_warehouse_sk"),
    "warehouse": ("w_warehouse_sk",),
    "ship_mode": ("sm_ship_mode_sk",),
    "call_center": ("cc_call_center_sk",),
    "reason": ("r_reason_sk",),
    "store_returns": ("sr_item_sk", "sr_ticket_number"),
    "catalog_returns": ("cr_item_sk", "cr_order_number"),
    "web_site": ("web_site_sk",),
    "web_returns": ("wr_item_sk", "wr_order_number"),
}


def _enc(dicts: DictionarySet, col: str, values: list[bytes]) -> np.ndarray:
    d = dicts.for_column(col)
    return np.array([d.add(v) for v in values], dtype=np.int32)


def _cents(rng, lo: float, hi: float, n: int) -> np.ndarray:
    return rng.integers(round(lo * 100), round(hi * 100), n,
                        dtype=np.int64)


class TpcdsData:
    """Generated TPC-DS table subset + shared dictionaries.

    Row counts scale with ``sf`` following dsdgen's SF-1 cardinalities
    (store_sales 2 880 404, catalog_sales 1 441 548, item 18 000,
    customer 100 000, ...), floored so tiny test scale factors still
    produce joinable data.
    """

    def __init__(self, sf: float = 0.01, seed: int = 42):
        rng = np.random.default_rng(seed)
        self.dicts = DictionarySet()
        self.tables: dict[str, dict[str, np.ndarray]] = {}
        # floors keep dsdgen's fixed attribute domains (1000 manufact
        # ids, 100 manager ids, ...) populated at tiny test scales so
        # the spec queries' literal constants still select rows
        self._gen_date_dim()
        self._gen_item(rng, max(2000, int(sf * 18_000)))
        self._gen_store(rng, max(14, int(sf * 12)))
        self._gen_time_dim()
        self._gen_promotion(rng, max(20, int(sf * 300)))
        self._gen_demographics()
        self._gen_customer(rng, max(2000, int(sf * 100_000)),
                           max(400, int(sf * 50_000)))
        self._gen_warehouses(rng)
        self._gen_reason()
        self._gen_store_sales(rng, max(50_000, int(sf * 2_880_404)))
        # returns generate BEFORE catalog_sales: a slice of catalog
        # orders re-buys returned items (the q25/q29 cross-channel
        # chain needs store-return -> catalog-purchase correlation)
        self._gen_store_returns(rng)
        self._gen_catalog_sales(rng, max(25_000, int(sf * 1_441_548)))
        self._gen_web_sales(rng, max(15_000, int(sf * 719_384)))
        self._gen_catalog_returns(rng)
        self._gen_web_returns(rng)
        self._gen_inventory(rng, max(260_000, int(sf * 11_745_000)))

    def _gen_date_dim(self):
        days = _D0 + np.arange(_N_DATES)
        ymd = days.astype("datetime64[D]")
        y = ymd.astype("datetime64[Y]")
        m = ymd.astype("datetime64[M]")
        self.tables["date_dim"] = {
            "d_date_sk": (_D0_SK + np.arange(_N_DATES)).astype(np.int64),
            "d_date": days.astype(np.int32),
            "d_year": (y.astype(int) + 1970).astype(np.int32),
            "d_moy": ((m - y).astype(int) + 1).astype(np.int32),
            "d_dom": ((ymd - m).astype(int) + 1).astype(np.int32),
            # months since 1998-01 (a consistent absolute month index)
            "d_month_seq": (m.astype(int)
                            - np.datetime64("1998-01", "M")
                            .astype(int)).astype(np.int32),
            "d_day_name": _enc(
                self.dicts, "d_day_name",
                [_DAY_NAMES[d] for d in
                 ((days.astype(int) + 3) % 7).tolist()]),
            # 0 = Sunday (the spec's convention: d_dow in (6,0) means
            # Saturday+Sunday)
            "d_dow": (((days.astype(int) + 3) % 7 + 1) % 7)
            .astype(np.int32),
            "d_qoy": (((m - y).astype(int)) // 3 + 1).astype(np.int32),
            # absolute week index (Monday-anchored weeks since epoch;
            # q2 joins consecutive years via d_week_seq - 53)
            "d_week_seq": ((days.astype(int) + 3) // 7).astype(np.int32),
        }

    def _gen_item(self, rng, n: int):
        # cyclic-then-shuffled assignment keeps dsdgen's fixed domains
        # (1000 manufacturers, 100 managers) uniformly covered even at
        # small n, so spec query constants always select some items
        manufact_id = rng.permutation(
            (np.arange(n) % 1000 + 1)).astype(np.int32)
        brand_in_manu = rng.integers(1, 11, n).astype(np.int32)
        brand_id = manufact_id * 10 + brand_in_manu
        cat_id = rng.integers(1, len(_CATEGORIES) + 1, n).astype(np.int32)
        self.tables["item"] = {
            "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
            "i_item_id": _enc(
                self.dicts, "i_item_id",
                [b"AAAAAAAA%08dCA" % i for i in range(1, n + 1)]),
            "i_brand_id": brand_id,
            "i_brand": _enc(
                self.dicts, "i_brand",
                [b"Brand#%d" % b for b in brand_id.tolist()]),
            "i_category_id": cat_id,
            "i_category": _enc(
                self.dicts, "i_category",
                [_CATEGORIES[c - 1] for c in cat_id.tolist()]),
            "i_manufact_id": manufact_id,
            "i_manufact": _enc(
                self.dicts, "i_manufact",
                [b"manufact#%d" % m for m in manufact_id.tolist()]),
            "i_manager_id": rng.permutation(
                (np.arange(n) % 100 + 1)).astype(np.int32),
            # dsdgen prices skew low: a fifth of items cluster under
            # $2 (q21's 0.99-1.49 band must select items at any scale)
            "i_current_price": np.where(
                rng.random(n) < 0.2, _cents(rng, 0.50, 2.00, n),
                _cents(rng, 2.00, 100.00, n)).astype(np.int64),
            "i_class_id": (class_id := rng.integers(
                1, 17, n).astype(np.int32)),
            "i_class": _enc(self.dicts, "i_class",
                            [b"class#%02d" % c
                             for c in class_id.tolist()]),
            "i_item_desc": _enc(
                self.dicts, "i_item_desc",
                [b"desc of item %d" % i
                 for i in range(1, n + 1)]),
            "i_wholesale_cost": _cents(rng, 0.30, 80.00, n),
            "i_color": _enc(
                self.dicts, "i_color",
                [_COLORS[c] for c in
                 rng.integers(0, len(_COLORS), n).tolist()]),
        }

    def _gen_store(self, rng, n: int):
        names = [_STORE_NAMES[i % len(_STORE_NAMES)] for i in range(n)]
        zips = [b"%05d" % z for z in
                rng.integers(10000, 99999, n).tolist()]
        self.tables["store"] = {
            "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
            "s_store_id": _enc(
                self.dicts, "s_store_id",
                [b"AAAAAAAA%08dCA" % i for i in range(1, n + 1)]),
            "s_store_name": _enc(self.dicts, "s_store_name", names),
            "s_gmt_offset": np.where(
                rng.random(n) < 0.8, -5, -6).astype(np.int32),
            "s_zip": _enc(self.dicts, "s_zip", zips),
            "s_city": _enc(self.dicts, "s_city",
                           [_CITIES[i % len(_CITIES)]
                            for i in range(n)]),
            "s_county": _enc(self.dicts, "s_county",
                             [_COUNTIES[i % len(_COUNTIES)]
                              for i in range(n)]),
            "s_number_employees": rng.integers(
                180, 310, n).astype(np.int32),
            # TN dominates (dsdgen's single-state default; the q1
            # literal)
            "s_state": _enc(
                self.dicts, "s_state",
                [b"TN" if f else b"SD"
                 for f in rng.random(n) < 0.8]),
        }

    def _gen_time_dim(self):
        sk = np.arange(86_400, dtype=np.int64)
        hour = (sk // 3600).astype(np.int32)
        # dsdgen meal times: breakfast 6-9, lunch 11-13, dinner 17-21,
        # empty otherwise (the spec's NULL; queries test equality only)
        meal = np.select(
            [(hour >= 6) & (hour < 9), (hour >= 11) & (hour < 13),
             (hour >= 17) & (hour < 21)],
            [0, 1, 2], default=3)
        meal_names = [b"breakfast", b"lunch", b"dinner", b""]
        self.tables["time_dim"] = {
            "t_time_sk": sk,
            "t_hour": hour,
            "t_minute": ((sk % 3600) // 60).astype(np.int32),
            "t_meal_time": _enc(
                self.dicts, "t_meal_time",
                [meal_names[m] for m in meal.tolist()]),
        }

    def _gen_promotion(self, rng, n: int):
        yn = [b"N", b"Y"]
        self.tables["promotion"] = {
            "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
            "p_channel_email": _enc(
                self.dicts, "p_channel_email",
                [yn[v] for v in (rng.random(n) < 0.1).astype(int)]),
            "p_channel_event": _enc(
                self.dicts, "p_channel_event",
                [yn[v] for v in (rng.random(n) < 0.1).astype(int)]),
            "p_channel_dmail": _enc(
                self.dicts, "p_channel_dmail",
                [yn[v] for v in (rng.random(n) < 0.3).astype(int)]),
            "p_channel_tv": _enc(
                self.dicts, "p_channel_tv",
                [yn[v] for v in (rng.random(n) < 0.3).astype(int)]),
        }

    def _gen_demographics(self):
        combos = [(g, m, e) for g in _GENDERS for m in _MARITAL
                  for e in _EDUCATION]
        nc = len(combos)
        self.tables["customer_demographics"] = {
            "cd_demo_sk": np.arange(1, nc + 1, dtype=np.int64),
            "cd_gender": _enc(self.dicts, "cd_gender",
                              [c[0] for c in combos]),
            "cd_marital_status": _enc(self.dicts, "cd_marital_status",
                                      [c[1] for c in combos]),
            "cd_education_status": _enc(self.dicts, "cd_education_status",
                                        [c[2] for c in combos]),
            "cd_purchase_estimate": ((np.arange(nc) % 20 + 1) * 500)
            .astype(np.int32),
            "cd_credit_rating": _enc(
                self.dicts, "cd_credit_rating",
                [_CREDIT_RATINGS[i % len(_CREDIT_RATINGS)]
                 for i in range(nc)]),
            "cd_dep_count": (np.arange(nc) % 7).astype(np.int32),
        }
        n_hd = 7200
        self.tables["household_demographics"] = {
            "hd_demo_sk": np.arange(1, n_hd + 1, dtype=np.int64),
            "hd_dep_count": (np.arange(n_hd) % 10).astype(np.int32),
            "hd_buy_potential": _enc(
                self.dicts, "hd_buy_potential",
                [_BUY_POTENTIAL[i % len(_BUY_POTENTIAL)]
                 for i in range(n_hd)]),
            "hd_vehicle_count": ((np.arange(n_hd) // 10) % 5)
            .astype(np.int32),
        }

    _STATES = [b"TX", b"OH", b"OR", b"NM", b"KY", b"VA", b"MS",
               b"CA", b"NY", b"WA", b"GA", b"FL", b"MO", b"MN",
               b"AZ"]

    _SPEC_ZIPS = [b"85669", b"86197", b"88274", b"83405", b"86475",
                  b"85392", b"85460", b"80348", b"81792"]

    def _gen_customer(self, rng, n_cust: int, n_addr: int):
        # every 50th address takes a spec-query zip (q15/q45 prefix
        # lists) so those OR branches select rows at any scale
        zips = [self._SPEC_ZIPS[i // 50 % len(self._SPEC_ZIPS)]
                if i % 50 == 0 else b"%05d" % z
                for i, z in enumerate(
                    rng.integers(10000, 99999, n_addr).tolist())]
        state_pick = rng.integers(0, len(self._STATES), n_addr)
        self.tables["customer_address"] = {
            "ca_address_sk": np.arange(1, n_addr + 1, dtype=np.int64),
            "ca_zip": _enc(self.dicts, "ca_zip", zips),
            "ca_state": _enc(self.dicts, "ca_state",
                             [self._STATES[i] for i in state_pick]),
            "ca_country": _enc(
                self.dicts, "ca_country",
                [b"United States" if us else b"Canada"
                 for us in rng.random(n_addr) < 0.95]),
            "ca_city": _enc(
                self.dicts, "ca_city",
                [_CITIES[i] for i in
                 rng.integers(0, len(_CITIES), n_addr).tolist()]),
            "ca_county": _enc(
                self.dicts, "ca_county",
                [_COUNTIES[i] for i in
                 rng.integers(0, len(_COUNTIES), n_addr).tolist()]),
            # US timezone offsets; -5 dominates (the q33/q60 literal)
            "ca_gmt_offset": np.select(
                [rng.random(n_addr) < 0.4,
                 rng.random(n_addr) < 0.5,
                 rng.random(n_addr) < 0.5],
                [-5, -6, -7], default=-8).astype(np.int32),
        }
        self.tables["customer"] = {
            "c_customer_sk": np.arange(1, n_cust + 1, dtype=np.int64),
            "c_customer_id": _enc(
                self.dicts, "c_customer_id",
                [b"AAAAAAAA%08dCA" % i for i in range(1, n_cust + 1)]),
            "c_current_addr_sk": rng.integers(
                1, n_addr + 1, n_cust, dtype=np.int64),
            "c_first_name": _enc(
                self.dicts, "c_first_name",
                [_FIRST_NAMES[i] for i in rng.integers(
                    0, len(_FIRST_NAMES), n_cust).tolist()]),
            "c_last_name": _enc(
                self.dicts, "c_last_name",
                [_LAST_NAMES[i] for i in rng.integers(
                    0, len(_LAST_NAMES), n_cust).tolist()]),
            "c_salutation": _enc(
                self.dicts, "c_salutation",
                [_SALUTATIONS[i] for i in rng.integers(
                    0, len(_SALUTATIONS), n_cust).tolist()]),
            "c_preferred_cust_flag": _enc(
                self.dicts, "c_preferred_cust_flag",
                [b"Y" if f else b"N"
                 for f in rng.random(n_cust) < 0.5]),
            "c_current_cdemo_sk": rng.integers(
                1, len(_GENDERS) * len(_MARITAL) * len(_EDUCATION) + 1,
                n_cust, dtype=np.int64),
            "c_current_hdemo_sk": rng.integers(
                1, 7201, n_cust, dtype=np.int64),
            "c_birth_month": rng.integers(
                1, 13, n_cust).astype(np.int32),
            "c_birth_year": rng.integers(
                1924, 1993, n_cust).astype(np.int32),
        }

    def _fk(self, rng, table: str, pk: str, n: int) -> np.ndarray:
        return rng.choice(self.tables[table][pk], size=n)

    def _gen_store_sales(self, rng, n: int):
        qty = rng.integers(1, 101, n).astype(np.int32)
        list_price = _cents(rng, 1.00, 200.00, n)
        sales_price = (list_price *
                       rng.integers(20, 101, n) // 100).astype(np.int64)
        # dsdgen groups store_sales rows into TICKETS: one (customer,
        # store, date, time, hdemo, addr) purchase spanning 1..24 line
        # items — the q34/q73 "cnt between" bands need real multi-item
        # tickets, so per-ticket attributes generate first and expand
        n_tickets = max(n // 8, 1)
        # min of two uniforms skews ticket sizes small (dsdgen-like:
        # most baskets are a few lines) so the cnt-between-1-and-5
        # bands (q73) select tickets at every scale
        t_sizes = np.minimum(rng.integers(1, 25, n_tickets),
                             rng.integers(1, 25, n_tickets))
        row_ticket = np.repeat(np.arange(n_tickets), t_sizes)[:n]
        if len(row_ticket) < n:  # top up: tail rows get fresh tickets
            extra = np.arange(n_tickets,
                              n_tickets + n - len(row_ticket))
            row_ticket = np.concatenate([row_ticket, extra])
        nt = int(row_ticket.max()) + 1
        t_date = self._fk(rng, "date_dim", "d_date_sk", nt)
        t_time = rng.integers(0, 86_400, nt, dtype=np.int64)
        t_cust = self._fk(rng, "customer", "c_customer_sk", nt)
        t_cdemo = self._fk(rng, "customer_demographics",
                           "cd_demo_sk", nt)
        t_hdemo = self._fk(rng, "household_demographics",
                           "hd_demo_sk", nt)
        t_store = self._fk(rng, "store", "s_store_sk", nt)
        t_addr = self._fk(rng, "customer_address",
                          "ca_address_sk", nt)
        self.tables["store_sales"] = {
            "ss_sold_date_sk": t_date[row_ticket],
            "ss_sold_time_sk": t_time[row_ticket],
            "ss_item_sk": self._fk(rng, "item", "i_item_sk", n),
            "ss_customer_sk": t_cust[row_ticket],
            "ss_cdemo_sk": t_cdemo[row_ticket],
            "ss_hdemo_sk": t_hdemo[row_ticket],
            "ss_store_sk": t_store[row_ticket],
            "ss_promo_sk": self._fk(rng, "promotion", "p_promo_sk", n),
            "ss_addr_sk": t_addr[row_ticket],
            "ss_ticket_number": (row_ticket + 1).astype(np.int64),
            "ss_quantity": qty,
            "ss_list_price": list_price,
            "ss_sales_price": sales_price,
            "ss_ext_sales_price": sales_price * qty,
            "ss_ext_wholesale_cost": (
                list_price * rng.integers(40, 80, n) // 100
                * qty).astype(np.int64),
            "ss_coupon_amt": np.where(
                rng.random(n) < 0.2, _cents(rng, 0.0, 50.0, n),
                0).astype(np.int64),
            "ss_net_profit": _cents(rng, -100.0, 300.0, n),
            "ss_ext_list_price": list_price * qty,
            "ss_ext_tax": (sales_price * qty *
                           rng.integers(0, 9, n) // 100)
            .astype(np.int64),
            "ss_ext_discount_amt": np.where(
                rng.random(n) < 0.4, _cents(rng, 0.0, 40.0, n),
                0).astype(np.int64),
            "ss_net_paid": sales_price * qty,
        }

    def _gen_catalog_sales(self, rng, n: int):
        qty = rng.integers(1, 101, n).astype(np.int32)
        list_price = _cents(rng, 1.00, 300.00, n)
        sales_price = (list_price *
                       rng.integers(20, 101, n) // 100).astype(np.int64)
        self.tables["catalog_sales"] = {
            "cs_sold_date_sk": self._fk(rng, "date_dim", "d_date_sk", n),
            "cs_item_sk": self._fk(rng, "item", "i_item_sk", n),
            "cs_bill_cdemo_sk": self._fk(
                rng, "customer_demographics", "cd_demo_sk", n),
            "cs_promo_sk": self._fk(rng, "promotion", "p_promo_sk", n),
            "cs_quantity": qty,
            "cs_list_price": list_price,
            "cs_sales_price": sales_price,
            "cs_ext_sales_price": sales_price * qty,
            "cs_coupon_amt": np.where(
                rng.random(n) < 0.2, _cents(rng, 0.0, 60.0, n),
                0).astype(np.int64),
            "cs_bill_customer_sk": self._fk(
                rng, "customer", "c_customer_sk", n),
            "cs_bill_addr_sk": self._fk(
                rng, "customer_address", "ca_address_sk", n),
            "cs_ship_addr_sk": self._fk(
                rng, "customer_address", "ca_address_sk", n),
            "cs_sold_time_sk": rng.integers(0, 86_400, n,
                                            dtype=np.int64),
            # two lines per order: the q16 EXISTS (same order shipped
            # from a DIFFERENT warehouse) needs multi-line orders
            "cs_order_number": (np.arange(n, dtype=np.int64) // 2 + 1),
            "cs_net_profit": _cents(rng, -100.0, 300.0, n),
            "cs_ext_ship_cost": _cents(rng, 0.50, 90.0, n),
            "cs_ext_list_price": list_price * qty,
            "cs_ext_wholesale_cost": (
                list_price * rng.integers(40, 80, n) // 100
                * qty).astype(np.int64),
            "cs_ext_discount_amt": np.where(
                rng.random(n) < 0.5, _cents(rng, 0.0, 80.0, n),
                0).astype(np.int64),
            "cs_warehouse_sk": self._fk(
                rng, "warehouse", "w_warehouse_sk", n),
            "cs_ship_mode_sk": self._fk(
                rng, "ship_mode", "sm_ship_mode_sk", n),
            "cs_call_center_sk": self._fk(
                rng, "call_center", "cc_call_center_sk", n),
        }
        # cross-channel correlation: ~5% of catalog orders are a
        # customer re-buying an item they returned in a store (the
        # q25/q29 store->return->catalog chain), sold 1..30 days after
        # the return
        cs = self.tables["catalog_sales"]
        max_sk = int(self.tables["date_dim"]["d_date_sk"].max())
        sr = self.tables.get("store_returns")
        if sr is not None and len(sr["sr_item_sk"]):
            n_inj = min(len(sr["sr_item_sk"]), n // 20)
            src = rng.choice(len(sr["sr_item_sk"]), n_inj,
                             replace=False)
            dst = rng.choice(n, n_inj, replace=False)
            cs["cs_bill_customer_sk"][dst] = sr["sr_customer_sk"][src]
            cs["cs_item_sk"][dst] = sr["sr_item_sk"][src]
            cs["cs_sold_date_sk"][dst] = np.minimum(
                sr["sr_returned_date_sk"][src]
                + rng.integers(1, 31, n_inj), max_sk)
        # shipping: 1..120 days after the sale (q99 buckets), clamped
        # into the date_dim domain
        cs["cs_ship_date_sk"] = np.minimum(
            cs["cs_sold_date_sk"] + rng.integers(1, 151, n), max_sk)

    def _gen_warehouses(self, rng):
        self.tables["warehouse"] = {
            "w_warehouse_sk": np.arange(1, 6, dtype=np.int64),
            "w_warehouse_name": _enc(
                self.dicts, "w_warehouse_name",
                [b"Warehouse number %d distribution" % i
                 for i in range(1, 6)]),
            "w_state": _enc(
                self.dicts, "w_state",
                [b"TN", b"SD", b"TN", b"OH", b"GA"]),
        }
        self.tables["ship_mode"] = {
            "sm_ship_mode_sk": np.arange(1, 21, dtype=np.int64),
            "sm_type": _enc(
                self.dicts, "sm_type",
                [_SHIP_TYPES[i % len(_SHIP_TYPES)] for i in range(20)]),
        }
        self.tables["call_center"] = {
            "cc_call_center_sk": np.arange(1, 7, dtype=np.int64),
            "cc_name": _enc(
                self.dicts, "cc_name",
                [_CC_NAMES[i % len(_CC_NAMES)] for i in range(6)]),
            "cc_county": _enc(
                self.dicts, "cc_county",
                [_COUNTIES[i % len(_COUNTIES)] for i in range(6)]),
        }
        self.tables["web_site"] = {
            "web_site_sk": np.arange(1, 9, dtype=np.int64),
            "web_name": _enc(
                self.dicts, "web_name",
                [b"site_%d" % i for i in range(1, 9)]),
            # dsdgen company names; 'pri' is the q94/q95 literal
            "web_company_name": _enc(
                self.dicts, "web_company_name",
                [_STORE_NAMES[i % len(_STORE_NAMES)]
                 for i in range(8)]),
        }

    def _gen_web_sales(self, rng, n: int):
        qty = rng.integers(1, 101, n).astype(np.int32)
        list_price = _cents(rng, 1.00, 300.00, n)
        sales_price = (list_price *
                       rng.integers(20, 101, n) // 100).astype(np.int64)
        # unique (item, date) pairs back the declared PK
        items = self.tables["item"]["i_item_sk"]
        dates = self.tables["date_dim"]["d_date_sk"]
        cells = rng.choice(len(items) * len(dates), size=n,
                           replace=False)
        self.tables["web_sales"] = {
            "ws_sold_date_sk": dates[cells % len(dates)],
            "ws_item_sk": items[cells // len(dates)],
            "ws_bill_customer_sk": self._fk(
                rng, "customer", "c_customer_sk", n),
            "ws_quantity": qty,
            "ws_sales_price": sales_price,
            "ws_ext_sales_price": sales_price * qty,
            "ws_ext_discount_amt": np.where(
                rng.random(n) < 0.5, _cents(rng, 0.0, 90.0, n),
                0).astype(np.int64),
            "ws_bill_addr_sk": self._fk(
                rng, "customer_address", "ca_address_sk", n),
            "ws_sold_time_sk": rng.integers(0, 86_400, n,
                                            dtype=np.int64),
            "ws_net_profit": _cents(rng, -100.0, 300.0, n),
            # two lines per order (q94's EXISTS wants a sibling line
            # shipped from a different warehouse)
            "ws_order_number": (np.arange(n, dtype=np.int64) // 2 + 1),
            "ws_warehouse_sk": self._fk(
                rng, "warehouse", "w_warehouse_sk", n),
            "ws_ship_mode_sk": self._fk(
                rng, "ship_mode", "sm_ship_mode_sk", n),
            "ws_web_site_sk": self._fk(
                rng, "web_site", "web_site_sk", n),
            "ws_ship_addr_sk": self._fk(
                rng, "customer_address", "ca_address_sk", n),
            "ws_ext_ship_cost": _cents(rng, 0.50, 90.0, n),
            "ws_net_paid": sales_price * qty,
            "ws_ext_list_price": list_price * qty,
            "ws_ext_wholesale_cost": (
                list_price * rng.integers(40, 80, n) // 100
                * qty).astype(np.int64),
        }
        ws = self.tables["web_sales"]
        max_sk = int(self.tables["date_dim"]["d_date_sk"].max())
        ws["ws_ship_date_sk"] = np.minimum(
            ws["ws_sold_date_sk"] + rng.integers(1, 151, n), max_sk)

    def _gen_reason(self):
        self.tables["reason"] = {
            "r_reason_sk": np.arange(1, len(_REASONS) + 1,
                                     dtype=np.int64),
            "r_reason_desc": _enc(self.dicts, "r_reason_desc",
                                  list(_REASONS)),
        }

    def _gen_store_returns(self, rng):
        """~10% of store_sales line items come back 1..60 days later.

        Returns keep the sale's (customer, item, ticket) triple so the
        q25/q29 chain joins and the q50 day-bucketing land on real
        matches; the returned quantity is 1..sold quantity."""
        ss = self.tables["store_sales"]
        n_ss = len(ss["ss_item_sk"])
        pick = np.flatnonzero(rng.random(n_ss) < 0.10)
        # a ticket can hold the same item twice; the returns PK is
        # (item, ticket), so keep one return per pair
        key = (ss["ss_item_sk"][pick] * (1 << 32)
               + ss["ss_ticket_number"][pick])
        pick = pick[np.unique(key, return_index=True)[1]]
        n = len(pick)
        max_sk = int(self.tables["date_dim"]["d_date_sk"].max())
        ret_qty = rng.integers(1, ss["ss_quantity"][pick] + 1)
        ret_amt = (ss["ss_sales_price"][pick] * ret_qty).astype(np.int64)
        self.tables["store_returns"] = {
            "sr_returned_date_sk": np.minimum(
                ss["ss_sold_date_sk"][pick]
                + rng.integers(1, 61, n), max_sk),
            "sr_item_sk": ss["ss_item_sk"][pick],
            "sr_customer_sk": ss["ss_customer_sk"][pick],
            "sr_ticket_number": ss["ss_ticket_number"][pick],
            "sr_store_sk": ss["ss_store_sk"][pick],
            "sr_reason_sk": self._fk(rng, "reason", "r_reason_sk", n),
            "sr_return_quantity": ret_qty.astype(np.int32),
            "sr_return_amt": ret_amt,
            "sr_net_loss": _cents(rng, 0.50, 120.00, n),
        }

    def _gen_catalog_returns(self, rng):
        """~8% of catalog_sales rows return; the (order, item) pair is
        the join identity (each generated order holds one line)."""
        cs = self.tables["catalog_sales"]
        n_cs = len(cs["cs_item_sk"])
        pick = np.flatnonzero(rng.random(n_cs) < 0.08)
        # orders hold two lines that can draw the same item; the
        # returns PK is (item, order), so keep one return per pair
        key = (cs["cs_item_sk"][pick] * (1 << 40)
               + cs["cs_order_number"][pick])
        pick = pick[np.unique(key, return_index=True)[1]]
        n = len(pick)
        max_sk = int(self.tables["date_dim"]["d_date_sk"].max())
        ret_qty = rng.integers(1, cs["cs_quantity"][pick] + 1)
        self.tables["catalog_returns"] = {
            "cr_returned_date_sk": np.minimum(
                cs["cs_sold_date_sk"][pick]
                + rng.integers(1, 61, n), max_sk),
            "cr_item_sk": cs["cs_item_sk"][pick],
            "cr_order_number": cs["cs_order_number"][pick],
            "cr_returning_customer_sk": cs["cs_bill_customer_sk"][pick],
            "cr_returning_addr_sk": cs["cs_bill_addr_sk"][pick],
            "cr_call_center_sk": cs["cs_call_center_sk"][pick],
            "cr_return_quantity": ret_qty.astype(np.int32),
            "cr_return_amount": (cs["cs_sales_price"][pick]
                                 * ret_qty).astype(np.int64),
            "cr_refunded_cash": _cents(rng, 0.50, 150.00, n),
            "cr_net_loss": _cents(rng, 0.50, 120.00, n),
        }

    def _gen_web_returns(self, rng):
        """~8% of web_sales lines return; join identity (item, order)."""
        ws = self.tables["web_sales"]
        n_ws = len(ws["ws_item_sk"])
        pick = np.flatnonzero(rng.random(n_ws) < 0.08)
        key = (ws["ws_item_sk"][pick] * (1 << 40)
               + ws["ws_order_number"][pick])
        pick = pick[np.unique(key, return_index=True)[1]]
        n = len(pick)
        max_sk = int(self.tables["date_dim"]["d_date_sk"].max())
        ret_qty = rng.integers(1, ws["ws_quantity"][pick] + 1)
        self.tables["web_returns"] = {
            "wr_returned_date_sk": np.minimum(
                ws["ws_sold_date_sk"][pick]
                + rng.integers(1, 61, n), max_sk),
            "wr_item_sk": ws["ws_item_sk"][pick],
            "wr_order_number": ws["ws_order_number"][pick],
            "wr_returning_customer_sk":
                ws["ws_bill_customer_sk"][pick],
            "wr_returning_addr_sk": ws["ws_bill_addr_sk"][pick],
            "wr_return_quantity": ret_qty.astype(np.int32),
            "wr_return_amt": (ws["ws_sales_price"][pick]
                              * ret_qty).astype(np.int64),
            "wr_net_loss": _cents(rng, 0.50, 120.00, n),
        }

    def _gen_inventory(self, rng, n: int):
        # weekly snapshots: every 7th date_dim day. Rows are a random
        # sample WITHOUT replacement of the (item, week, warehouse)
        # grid, interleaved over items: the declared PK triple is
        # genuinely unique AND every item keeps inventory coverage at
        # every scale (q37/q82 point bands stay non-vacuous)
        weekly = self.tables["date_dim"]["d_date_sk"][::7]
        items = self.tables["item"]["i_item_sk"]
        wss = self.tables["warehouse"]["w_warehouse_sk"]
        n_cells = len(items) * len(weekly) * len(wss)
        n = min(n, n_cells)
        per_item = len(weekly) * len(wss)
        cell = np.concatenate([
            off + rng.permutation(per_item)[:(
                n // len(items) + (1 if i < n % len(items) else 0))]
            for i, off in enumerate(
                range(0, n_cells, per_item))])[:n]
        self.tables["inventory"] = {
            "inv_date_sk": weekly[(cell % per_item) // len(wss)],
            "inv_item_sk": items[cell // per_item],
            "inv_warehouse_sk": wss[cell % len(wss)],
            "inv_quantity_on_hand": rng.integers(
                0, 1000, n).astype(np.int32),
        }

    def schema(self, table: str) -> dtypes.Schema:
        return SCHEMAS[table]


QUERIES = {
    # q3: brand revenue by year for one manufacturer's November sales
    "q3": """
select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id = 128
  and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, i_brand_id
limit 100""",
    # q6: states whose customers bought items priced 20% above their
    # category average, in one chosen month (uncorrelated DISTINCT
    # subquery for the month + correlated avg-by-category subquery)
    "q6": """
select a.ca_state, count(*) as cnt
from customer_address a, customer c, store_sales s, date_dim d,
     item i
where a.ca_address_sk = c.c_current_addr_sk
  and c.c_customer_sk = s.ss_customer_sk
  and s.ss_sold_date_sk = d.d_date_sk
  and s.ss_item_sk = i.i_item_sk
  and d.d_month_seq = (select distinct d_month_seq from date_dim
                       where d_year = 2001 and d_moy = 1)
  and i.i_current_price > 1.2 * (select avg(j.i_current_price)
                                 from item j
                                 where j.i_category = i.i_category)
group by a.ca_state
having count(*) >= 10
order by cnt, a.ca_state
limit 100""",
    # q7: demographic/promotion item averages
    "q7": """
select i_item_id,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100""",
    # q19: brand revenue where customer and store zip prefixes differ
    "q19": """
select i_brand_id, i_brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11
  and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, i_brand, i_brand_id, i_manufact_id, i_manufact
limit 100""",
    # q13: store-sales averages under OR-combined demographic and
    # address bands (join equalities hoisted out of the OR groups —
    # (E and F1) or (E and F2) == E and (F1 or F2), exactly)
    "q13": """
select avg(ss_quantity) as avg_qty,
       avg(ss_ext_sales_price) as avg_esp,
       avg(ss_ext_wholesale_cost) as avg_ewc,
       sum(ss_ext_wholesale_cost) as sum_ewc
from store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = 2001
  and ss_hdemo_sk = hd_demo_sk
  and cd_demo_sk = ss_cdemo_sk
  and ss_addr_sk = ca_address_sk
  and ((cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
    or (cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 100.00
        and hd_dep_count = 1)
    or (cd_marital_status = 'W'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 150.00 and 200.00
        and hd_dep_count = 1))
  and ((ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'TX')
        and ss_net_profit between 100 and 200)
    or (ca_country = 'United States'
        and ca_state in ('OR', 'NM', 'KY')
        and ss_net_profit between 150 and 300)
    or (ca_country = 'United States'
        and ca_state in ('VA', 'TX', 'MS')
        and ss_net_profit between 50 and 250))""",
    # q26: the catalog_sales twin of q7
    "q26": """
select i_item_id,
       avg(cs_quantity) as agg1,
       avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3,
       avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100""",
    # q48: total quantity under OR-combined demographic/address bands
    # (same hoisting identity as q13)
    "q48": """
select sum(ss_quantity) as total_qty
from store_sales, store, customer_demographics, customer_address,
     date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = 2001
  and cd_demo_sk = ss_cdemo_sk
  and ss_addr_sk = ca_address_sk
  and ((cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
    or (cd_marital_status = 'D'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 50.00 and 100.00)
    or (cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 150.00 and 200.00))
  and ((ca_country = 'United States'
        and ca_state in ('CO', 'OH', 'TX')
        and ss_net_profit between 0 and 2000)
    or (ca_country = 'United States'
        and ca_state in ('OR', 'MN', 'KY')
        and ss_net_profit between 150 and 3000)
    or (ca_country = 'United States'
        and ca_state in ('VA', 'CA', 'MS')
        and ss_net_profit between 50 and 25000))""",
    # q42: category revenue for one manager's items
    "q42": """
select d_year, i_category_id, i_category,
       sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 1
  and d_moy = 11
  and d_year = 2000
group by d_year, i_category_id, i_category
order by sum_agg desc, d_year, i_category_id, i_category
limit 100""",
    # q43: store sales pivoted by day of week
    "q43": """
select s_store_name, s_store_id,
  sum(case when d_day_name = 'Sunday' then ss_sales_price
      else 0.00 end) as sun_sales,
  sum(case when d_day_name = 'Monday' then ss_sales_price
      else 0.00 end) as mon_sales,
  sum(case when d_day_name = 'Tuesday' then ss_sales_price
      else 0.00 end) as tue_sales,
  sum(case when d_day_name = 'Wednesday' then ss_sales_price
      else 0.00 end) as wed_sales,
  sum(case when d_day_name = 'Thursday' then ss_sales_price
      else 0.00 end) as thu_sales,
  sum(case when d_day_name = 'Friday' then ss_sales_price
      else 0.00 end) as fri_sales,
  sum(case when d_day_name = 'Saturday' then ss_sales_price
      else 0.00 end) as sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and ss_store_sk = s_store_sk
  and s_gmt_offset = -5
  and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100""",
    # q52: brand revenue, manager 1, November 2000
    "q52": """
select d_year, i_brand_id, i_brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 1
  and d_moy = 11
  and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, i_brand_id
limit 100""",
    # q55: brand revenue, manager 28
    "q55": """
select i_brand_id, i_brand, sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, i_brand_id
limit 100""",
    # q96: count of evening sales to 7-dependent households at 'ese'
    "q96": """
select count(*) as cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
  and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20
  and t_minute >= 30
  and hd_dep_count = 7
  and s_store_name = 'ese'""",
    # q15: catalog sales by customer zip for Q2/1998 under an OR of
    # zip-prefix / state / price predicates
    "q15": """
select ca_zip, sum(cs_sales_price) as total
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substring(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                   '86475', '85392', '85460', '80348',
                                   '81792')
       or ca_state in ('CA', 'WA', 'GA')
       or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 1998
group by ca_zip
order by ca_zip
limit 100""",
    # q32: excess discount amount vs 1.3x the per-item average in a
    # 90-day window (official derives adi over item; grouping by
    # cs_item_sk is the same partition)
    "q32": """
with adi as (
  select cs_item_sk as adi_item_sk,
         avg(cs_ext_discount_amt) as avg_discount
  from catalog_sales, date_dim
  where d_date between date '2002-03-29' and date '2002-06-27'
    and d_date_sk = cs_sold_date_sk
  group by cs_item_sk)
select sum(cs_ext_discount_amt) as excess
from catalog_sales, item, date_dim, adi
where i_manufact_id = 66
  and i_item_sk = cs_item_sk
  and d_date between date '2002-03-29' and date '2002-06-27'
  and d_date_sk = cs_sold_date_sk
  and cs_item_sk = adi_item_sk
  and cs_ext_discount_amt > 1.3 * avg_discount""",
    # q34: customers with 15-20-item tickets on month edges (the
    # dep/vehicle ratio predicate rewrites as a multiply — exact under
    # the hd_vehicle_count > 0 guard)
    "q34": """
with dn as (
  select ss_ticket_number, ss_customer_sk, count(*) as cnt
  from store_sales, date_dim, store, household_demographics
  where ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and ss_hdemo_sk = hd_demo_sk
    and (d_dom between 1 and 3 or d_dom between 25 and 28)
    and (hd_buy_potential = '>10000' or hd_buy_potential = 'Unknown')
    and hd_vehicle_count > 0
    and hd_dep_count > 1.2 * hd_vehicle_count
    and d_year in (2000, 2001, 2002)
    and s_county in ('Salem County', 'Terrell County', 'Arthur County',
                     'Oglethorpe County', 'Lunenburg County',
                     'Perry County', 'Halifax County', 'Sumner County')
  group by ss_ticket_number, ss_customer_sk)
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from dn, customer
where ss_customer_sk = c_customer_sk
  and cnt between 15 and 20
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number""",
    # q46: weekend coupon/profit per ticket in five cities, for
    # customers whose current city differs from the bought city
    "q46": """
with dn as (
  select ss_ticket_number, ss_customer_sk, ss_addr_sk,
         ca_city as bought_city, sum(ss_coupon_amt) as amt,
         sum(ss_net_profit) as profit
  from store_sales, date_dim, store, household_demographics,
       customer_address
  where ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and ss_hdemo_sk = hd_demo_sk
    and ss_addr_sk = ca_address_sk
    and (hd_dep_count = 0 or hd_vehicle_count = 1)
    and d_dow in (6, 0)
    and d_year in (2000, 2001, 2002)
    and s_city in ('Five Forks', 'Oakland', 'Fairview', 'Winchester',
                   'Farmington')
  group by ss_ticket_number, ss_customer_sk, ss_addr_sk, bought_city)
select c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, amt, profit
from dn, customer, customer_address
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city,
         ss_ticket_number
limit 100""",
    # q65: items whose yearly revenue is under 10% of their store's
    # average per-item revenue (month window adapted to our epoch)
    "q65": """
with sc as (
  select ss_store_sk as sc_store_sk, ss_item_sk as sc_item_sk,
         sum(ss_sales_price) as revenue
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk and d_month_seq between 48 and 59
  group by ss_store_sk, ss_item_sk),
sb as (
  select sc_store_sk as sb_store_sk, avg(revenue) as ave
  from sc
  group by sc_store_sk)
select s_store_name, i_item_desc, revenue, i_current_price,
       i_wholesale_cost, i_brand
from store, item, sb, sc
where sb_store_sk = sc_store_sk
  and revenue <= 0.1 * ave
  and s_store_sk = sc_store_sk
  and i_item_sk = sc_item_sk
order by s_store_name, i_item_desc, revenue, i_current_price,
         i_wholesale_cost, i_brand
limit 100""",
    # q68: month-start sales in two cities, moved-customer filter
    "q68": """
with dn as (
  select ss_ticket_number, ss_customer_sk, ss_addr_sk,
         ca_city as bought_city,
         sum(ss_ext_sales_price) as extended_price,
         sum(ss_ext_list_price) as list_price,
         sum(ss_ext_tax) as extended_tax
  from store_sales, date_dim, store, household_demographics,
       customer_address
  where ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and ss_hdemo_sk = hd_demo_sk
    and ss_addr_sk = ca_address_sk
    and d_dom between 1 and 2
    and (hd_dep_count = 4 or hd_vehicle_count = 0)
    and d_year in (1999, 2000, 2001)
    and s_city in ('Pleasant Hill', 'Bethel')
  group by ss_ticket_number, ss_customer_sk, ss_addr_sk, bought_city)
select c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
from dn, customer, customer_address
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100""",
    # q73: 1-5-item tickets for high-buy-potential households (the
    # dep/vehicle > 1 ratio rewrites as dep > vehicle, exact under the
    # vehicle > 0 guard)
    "q73": """
with dj as (
  select ss_ticket_number, ss_customer_sk, count(*) as cnt
  from store_sales, date_dim, store, household_demographics
  where ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and ss_hdemo_sk = hd_demo_sk
    and d_dom between 1 and 2
    and (hd_buy_potential = '>10000'
         or hd_buy_potential = '5001-10000')
    and hd_vehicle_count > 0
    and hd_dep_count > hd_vehicle_count
    and d_year in (2000, 2001, 2002)
    and s_county in ('Lea County', 'Furnas County',
                     'Pennington County', 'Bronx County')
  group by ss_ticket_number, ss_customer_sk)
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from dj, customer
where ss_customer_sk = c_customer_sk
  and cnt between 1 and 5
order by cnt desc, c_last_name, ss_ticket_number""",
    # q79: Monday coupon/profit per ticket at mid-size stores
    "q79": """
with ms as (
  select ss_ticket_number, ss_customer_sk, s_city,
         sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
  from store_sales, date_dim, store, household_demographics
  where ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and ss_hdemo_sk = hd_demo_sk
    and (hd_dep_count = 0 or hd_vehicle_count > 3)
    and d_dow = 1
    and d_year in (1998, 1999, 2000)
    and s_number_employees between 200 and 295
  group by ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city)
select c_last_name, c_first_name, substring(s_city, 1, 30) as city30,
       ss_ticket_number, amt, profit
from ms, customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city30, profit, ss_ticket_number
limit 100""",
    # q98: item revenue + share of its class (the official window
    # sum over partition restated as a class-total self-join — the
    # same partition sum, exactly)
    "q98": """
with ir as (
  select i_item_id, i_item_desc, i_category, i_class, i_current_price,
         sum(ss_ext_sales_price) as itemrevenue
  from store_sales, item, date_dim
  where ss_item_sk = i_item_sk
    and i_category in ('Home', 'Sports', 'Men')
    and ss_sold_date_sk = d_date_sk
    and d_date between date '2002-01-05' and date '2002-02-04'
  group by i_item_id, i_item_desc, i_category, i_class,
           i_current_price),
cr as (
  select i_class as cr_class, sum(itemrevenue) as classrevenue
  from ir group by i_class)
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue, itemrevenue * 100.0 / classrevenue as revenueratio
from ir, cr
where i_class = cr_class
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100""",
    # q12: web twin of q98 (window partition sum restated as a
    # class-total self-join)
    "q12": """
with ir as (
  select i_item_id, i_item_desc, i_category, i_class, i_current_price,
         sum(ws_ext_sales_price) as itemrevenue
  from web_sales, item, date_dim
  where ws_item_sk = i_item_sk
    and i_category in ('Electronics', 'Books', 'Women')
    and ws_sold_date_sk = d_date_sk
    and d_date between date '1998-01-06' and date '1998-02-05'
  group by i_item_id, i_item_desc, i_category, i_class,
           i_current_price),
cr as (
  select i_class as cr_class, sum(itemrevenue) as classrevenue
  from ir group by i_class)
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue, itemrevenue * 100.0 / classrevenue as revenueratio
from ir, cr
where i_class = cr_class
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100""",
    # q20: catalog twin of q98
    "q20": """
with ir as (
  select i_item_id, i_item_desc, i_category, i_class, i_current_price,
         sum(cs_ext_sales_price) as itemrevenue
  from catalog_sales, item, date_dim
  where cs_item_sk = i_item_sk
    and i_category in ('Shoes', 'Electronics', 'Children')
    and cs_sold_date_sk = d_date_sk
    and d_date between date '2001-03-14' and date '2001-04-13'
  group by i_item_id, i_item_desc, i_category, i_class,
           i_current_price),
cr as (
  select i_class as cr_class, sum(itemrevenue) as classrevenue
  from ir group by i_class)
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue, itemrevenue * 100.0 / classrevenue as revenueratio
from ir, cr
where i_class = cr_class
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100""",
    # q21: warehouse inventory before/after a date (the ratio band
    # 2/3 <= after/before <= 3/2 rewritten as multiplies — exact for
    # before > 0)
    "q21": """
with x as (
  select w_warehouse_name, i_item_id,
         sum(case when d_date < date '1999-03-20'
             then inv_quantity_on_hand else 0 end) as inv_before,
         sum(case when d_date >= date '1999-03-20'
             then inv_quantity_on_hand else 0 end) as inv_after
  from inventory, warehouse, item, date_dim
  where i_current_price between 0.99 and 1.49
    and i_item_sk = inv_item_sk
    and inv_warehouse_sk = w_warehouse_sk
    and inv_date_sk = d_date_sk
    and d_date between date '1999-02-18' and date '1999-04-19'
  group by w_warehouse_name, i_item_id)
select w_warehouse_name, i_item_id, inv_before, inv_after
from x
where inv_before > 0
  and 3 * inv_after >= 2 * inv_before
  and 2 * inv_after <= 3 * inv_before
order by w_warehouse_name, i_item_id
limit 100""",
    # q37: catalog-sold items with 100-500 on hand in a 60-day window
    "q37": """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 39 and 69
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2001-01-16' and date '2001-03-17'
  and i_manufact_id in (765, 886, 889, 728)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100""",
    # q45: web sales by zip/county (the official's item-id IN-subquery
    # over fixed item_sks rewrites to the item_sk set — exact, item
    # ids are unique per sk)
    "q45": """
select ca_zip, ca_county, sum(ws_sales_price) as total
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and (substring(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                   '86475', '85392', '85460', '80348',
                                   '81792')
       or i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29))
  and ws_sold_date_sk = d_date_sk
  and d_qoy = 1 and d_year = 1998
group by ca_zip, ca_county
order by ca_zip, ca_county
limit 100""",
    # q69: demographics of customers active in store but not web or
    # catalog in the window (d_year 2003 adapts to 2001, inside our
    # five-year date domain)
    "q69": """
select cd_gender, cd_marital_status, cd_education_status,
       count(*) as cnt1, cd_purchase_estimate, count(*) as cnt2,
       cd_credit_rating, count(*) as cnt3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_state in ('MO', 'MN', 'AZ')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = 2001 and d_moy between 2 and 4)
  and not exists (select * from web_sales, date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 2 and 4)
  and not exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_bill_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 2 and 4)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100""",
    # q82: store twin of q37
    "q82": """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 49 and 79
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2001-01-28' and date '2001-03-29'
  and i_manufact_id in (80, 675, 292, 17)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100""",
    # q92: web twin of q32 (excess discount vs 1.3x per-item average)
    "q92": """
with adi as (
  select ws_item_sk as adi_item_sk,
         avg(ws_ext_discount_amt) as avg_discount
  from web_sales, date_dim
  where d_date between date '2001-03-12' and date '2001-06-10'
    and d_date_sk = ws_sold_date_sk
  group by ws_item_sk)
select sum(ws_ext_discount_amt) as excess
from web_sales, item, date_dim, adi
where i_manufact_id = 356
  and i_item_sk = ws_item_sk
  and d_date between date '2001-03-12' and date '2001-06-10'
  and d_date_sk = ws_sold_date_sk
  and ws_item_sk = adi_item_sk
  and ws_ext_discount_amt > 1.3 * avg_discount""",
    # q99: catalog shipping-delay buckets by warehouse/mode/center
    # (month window adapted to our epoch)
    "q99": """
select substring(w_warehouse_name, 1, 20) as wname, sm_type, cc_name,
  sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30
      then 1 else 0 end) as d30,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
           and cs_ship_date_sk - cs_sold_date_sk <= 60
      then 1 else 0 end) as d60,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 60
           and cs_ship_date_sk - cs_sold_date_sk <= 90
      then 1 else 0 end) as d90,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 90
           and cs_ship_date_sk - cs_sold_date_sk <= 120
      then 1 else 0 end) as d120,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 120
      then 1 else 0 end) as dmore
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 36 and 47
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by wname, sm_type, cc_name
order by wname, sm_type, cc_name
limit 100""",
    # q33: Electronics revenue by manufacturer across all three sales
    # channels (CTE per channel, UNION ALL, re-aggregate;
    # deterministic i_manufact_id tiebreaker added)
    "q33": """
with ss as (
  select i_manufact_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category = 'Electronics')
    and ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 5
    and ss_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_manufact_id),
cs as (
  select i_manufact_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category = 'Electronics')
    and cs_item_sk = i_item_sk
    and cs_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 5
    and cs_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_manufact_id),
ws as (
  select i_manufact_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category = 'Electronics')
    and ws_item_sk = i_item_sk
    and ws_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 5
    and ws_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_manufact_id)
select i_manufact_id, sum(total_sales) as total_sales
from (select i_manufact_id, total_sales from ss
      union all
      select i_manufact_id, total_sales from cs
      union all
      select i_manufact_id, total_sales from ws) tmp1
group by i_manufact_id
order by total_sales, i_manufact_id
limit 100""",
    # q56: three-channel revenue for items in chosen colors
    "q56": """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_color in ('slate', 'blanched',
                                        'cornsilk'))
    and ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and d_year = 2001 and d_moy = 2
    and ss_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_item_id),
cs as (
  select i_item_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_color in ('slate', 'blanched',
                                        'cornsilk'))
    and cs_item_sk = i_item_sk
    and cs_sold_date_sk = d_date_sk
    and d_year = 2001 and d_moy = 2
    and cs_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_item_id),
ws as (
  select i_item_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_color in ('slate', 'blanched',
                                        'cornsilk'))
    and ws_item_sk = i_item_sk
    and ws_sold_date_sk = d_date_sk
    and d_year = 2001 and d_moy = 2
    and ws_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_item_id)
select i_item_id, sum(total_sales) as total_sales
from (select i_item_id, total_sales from ss
      union all
      select i_item_id, total_sales from cs
      union all
      select i_item_id, total_sales from ws) tmp1
group by i_item_id
order by total_sales, i_item_id
limit 100""",
    # q60: three-channel revenue for the Music category
    "q60": """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) as total_sales
  from store_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_category = 'Music')
    and ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 9
    and ss_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_item_id),
cs as (
  select i_item_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_category = 'Music')
    and cs_item_sk = i_item_sk
    and cs_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 9
    and cs_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_item_id),
ws as (
  select i_item_id, sum(ws_ext_sales_price) as total_sales
  from web_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_category = 'Music')
    and ws_item_sk = i_item_sk
    and ws_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 9
    and ws_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_item_id)
select i_item_id, sum(total_sales) as total_sales
from (select i_item_id, total_sales from ss
      union all
      select i_item_id, total_sales from cs
      union all
      select i_item_id, total_sales from ws) tmp1
group by i_item_id
order by i_item_id, total_sales
limit 100""",
    # q71: brand revenue by meal-time minute across the three channels
    # (deterministic brand/hour/minute tiebreakers added)
    "q71": """
select i_brand_id as brand_id, i_brand as brand,
       t_hour, t_minute, sum(ext_price) as ext_price
from item,
     (select ws_ext_sales_price as ext_price,
             ws_item_sk as sold_item_sk,
             ws_sold_time_sk as time_sk
      from web_sales, date_dim
      where d_date_sk = ws_sold_date_sk
        and d_moy = 11 and d_year = 1999
      union all
      select cs_ext_sales_price as ext_price,
             cs_item_sk as sold_item_sk,
             cs_sold_time_sk as time_sk
      from catalog_sales, date_dim
      where d_date_sk = cs_sold_date_sk
        and d_moy = 11 and d_year = 1999
      union all
      select ss_ext_sales_price as ext_price,
             ss_item_sk as sold_item_sk,
             ss_sold_time_sk as time_sk
      from store_sales, date_dim
      where d_date_sk = ss_sold_date_sk
        and d_moy = 11 and d_year = 1999) tmp,
     time_dim
where sold_item_sk = i_item_sk
  and i_manager_id = 1
  and time_sk = t_time_sk
  and (t_meal_time = 'breakfast' or t_meal_time = 'dinner')
group by i_brand, i_brand_id, t_hour, t_minute
order by ext_price desc, brand_id, t_hour, t_minute""",
    # q1: customers returning over 1.2x their store's average (CTE
    # referenced twice; correlated per-store average; q6's multiplier
    # placement)
    "q1": """
with customer_total_return as (
  select sr_customer_sk as ctr_customer_sk,
         sr_store_sk as ctr_store_sk,
         sum(sr_return_amt) as ctr_total_return
  from store_returns, date_dim
  where sr_returned_date_sk = d_date_sk and d_year = 2000
  group by sr_customer_sk, sr_store_sk)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return >
      1.2 * (select avg(ctr2.ctr_total_return)
             from customer_total_return ctr2
             where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk
  and s_state = 'TN'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100""",
    # q25: store sale -> store return -> catalog re-purchase profit
    # chain by item and store
    "q25": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4 and d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10 and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10 and d3.d_year = 2001
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100""",
    # q29: the same chain, quantities over a wider catalog window
    "q29": """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 9 and d1.d_year = 1999
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 9 and 12 and d2.d_year = 1999
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100""",
    # q40: catalog sales net of refunds by warehouse state around a
    # pivot date (left join to returns; NULL refund -> full price)
    "q40": """
select w_state, i_item_id,
  sum(case when d_date < date '2000-03-11' then
        case when cr_refunded_cash is null then cs_sales_price
             else cs_sales_price - cr_refunded_cash end
      else 0 end) as sales_before,
  sum(case when d_date >= date '2000-03-11' then
        case when cr_refunded_cash is null then cs_sales_price
             else cs_sales_price - cr_refunded_cash end
      else 0 end) as sales_after
from catalog_sales
  left join catalog_returns
    on cs_order_number = cr_order_number
   and cs_item_sk = cr_item_sk,
  warehouse, item, date_dim
where i_current_price between 0.99 and 1.49
  and i_item_sk = cs_item_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_sold_date_sk = d_date_sk
  and d_date between date '2000-02-10' and date '2000-04-10'
group by w_state, i_item_id
order by w_state, i_item_id
limit 100""",
    # q50: return-lag day buckets per store for August-2001 returns
    "q50": """
select s_store_name, s_store_id,
  sum(case when sr_returned_date_sk - ss_sold_date_sk <= 30
      then 1 else 0 end) as d30,
  sum(case when sr_returned_date_sk - ss_sold_date_sk > 30
           and sr_returned_date_sk - ss_sold_date_sk <= 60
      then 1 else 0 end) as d60,
  sum(case when sr_returned_date_sk - ss_sold_date_sk > 60
           and sr_returned_date_sk - ss_sold_date_sk <= 90
      then 1 else 0 end) as d90,
  sum(case when sr_returned_date_sk - ss_sold_date_sk > 90
           and sr_returned_date_sk - ss_sold_date_sk <= 120
      then 1 else 0 end) as d120,
  sum(case when sr_returned_date_sk - ss_sold_date_sk > 120
      then 1 else 0 end) as dmore
from store_sales, store_returns, store, date_dim d2
where d2.d_year = 2001 and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number
  and ss_item_sk = sr_item_sk
  and ss_customer_sk = sr_customer_sk
  and sr_returned_date_sk = d2.d_date_sk
  and ss_store_sk = s_store_sk
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100""",
    # q93: per-customer sales net of returns for one return reason
    "q93": """
select ss_customer_sk, sum(act_sales) as sumsales
from (select ss_customer_sk,
             (ss_quantity - sr_return_quantity) * ss_sales_price
               as act_sales
      from store_sales, store_returns, reason
      where sr_item_sk = ss_item_sk
        and sr_ticket_number = ss_ticket_number
        and sr_reason_sk = r_reason_sk
        and r_reason_desc = 'Stopped working') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100""",
    # q16: catalog orders shipped cross-warehouse with no returns.
    # COUNT(DISTINCT order) restated exactly as a per-order derived
    # aggregate (count of groups == count of distinct orders; the sums
    # are sums of per-order sums)
    "q16": """
select count(*) as order_count,
       sum(ship) as total_shipping_cost,
       sum(profit) as total_net_profit
from (select cs_order_number,
             sum(cs_ext_ship_cost) as ship,
             sum(cs_net_profit) as profit
      from catalog_sales cs1, date_dim, customer_address, call_center
      where d_date between date '1999-02-01' and date '1999-04-01'
        and cs1.cs_ship_date_sk = d_date_sk
        and cs1.cs_ship_addr_sk = ca_address_sk
        and ca_state = 'GA'
        and cs1.cs_call_center_sk = cc_call_center_sk
        and cc_county = 'Salem County'
        and exists (select * from catalog_sales cs2
                    where cs1.cs_order_number = cs2.cs_order_number
                      and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
        and not exists (select * from catalog_returns cr1
                        where cs1.cs_order_number
                              = cr1.cr_order_number)
      group by cs_order_number) o
limit 100""",
    # q94: the web twin of q16
    "q94": """
select count(*) as order_count,
       sum(ship) as total_shipping_cost,
       sum(profit) as total_net_profit
from (select ws_order_number,
             sum(ws_ext_ship_cost) as ship,
             sum(ws_net_profit) as profit
      from web_sales ws1, date_dim, customer_address, web_site
      where d_date between date '1999-02-01' and date '1999-04-01'
        and ws1.ws_ship_date_sk = d_date_sk
        and ws1.ws_ship_addr_sk = ca_address_sk
        and ca_state = 'GA'
        and ws1.ws_web_site_sk = web_site_sk
        and web_company_name = 'ought'
        and exists (select * from web_sales ws2
                    where ws1.ws_order_number = ws2.ws_order_number
                      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
        and not exists (select * from web_returns wr1
                        where ws1.ws_order_number
                              = wr1.wr_order_number)
      group by ws_order_number) o
limit 100""",
    # q62: web shipping-delay buckets (q99's web twin)
    "q62": """
select substring(w_warehouse_name, 1, 20) as wname, sm_type, web_name,
  sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30
      then 1 else 0 end) as d30,
  sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
           and ws_ship_date_sk - ws_sold_date_sk <= 60
      then 1 else 0 end) as d60,
  sum(case when ws_ship_date_sk - ws_sold_date_sk > 60
           and ws_ship_date_sk - ws_sold_date_sk <= 90
      then 1 else 0 end) as d90,
  sum(case when ws_ship_date_sk - ws_sold_date_sk > 90
           and ws_ship_date_sk - ws_sold_date_sk <= 120
      then 1 else 0 end) as d120,
  sum(case when ws_ship_date_sk - ws_sold_date_sk > 120
      then 1 else 0 end) as dmore
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 36 and 47
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by wname, sm_type, web_name
order by wname, sm_type, web_name
limit 100""",
    # q81: catalog returners above 1.2x their return-state average
    "q81": """
with customer_total_return as (
  select cr_returning_customer_sk as ctr_customer_sk,
         ca_state as ctr_state,
         sum(cr_return_amount) as ctr_total_return
  from catalog_returns, date_dim, customer_address
  where cr_returned_date_sk = d_date_sk and d_year = 2000
    and cr_returning_addr_sk = ca_address_sk
  group by cr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       ctr_total_return
from customer_total_return ctr1, customer_address, customer
where ctr1.ctr_total_return >
      1.2 * (select avg(ctr2.ctr_total_return)
             from customer_total_return ctr2
             where ctr1.ctr_state = ctr2.ctr_state)
  and ca_address_sk = c_current_addr_sk
  and ca_state = 'GA'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, c_salutation, c_first_name, c_last_name,
         ctr_total_return
limit 100""",
    # q30: the web twin of q81
    "q30": """
with customer_total_return as (
  select wr_returning_customer_sk as ctr_customer_sk,
         ca_state as ctr_state,
         sum(wr_return_amt) as ctr_total_return
  from web_returns, date_dim, customer_address
  where wr_returned_date_sk = d_date_sk and d_year = 2000
    and wr_returning_addr_sk = ca_address_sk
  group by wr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       ctr_total_return
from customer_total_return ctr1, customer_address, customer
where ctr1.ctr_total_return >
      1.2 * (select avg(ctr2.ctr_total_return)
             from customer_total_return ctr2
             where ctr1.ctr_state = ctr2.ctr_state)
  and ca_address_sk = c_current_addr_sk
  and ca_state = 'MO'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, c_salutation, c_first_name, c_last_name,
         ctr_total_return
limit 100""",
    # q61: promotional share of Jewelry sales. The official cross join
    # of two single-row derived tables restates exactly as one pass:
    # the promotion dimension is N:1 total, so joining it in the
    # "all sales" leg changes nothing, and the promotional leg becomes
    # a CASE-filtered sum
    "q61": """
select sum(case when p_channel_dmail = 'Y' or p_channel_email = 'Y'
                 or p_channel_tv = 'Y'
            then ss_ext_sales_price else 0 end) as promotions,
       sum(ss_ext_sales_price) as total
from store_sales, store, promotion, date_dim, customer,
     customer_address, item
where ss_sold_date_sk = d_date_sk
  and ss_store_sk = s_store_sk
  and ss_promo_sk = p_promo_sk
  and ss_customer_sk = c_customer_sk
  and ca_address_sk = c_current_addr_sk
  and ss_item_sk = i_item_sk
  and ca_gmt_offset = -5
  and i_category = 'Jewelry'
  and s_gmt_offset = -5
  and d_year = 1998 and d_moy = 11""",
    # q88: half-hour store traffic bands. The official 8-way cross join
    # of single-row counts restates exactly as 8 CASE-filtered sums
    # over one pass (all legs share the demographic and store filters)
    "q88": """
select
  sum(case when t_hour = 8 and t_minute >= 30 then 1 else 0 end)
    as h8_30_to_9,
  sum(case when t_hour = 9 and t_minute < 30 then 1 else 0 end)
    as h9_to_9_30,
  sum(case when t_hour = 9 and t_minute >= 30 then 1 else 0 end)
    as h9_30_to_10,
  sum(case when t_hour = 10 and t_minute < 30 then 1 else 0 end)
    as h10_to_10_30,
  sum(case when t_hour = 10 and t_minute >= 30 then 1 else 0 end)
    as h10_30_to_11,
  sum(case when t_hour = 11 and t_minute < 30 then 1 else 0 end)
    as h11_to_11_30,
  sum(case when t_hour = 11 and t_minute >= 30 then 1 else 0 end)
    as h11_30_to_12,
  sum(case when t_hour = 12 and t_minute < 30 then 1 else 0 end)
    as h12_to_12_30
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
  and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour between 8 and 12
  and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
       or (hd_dep_count = 2 and hd_vehicle_count <= 4)
       or (hd_dep_count = 0 and hd_vehicle_count <= 2))
  and s_store_name = 'ese'""",
    # q91: call-center catalog-return losses by demographic band
    # (window widened to the year and the gmt conjunct dropped — the
    # official compound selectivity is vacuous at synthetic test scale,
    # same adaptation practice as q65's month window)
    "q91": """
select cc_name, cd_marital_status, cd_education_status,
       sum(cr_net_loss) as returns_loss
from call_center, catalog_returns, date_dim, customer,
     customer_demographics, household_demographics
where cr_call_center_sk = cc_call_center_sk
  and cr_returned_date_sk = d_date_sk
  and cr_returning_customer_sk = c_customer_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and d_year = 1998
  and ((cd_marital_status = 'M' and cd_education_status = 'Unknown')
       or (cd_marital_status = 'W'
           and cd_education_status = 'Advanced Degree'))
  and hd_buy_potential like 'Unknown%'
group by cc_name, cd_marital_status, cd_education_status
order by returns_loss desc, cc_name, cd_marital_status,
         cd_education_status""",
    # q17: quantity statistics (count/avg/stddev_samp) over the store
    # sale -> return -> catalog re-purchase chain by item and store
    # state (the cov ratio columns are display math and are omitted)
    "q17": """
select i_item_id, i_item_desc, s_state,
       count(ss_quantity) as store_sales_quantitycount,
       avg(ss_quantity) as store_sales_quantityave,
       stddev_samp(ss_quantity) as store_sales_quantitystdev,
       count(sr_return_quantity) as store_returns_quantitycount,
       avg(sr_return_quantity) as store_returns_quantityave,
       stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
       count(cs_quantity) as catalog_sales_quantitycount,
       avg(cs_quantity) as catalog_sales_quantityave,
       stddev_samp(cs_quantity) as catalog_sales_quantitystdev
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_qoy = 1 and d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_qoy in (1, 2, 3) and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_qoy in (1, 2, 3) and d3.d_year = 2001
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100""",
    # q39: warehouse/item inventory demand variability across two
    # consecutive months (cov threshold adapted to the uniform
    # synthetic quantities: 0.5 instead of 1, same practice as q65)
    "q39": """
with inv as (
  select w_warehouse_sk, i_item_sk, d_moy,
         stddev_samp(inv_quantity_on_hand) as stdev,
         avg(inv_quantity_on_hand) as mean
  from inventory, item, warehouse, date_dim
  where inv_item_sk = i_item_sk
    and inv_warehouse_sk = w_warehouse_sk
    and inv_date_sk = d_date_sk
    and d_year = 2001
  group by w_warehouse_sk, i_item_sk, d_moy)
select inv1.w_warehouse_sk as wsk, inv1.i_item_sk as isk,
       inv1.d_moy as moy1, inv1.mean as mean1, inv1.stdev as stdev1,
       inv2.d_moy as moy2, inv2.mean as mean2, inv2.stdev as stdev2
from inv inv1, inv inv2
where inv1.i_item_sk = inv2.i_item_sk
  and inv1.w_warehouse_sk = inv2.w_warehouse_sk
  and inv1.d_moy = 1
  and inv2.d_moy = 2
  and inv1.stdev / inv1.mean > 0.5
  and inv2.stdev / inv2.mean > 0.5
order by wsk, isk
limit 100""",
    # q27: demographic item averages by store state (ROLLUP restated
    # flat at its finest grouping, the practice used for every rollup)
    "q27": """
select i_item_id, s_state,
       avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002 and s_state = 'TN'
group by i_item_id, s_state
order by i_item_id, s_state
limit 100""",
    # q18: catalog averages by item and bill-to geography for chosen
    # birth months (ROLLUP restated flat; the unfiltered cd2 join is
    # N:1 total and drops out)
    "q18": """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cs_quantity) as agg1, avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3, avg(cs_sales_price) as agg4,
       avg(cs_net_profit) as agg5, avg(c_birth_year) as agg6,
       avg(cd_dep_count) as agg7
from catalog_sales, customer_demographics, customer,
     customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd_gender = 'F' and cd_education_status = 'Unknown'
  and c_birth_month in (1, 6, 8, 9, 12, 2)
  and d_year = 1998
  and c_current_addr_sk = ca_address_sk
  and ca_state in ('MS', 'GA', 'NM', 'OH', 'TX')
group by i_item_id, ca_country, ca_state, ca_county
order by i_item_id, ca_country, ca_state, ca_county
limit 100""",
    # q9: five quantity-band buckets picked by CASE over scalar
    # subqueries, driven off a one-row reason scan (count thresholds
    # adapted to synthetic scale, same practice as q65/q91)
    "q9": """
select
  case when (select count(*) from store_sales
             where ss_quantity between 1 and 20) > 10000
       then (select avg(ss_ext_discount_amt) from store_sales
             where ss_quantity between 1 and 20)
       else (select avg(ss_net_paid) from store_sales
             where ss_quantity between 1 and 20) end as bucket1,
  case when (select count(*) from store_sales
             where ss_quantity between 21 and 40) > 10000
       then (select avg(ss_ext_discount_amt) from store_sales
             where ss_quantity between 21 and 40)
       else (select avg(ss_net_paid) from store_sales
             where ss_quantity between 21 and 40) end as bucket2,
  case when (select count(*) from store_sales
             where ss_quantity between 41 and 60) > 10000
       then (select avg(ss_ext_discount_amt) from store_sales
             where ss_quantity between 41 and 60)
       else (select avg(ss_net_paid) from store_sales
             where ss_quantity between 41 and 60) end as bucket3,
  case when (select count(*) from store_sales
             where ss_quantity between 61 and 80) > 10000
       then (select avg(ss_ext_discount_amt) from store_sales
             where ss_quantity between 61 and 80)
       else (select avg(ss_net_paid) from store_sales
             where ss_quantity between 61 and 80) end as bucket4,
  case when (select count(*) from store_sales
             where ss_quantity between 81 and 100) > 10000
       then (select avg(ss_ext_discount_amt) from store_sales
             where ss_quantity between 81 and 100)
       else (select avg(ss_net_paid) from store_sales
             where ss_quantity between 81 and 100) end as bucket5
from reason
where r_reason_sk = 1""",
    # q74: customers whose web spending grew faster than their store
    # spending year over year. The official UNION ALL year_total CTE
    # with a literal sale_type column restates exactly as one CTE per
    # channel (each self-join leg filters to a single sale_type)
    "q74": """
with store_total as (
  select c_customer_id as customer_id,
         c_first_name as customer_first_name,
         c_last_name as customer_last_name,
         d_year as yr, sum(ss_net_paid) as year_total
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk
    and ss_sold_date_sk = d_date_sk
    and d_year in (1998, 1999)
  group by c_customer_id, c_first_name, c_last_name, d_year),
web_total as (
  select c_customer_id as customer_id,
         c_first_name as customer_first_name,
         c_last_name as customer_last_name,
         d_year as yr, sum(ws_net_paid) as year_total
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk
    and ws_sold_date_sk = d_date_sk
    and d_year in (1998, 1999)
  group by c_customer_id, c_first_name, c_last_name, d_year)
select s2.customer_id, s2.customer_first_name,
       s2.customer_last_name
from store_total s1, store_total s2, web_total w1, web_total w2
where s2.customer_id = s1.customer_id
  and s1.customer_id = w1.customer_id
  and s1.customer_id = w2.customer_id
  and s1.yr = 1998 and s2.yr = 1999
  and w1.yr = 1998 and w2.yr = 1999
  and s1.year_total > 0
  and w1.year_total > 0
  and w2.year_total / w1.year_total
      > s2.year_total / s1.year_total
order by customer_id, customer_first_name, customer_last_name
limit 100""",
    # q36: gross margin by category/class (ROLLUP + lochierarchy rank
    # restated flat at the finest grouping; margin sorts via its
    # output alias)
    "q36": """
select sum(ss_net_profit) / sum(ss_ext_sales_price) as gross_margin,
       i_category, i_class
from store_sales, date_dim, item, store
where d_year = 2001
  and d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state = 'TN'
group by i_category, i_class
order by gross_margin, i_category, i_class
limit 100""",
    # q86: web revenue by category/class (ROLLUP restated flat)
    "q86": """
select sum(ws_net_paid) as total_sum, i_category, i_class
from web_sales, date_dim, item
where d_month_seq between 24 and 35
  and d_date_sk = ws_sold_date_sk
  and i_item_sk = ws_item_sk
group by i_category, i_class
order by total_sum desc, i_category, i_class
limit 100""",
    # q22: average inventory quantity by item attributes (ROLLUP
    # restated flat; i_product_name adapted to i_item_id)
    "q22": """
select i_item_id, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) as qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between 24 and 35
group by i_item_id, i_brand, i_class, i_category
order by qoh, i_item_id, i_brand, i_class, i_category
limit 100""",
    # q53: manufacturers whose quarterly revenue deviates >10% from
    # their yearly average (q89's partition-average restatement by
    # manufacturer and quarter)
    "q53": """
with msum as (
  select i_manufact_id, d_qoy,
         sum(ss_sales_price) as sum_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year = 1999
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('class#01', 'class#02', 'class#03'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('class#04', 'class#05', 'class#06')))
  group by i_manufact_id, d_qoy),
mavg as (
  select i_manufact_id as a_id,
         avg(sum_sales) as avg_quarterly_sales
  from msum
  group by i_manufact_id)
select i_manufact_id, d_qoy, sum_sales, avg_quarterly_sales
from msum, mavg
where i_manufact_id = a_id
  and avg_quarterly_sales > 0
  and abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id, d_qoy
limit 100""",
    # q10: demographics of county customers who bought in a store AND
    # in at least one remote channel in the window (EXISTS plus an
    # OR of EXISTS, decorrelated through counting scalar joins;
    # dep-employed/college columns adapted to cd_dep_count)
    "q10": """
select cd_gender, cd_marital_status, cd_education_status,
       cd_purchase_estimate, cd_credit_rating, cd_dep_count,
       count(*) as cnt
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_county in ('Salem County', 'Terrell County',
                    'Arthur County', 'Oglethorpe County',
                    'Lunenburg County')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = 2002 and d_moy between 1 and 4)
  and (exists (select * from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk
                 and d_year = 2002 and d_moy between 1 and 4)
       or exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_bill_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_moy between 1 and 4))
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count
limit 100""",
    # q35: q10's state-level twin with dep-count statistics
    "q35": """
select ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) as cnt1, min(cd_dep_count) as mn,
       max(cd_dep_count) as mx, avg(cd_dep_count) as av
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = 2002 and d_qoy < 4)
  and (exists (select * from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk
                 and d_year = 2002 and d_qoy < 4)
       or exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_bill_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_qoy < 4))
group by ca_state, cd_gender, cd_marital_status, cd_dep_count
order by ca_state, cd_gender, cd_marital_status, cd_dep_count
limit 100""",
    # q63: q53's twin — managers whose monthly revenue deviates >10%
    # from their yearly average
    "q63": """
with msum as (
  select i_manager_id, d_moy,
         sum(ss_sales_price) as sum_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year = 1999
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('class#01', 'class#02', 'class#03'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('class#04', 'class#05', 'class#06')))
  group by i_manager_id, d_moy),
mavg as (
  select i_manager_id as a_id,
         avg(sum_sales) as avg_monthly_sales
  from msum
  group by i_manager_id)
select i_manager_id, d_moy, sum_sales, avg_monthly_sales
from msum, mavg
where i_manager_id = a_id
  and avg_monthly_sales > 0
  and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales, d_moy
limit 100""",
    # q67: top-ranked item/month/store revenue cells per category
    # (ROLLUP restated flat at the finest grouping; i_product_name
    # adapted to i_item_id; full tiebreakers added to the sort)
    "q67": """
select i_category, i_class, i_brand, i_item_id, d_year, d_qoy,
       d_moy, s_store_id, sumsales, rk
from (select i_category, i_class, i_brand, i_item_id, d_year,
             d_qoy, d_moy, s_store_id, sumsales,
             rank() over (partition by i_category
                          order by sumsales desc) as rk
      from (select i_category, i_class, i_brand, i_item_id,
                   d_year, d_qoy, d_moy, s_store_id,
                   sum(ss_sales_price * ss_quantity) as sumsales
            from store_sales, date_dim, store, item
            where ss_sold_date_sk = d_date_sk
              and ss_item_sk = i_item_sk
              and ss_store_sk = s_store_sk
              and d_month_seq between 24 and 35
            group by i_category, i_class, i_brand, i_item_id,
                     d_year, d_qoy, d_moy, s_store_id) t) w
where rk <= 100
order by i_category, rk, i_class, i_brand, i_item_id, d_year,
         d_qoy, d_moy, s_store_id
limit 100""",
    # q70: county profit ranked within state (ROLLUP + the
    # tautological top-5-state IN subquery restated flat — partition
    # by s_state over one row per state always ranks 1)
    "q70": """
select s_state, s_county, sumsales, rk
from (select s_state, s_county, sumsales,
             rank() over (partition by s_state
                          order by sumsales desc) as rk
      from (select s_state, s_county,
                   sum(ss_net_profit) as sumsales
            from store_sales, date_dim, store
            where ss_sold_date_sk = d_date_sk
              and ss_store_sk = s_store_sk
              and d_month_seq between 24 and 35
            group by s_state, s_county) t) w
order by s_state, rk, s_county
limit 100""",
    # q44: best vs worst items by average profit at one store
    # (row_number with an item tiebreaker instead of rank, so the
    # rnk = rnk join never fans out on avg ties)
    "q44": """
with v as (
  select ss_item_sk as item_sk, avg(ss_net_profit) as avgp
  from store_sales
  where ss_store_sk = 4
  group by ss_item_sk)
select a.rnk as rnk, i1.i_item_id as best_performing,
       i2.i_item_id as worst_performing
from (select item_sk, rnk from (
        select item_sk,
               row_number() over (order by avgp desc, item_sk)
                 as rnk from v) x
      where rnk < 11) a,
     (select item_sk, rnk from (
        select item_sk,
               row_number() over (order by avgp, item_sk)
                 as rnk from v) y
      where rnk < 11) b,
     item i1, item i2
where a.rnk = b.rnk
  and i1.i_item_sk = a.item_sk
  and i2.i_item_sk = b.item_sk
order by rnk
limit 100""",
    # q11: q74's twin over list-price-minus-discount revenue with the
    # preferred-customer flag carried (same per-channel CTE
    # restatement of the official UNION ALL year_total)
    "q11": """
with store_total as (
  select c_customer_id as customer_id,
         c_preferred_cust_flag as flag,
         d_year as yr,
         sum(ss_ext_list_price - ss_ext_discount_amt) as year_total
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk
    and ss_sold_date_sk = d_date_sk
    and d_year in (1998, 1999)
  group by c_customer_id, c_preferred_cust_flag, d_year),
web_total as (
  select c_customer_id as customer_id,
         c_preferred_cust_flag as flag,
         d_year as yr,
         sum(ws_ext_list_price - ws_ext_discount_amt) as year_total
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk
    and ws_sold_date_sk = d_date_sk
    and d_year in (1998, 1999)
  group by c_customer_id, c_preferred_cust_flag, d_year)
select s2.customer_id, s2.flag
from store_total s1, store_total s2, web_total w1, web_total w2
where s2.customer_id = s1.customer_id
  and s1.customer_id = w1.customer_id
  and s1.customer_id = w2.customer_id
  and s1.yr = 1998 and s2.yr = 1999
  and w1.yr = 1998 and w2.yr = 1999
  and s1.year_total > 0
  and w1.year_total > 0
  and w2.year_total / w1.year_total
      > s2.year_total / s1.year_total
order by customer_id, flag
limit 100""",
    # q31: counties where web sales grew faster than store sales in
    # consecutive 2000 quarters (6-way self-join of per-channel CTEs;
    # the zero-denominator CASEs drop to plain >0 guards — a NULL
    # comparison is never satisfied either way)
    "q31": """
with ss as (
  select ca_county, d_qoy, d_year,
         sum(ss_ext_sales_price) as store_sales
  from store_sales, date_dim, customer_address
  where ss_sold_date_sk = d_date_sk
    and ss_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year),
ws as (
  select ca_county, d_qoy, d_year,
         sum(ws_ext_sales_price) as web_sales
  from web_sales, date_dim, customer_address
  where ws_sold_date_sk = d_date_sk
    and ws_bill_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year)
select ss1.ca_county, ss1.d_year,
       ws2.web_sales / ws1.web_sales as web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales as store_q1_q2_increase,
       ws3.web_sales / ws2.web_sales as web_q2_q3_increase,
       ss3.store_sales / ss2.store_sales as store_q2_q3_increase
from ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
where ss1.d_qoy = 1 and ss1.d_year = 2000
  and ss1.ca_county = ss2.ca_county
  and ss2.d_qoy = 2 and ss2.d_year = 2000
  and ss2.ca_county = ss3.ca_county
  and ss3.d_qoy = 3 and ss3.d_year = 2000
  and ss1.ca_county = ws1.ca_county
  and ws1.d_qoy = 1 and ws1.d_year = 2000
  and ws1.ca_county = ws2.ca_county
  and ws2.d_qoy = 2 and ws2.d_year = 2000
  and ws2.ca_county = ws3.ca_county
  and ws3.d_qoy = 3 and ws3.d_year = 2000
  and ws1.web_sales > 0 and ss1.store_sales > 0
  and ws2.web_sales > 0 and ss2.store_sales > 0
  and ws2.web_sales / ws1.web_sales
      > ss2.store_sales / ss1.store_sales
  and ws3.web_sales / ws2.web_sales
      > ss3.store_sales / ss2.store_sales
order by ss1.ca_county""",
    # q38: customers buying in all three channels in one year. The
    # official INTERSECT of DISTINCT (last, first, date) triples
    # restates exactly as a 1:1 join of the three distinct derived
    # tables on the triple
    "q38": """
select count(*) as cnt
from (select distinct c_last_name as ln, c_first_name as fn,
             d_date as dt
      from store_sales, date_dim, customer
      where ss_sold_date_sk = d_date_sk
        and ss_customer_sk = c_customer_sk
        and d_month_seq between 24 and 35) s,
     (select distinct c_last_name as ln, c_first_name as fn,
             d_date as dt
      from catalog_sales, date_dim, customer
      where cs_sold_date_sk = d_date_sk
        and cs_bill_customer_sk = c_customer_sk
        and d_month_seq between 24 and 35) c,
     (select distinct c_last_name as ln, c_first_name as fn,
             d_date as dt
      from web_sales, date_dim, customer
      where ws_sold_date_sk = d_date_sk
        and ws_bill_customer_sk = c_customer_sk
        and d_month_seq between 24 and 35) w
where s.ln = c.ln and s.fn = c.fn and s.dt = c.dt
  and s.ln = w.ln and s.fn = w.fn and s.dt = w.dt""",
    # q89: months deviating >10% from the (category, brand, store)
    # yearly average — the official AVG() OVER (PARTITION BY) restates
    # exactly as a join against a per-partition average CTE (the q98
    # practice; company-name column adapted to s_store_name)
    "q89": """
with msum as (
  select i_category, i_brand, s_store_name, d_moy,
         sum(ss_sales_price) as sum_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year = 1999
    and ((i_category in ('Books', 'Electronics', 'Sports')
          and i_class in ('class#01', 'class#02', 'class#03'))
         or (i_category in ('Men', 'Jewelry', 'Women')
             and i_class in ('class#04', 'class#05', 'class#06')))
  group by i_category, i_brand, s_store_name, d_moy),
mavg as (
  select i_category as a_category, i_brand as a_brand,
         s_store_name as a_store_name,
         avg(sum_sales) as avg_monthly_sales
  from msum
  group by i_category, i_brand, s_store_name)
select i_category, i_brand, s_store_name, d_moy, sum_sales,
       avg_monthly_sales,
       sum_sales - avg_monthly_sales as diff
from msum, mavg
where i_category = a_category
  and i_brand = a_brand
  and s_store_name = a_store_name
  and avg_monthly_sales > 0
  and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
order by diff, i_category, i_brand, s_store_name, d_moy
limit 100""",
    # q2: web+catalog weekly day-of-week sales, each week ratioed to
    # the same week one year later (53-week shift precomputed in the
    # second leg; week membership in a year via IN, avoiding the
    # official's row-duplicating date_dim join; ratios as plain
    # division, ROUND omitted)
    "q2": """
with wscs as (
  select ws_sold_date_sk as sold_date_sk,
         ws_ext_sales_price as sales_price
  from web_sales
  union all
  select cs_sold_date_sk as sold_date_sk,
         cs_ext_sales_price as sales_price
  from catalog_sales),
wswscs as (
  select d_week_seq,
         sum(case when d_day_name = 'Sunday'
             then sales_price else 0 end) as sun_sales,
         sum(case when d_day_name = 'Monday'
             then sales_price else 0 end) as mon_sales,
         sum(case when d_day_name = 'Tuesday'
             then sales_price else 0 end) as tue_sales,
         sum(case when d_day_name = 'Wednesday'
             then sales_price else 0 end) as wed_sales,
         sum(case when d_day_name = 'Thursday'
             then sales_price else 0 end) as thu_sales,
         sum(case when d_day_name = 'Friday'
             then sales_price else 0 end) as fri_sales,
         sum(case when d_day_name = 'Saturday'
             then sales_price else 0 end) as sat_sales
  from wscs, date_dim
  where d_date_sk = sold_date_sk
  group by d_week_seq)
select y.d_week_seq as week1,
       y.sun_sales / z.sun_sales as sun_ratio,
       y.mon_sales / z.mon_sales as mon_ratio,
       y.tue_sales / z.tue_sales as tue_ratio,
       y.wed_sales / z.wed_sales as wed_ratio,
       y.thu_sales / z.thu_sales as thu_ratio,
       y.fri_sales / z.fri_sales as fri_ratio,
       y.sat_sales / z.sat_sales as sat_ratio
from (select d_week_seq, sun_sales, mon_sales, tue_sales, wed_sales,
             thu_sales, fri_sales, sat_sales
      from wswscs
      where d_week_seq in (select d_week_seq from date_dim
                           where d_year = 2001)) y,
     (select d_week_seq - 53 as week_m53, sun_sales, mon_sales,
             tue_sales, wed_sales, thu_sales, fri_sales, sat_sales
      from wswscs
      where d_week_seq in (select d_week_seq from date_dim
                           where d_year = 2002)) z
where y.d_week_seq = z.week_m53
  and z.sun_sales > 0 and z.mon_sales > 0 and z.tue_sales > 0
  and z.wed_sales > 0 and z.thu_sales > 0 and z.fri_sales > 0
  and z.sat_sales > 0
order by week1""",
    # q4: customers whose catalog growth beats both store and web
    # growth (three per-channel CTEs as in q74/q11; the official /2
    # inside each sum scales every total equally and drops out of the
    # ratio comparisons; first-year totals of all channels guarded >0)
    "q4": """
with store_total as (
  select c_customer_id as customer_id,
         c_first_name as customer_first_name,
         c_last_name as customer_last_name,
         d_year as yr,
         sum(ss_ext_list_price - ss_ext_wholesale_cost
             - ss_ext_discount_amt + ss_ext_sales_price)
           as year_total
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk
    and ss_sold_date_sk = d_date_sk
    and d_year in (1998, 1999)
  group by c_customer_id, c_first_name, c_last_name, d_year),
cat_total as (
  select c_customer_id as customer_id, d_year as yr,
         sum(cs_ext_list_price - cs_ext_wholesale_cost
             - cs_ext_discount_amt + cs_ext_sales_price)
           as year_total
  from customer, catalog_sales, date_dim
  where c_customer_sk = cs_bill_customer_sk
    and cs_sold_date_sk = d_date_sk
    and d_year in (1998, 1999)
  group by c_customer_id, d_year),
web_total as (
  select c_customer_id as customer_id, d_year as yr,
         sum(ws_ext_list_price - ws_ext_wholesale_cost
             - ws_ext_discount_amt + ws_ext_sales_price)
           as year_total
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk
    and ws_sold_date_sk = d_date_sk
    and d_year in (1998, 1999)
  group by c_customer_id, d_year)
select s2.customer_id, s2.customer_first_name,
       s2.customer_last_name
from store_total s1, store_total s2, cat_total c1, cat_total c2,
     web_total w1, web_total w2
where s2.customer_id = s1.customer_id
  and s1.customer_id = c1.customer_id
  and s1.customer_id = c2.customer_id
  and s1.customer_id = w1.customer_id
  and s1.customer_id = w2.customer_id
  and s1.yr = 1998 and s2.yr = 1999
  and c1.yr = 1998 and c2.yr = 1999
  and w1.yr = 1998 and w2.yr = 1999
  and s1.year_total > 0 and c1.year_total > 0
  and w1.year_total > 0
  and c2.year_total / c1.year_total
      > s2.year_total / s1.year_total
  and c2.year_total / c1.year_total
      > w2.year_total / w1.year_total
order by customer_id, customer_first_name, customer_last_name
limit 100""",
}


def _decode(data: TpcdsData, table: str, col: str) -> np.ndarray:
    d = data.dicts[col]
    vals = np.array(d.values + [b""], dtype=object)
    return vals[data.tables[table][col]]


def _desc_bytes(b: bytes) -> tuple:
    """Sort key inverting lexicographic byte order (DESC string sort)."""
    return tuple(255 - x for x in b) + (256,)


def _pk_map(data, table, pk, *cols):
    t = data.tables[table]
    out = {}
    for i, k in enumerate(t[pk].tolist()):
        out[k] = tuple(t[c][i] for c in cols)
    return out


def reference_answers(data: TpcdsData,
                      queries=None) -> dict[str, list[tuple]]:
    """Independent numpy/python reference results (the canondata)."""
    names = queries or sorted(QUERIES)
    ref = _Ref(data)  # shared: the lookup-dict helpers memoize on self
    out: dict[str, list[tuple]] = {}
    for name in names:
        out[name] = getattr(ref, name)()
    return out


class _Ref:
    def __init__(self, data: TpcdsData):
        self.d = data

    def _date_info(self):
        dd = self.d.tables["date_dim"]
        return {k: (y, m) for k, y, m in zip(
            dd["d_date_sk"].tolist(), dd["d_year"].tolist(),
            dd["d_moy"].tolist())}

    def _brand_rollup(self, manager_id=None, manufact_id=None,
                      moy=11, year=None, key="brand"):
        d = self.d
        ss = d.tables["store_sales"]
        it = d.tables["item"]
        dates = self._date_info()
        brands = _decode(d, "item", "i_brand")
        cats = _decode(d, "item", "i_category")
        imap = {}
        for i, sk in enumerate(it["i_item_sk"].tolist()):
            imap[sk] = i
        acc: dict = collections.defaultdict(int)
        for dk, ik, p in zip(ss["ss_sold_date_sk"].tolist(),
                             ss["ss_item_sk"].tolist(),
                             ss["ss_ext_sales_price"].tolist()):
            y, m = dates[dk]
            if m != moy or (year is not None and y != year):
                continue
            i = imap[ik]
            if manager_id is not None and \
                    it["i_manager_id"][i] != manager_id:
                continue
            if manufact_id is not None and \
                    it["i_manufact_id"][i] != manufact_id:
                continue
            if key == "brand":
                k = (y, int(it["i_brand_id"][i]), brands[i])
            elif key == "category":
                k = (y, int(it["i_category_id"][i]), cats[i])
            else:
                raise KeyError(key)
            acc[k] += p
        return acc

    def q3(self):
        acc = self._brand_rollup(manufact_id=128, moy=11)
        rows = [(y, b, bn, s) for (y, b, bn), s in acc.items()]
        rows.sort(key=lambda r: (r[0], -r[3], r[1]))
        return rows[:100]

    def _demo_avgs(self, fact, pfx, cdemo_col):
        d = self.d
        f = d.tables[fact]
        dd = d.tables["date_dim"]
        years = dict(zip(dd["d_date_sk"].tolist(),
                         dd["d_year"].tolist()))
        cd = d.tables["customer_demographics"]
        g = _decode(d, "customer_demographics", "cd_gender")
        m = _decode(d, "customer_demographics", "cd_marital_status")
        e = _decode(d, "customer_demographics", "cd_education_status")
        demo_ok = {sk for i, sk in enumerate(cd["cd_demo_sk"].tolist())
                   if g[i] == b"M" and m[i] == b"S"
                   and e[i] == b"College"}
        pr = d.tables["promotion"]
        em = _decode(d, "promotion", "p_channel_email")
        ev = _decode(d, "promotion", "p_channel_event")
        promo_ok = {sk for i, sk in enumerate(pr["p_promo_sk"].tolist())
                    if em[i] == b"N" or ev[i] == b"N"}
        item_ids = _decode(d, "item", "i_item_id")
        iid = dict(zip(self.d.tables["item"]["i_item_sk"].tolist(),
                       item_ids.tolist()))
        acc: dict = collections.defaultdict(
            lambda: [0, 0, 0, 0, 0])  # qty, list, coupon, sales, n
        for dk, ik, cdk, pk, q, lp, cp, sp in zip(
                f[pfx + "sold_date_sk"].tolist(),
                f[pfx + "item_sk"].tolist(),
                f[cdemo_col].tolist(),
                f[pfx + "promo_sk"].tolist(),
                f[pfx + "quantity"].tolist(),
                f[pfx + "list_price"].tolist(),
                f[pfx + "coupon_amt"].tolist(),
                f[pfx + "sales_price"].tolist()):
            if years[dk] != 2000 or cdk not in demo_ok \
                    or pk not in promo_ok:
                continue
            st = acc[iid[ik]]
            st[0] += q
            st[1] += lp
            st[2] += cp
            st[3] += sp
            st[4] += 1
        rows = [(k, st[0] / st[4], st[1] / st[4] / 100,
                 st[2] / st[4] / 100, st[3] / st[4] / 100)
                for k, st in sorted(acc.items())]
        return rows[:100]

    def q6(self):
        d = self.d
        dd = d.tables["date_dim"]
        target_seq = {int(s) for y, m, s in zip(
            dd["d_year"].tolist(), dd["d_moy"].tolist(),
            dd["d_month_seq"].tolist()) if y == 2001 and m == 1}
        assert len(target_seq) == 1
        seq = next(iter(target_seq))
        date_ok = {k for k, s in zip(dd["d_date_sk"].tolist(),
                                     dd["d_month_seq"].tolist())
                   if s == seq}
        it = d.tables["item"]
        cat_sum: dict = collections.defaultdict(lambda: [0, 0])
        for c, p in zip(it["i_category_id"].tolist(),
                        it["i_current_price"].tolist()):
            cat_sum[c][0] += p
            cat_sum[c][1] += 1
        cat_avg = {c: s / n for c, (s, n) in cat_sum.items()}
        pricey = {sk for sk, c, p in zip(
            it["i_item_sk"].tolist(), it["i_category_id"].tolist(),
            it["i_current_price"].tolist())
            if p > 1.2 * cat_avg[c]}
        cust_addr = dict(zip(
            d.tables["customer"]["c_customer_sk"].tolist(),
            d.tables["customer"]["c_current_addr_sk"].tolist()))
        states = _decode(d, "customer_address", "ca_state")
        addr_state = dict(zip(
            d.tables["customer_address"]["ca_address_sk"].tolist(),
            states.tolist()))
        ss = d.tables["store_sales"]
        cnt: dict = collections.Counter()
        for dk, ck, ik in zip(ss["ss_sold_date_sk"].tolist(),
                              ss["ss_customer_sk"].tolist(),
                              ss["ss_item_sk"].tolist()):
            if dk in date_ok and ik in pricey:
                cnt[addr_state[cust_addr[ck]]] += 1
        rows = [(st, n) for st, n in cnt.items() if n >= 10]
        rows.sort(key=lambda r: (r[1], r[0]))
        return rows[:100]

    def q7(self):
        return self._demo_avgs("store_sales", "ss_", "ss_cdemo_sk")

    def q26(self):
        return self._demo_avgs("catalog_sales", "cs_", "cs_bill_cdemo_sk")

    def q19(self):
        d = self.d
        ss = d.tables["store_sales"]
        it = d.tables["item"]
        dates = self._date_info()
        brands = _decode(d, "item", "i_brand")
        manufacts = _decode(d, "item", "i_manufact")
        imap = dict((sk, i) for i, sk in
                    enumerate(it["i_item_sk"].tolist()))
        cust_addr = dict(zip(
            d.tables["customer"]["c_customer_sk"].tolist(),
            d.tables["customer"]["c_current_addr_sk"].tolist()))
        azip = dict(zip(
            d.tables["customer_address"]["ca_address_sk"].tolist(),
            _decode(d, "customer_address", "ca_zip").tolist()))
        szip = dict(zip(d.tables["store"]["s_store_sk"].tolist(),
                        _decode(d, "store", "s_zip").tolist()))
        acc: dict = collections.defaultdict(int)
        for dk, ik, ck, sk, p in zip(
                ss["ss_sold_date_sk"].tolist(),
                ss["ss_item_sk"].tolist(),
                ss["ss_customer_sk"].tolist(),
                ss["ss_store_sk"].tolist(),
                ss["ss_ext_sales_price"].tolist()):
            y, m = dates[dk]
            if m != 11 or y != 1998:
                continue
            i = imap[ik]
            if it["i_manager_id"][i] != 8:
                continue
            if azip[cust_addr[ck]][:5] == szip[sk][:5]:
                continue
            acc[(int(it["i_brand_id"][i]), brands[i],
                 int(it["i_manufact_id"][i]), manufacts[i])] += p
        rows = [(b, bn, mi, mn, s) for (b, bn, mi, mn), s
                in acc.items()]
        rows.sort(key=lambda r: (-r[4], r[1], r[0], r[2], r[3]))
        return rows[:100]

    def _sales_dim_maps(self):
        """Shared q13/q48 lookup maps: date_sk->year, cd_demo_sk->
        (marital, education), ca_address_sk->(state, country)."""
        d = self.d
        dd = d.tables["date_dim"]
        years = dict(zip(dd["d_date_sk"].tolist(),
                         dd["d_year"].tolist()))
        cd = d.tables["customer_demographics"]
        m = _decode(d, "customer_demographics", "cd_marital_status")
        e = _decode(d, "customer_demographics", "cd_education_status")
        demo = {sk: (m[i], e[i]) for i, sk in
                enumerate(cd["cd_demo_sk"].tolist())}
        ca = d.tables["customer_address"]
        states = _decode(d, "customer_address", "ca_state")
        countries = _decode(d, "customer_address", "ca_country")
        addr = {sk: (states[i], countries[i]) for i, sk in
                enumerate(ca["ca_address_sk"].tolist())}
        return years, demo, addr

    def q13(self):
        d = self.d
        ss = d.tables["store_sales"]
        years, demo, addr = self._sales_dim_maps()
        hd = dict(zip(
            d.tables["household_demographics"]["hd_demo_sk"].tolist(),
            d.tables["household_demographics"]["hd_dep_count"].tolist()))
        qty_sum = esp_sum = ewc_sum = n_rows = 0
        for dk, hk, ck, ak, q, sp, esp, ewc, npf in zip(
                ss["ss_sold_date_sk"].tolist(),
                ss["ss_hdemo_sk"].tolist(),
                ss["ss_cdemo_sk"].tolist(),
                ss["ss_addr_sk"].tolist(),
                ss["ss_quantity"].tolist(),
                ss["ss_sales_price"].tolist(),
                ss["ss_ext_sales_price"].tolist(),
                ss["ss_ext_wholesale_cost"].tolist(),
                ss["ss_net_profit"].tolist()):
            if years[dk] != 2001:
                continue
            ms, ed = demo[ck]
            dep = hd[hk]
            band1 = (
                (ms == b"M" and ed == b"Advanced Degree"
                 and 10000 <= sp <= 15000 and dep == 3)
                or (ms == b"S" and ed == b"College"
                    and 5000 <= sp <= 10000 and dep == 1)
                or (ms == b"W" and ed == b"2 yr Degree"
                    and 15000 <= sp <= 20000 and dep == 1))
            if not band1:
                continue
            st, country = addr[ak]
            band2 = country == b"United States" and (
                (st in (b"TX", b"OH") and 10000 <= npf <= 20000)
                or (st in (b"OR", b"NM", b"KY")
                    and 15000 <= npf <= 30000)
                or (st in (b"VA", b"TX", b"MS")
                    and 5000 <= npf <= 25000))
            if not band2:
                continue
            qty_sum += q
            esp_sum += esp
            ewc_sum += ewc
            n_rows += 1
        if n_rows == 0:
            return [(None, None, None, None)]
        return [(qty_sum / n_rows, esp_sum / n_rows / 100,
                 ewc_sum / n_rows / 100, ewc_sum)]

    def q48(self):
        ss = self.d.tables["store_sales"]
        years, demo, addr = self._sales_dim_maps()
        total = 0
        for dk, ck, ak, q, sp, npf in zip(
                ss["ss_sold_date_sk"].tolist(),
                ss["ss_cdemo_sk"].tolist(),
                ss["ss_addr_sk"].tolist(),
                ss["ss_quantity"].tolist(),
                ss["ss_sales_price"].tolist(),
                ss["ss_net_profit"].tolist()):
            if years[dk] != 2001:
                continue
            ms, ed = demo[ck]
            band1 = (
                (ms == b"M" and ed == b"4 yr Degree"
                 and 10000 <= sp <= 15000)
                or (ms == b"D" and ed == b"2 yr Degree"
                    and 5000 <= sp <= 10000)
                or (ms == b"S" and ed == b"College"
                    and 15000 <= sp <= 20000))
            if not band1:
                continue
            st, country = addr[ak]
            band2 = country == b"United States" and (
                (st in (b"CO", b"OH", b"TX")
                 and 0 <= npf <= 200000)
                or (st in (b"OR", b"MN", b"KY")
                    and 15000 <= npf <= 300000)
                or (st in (b"VA", b"CA", b"MS")
                    and 5000 <= npf <= 2500000))
            if not band2:
                continue
            total += q
        return [(total if total else None,)]

    def q42(self):
        acc = self._brand_rollup(manager_id=1, moy=11, year=2000,
                                 key="category")
        rows = [(y, c, cn, s) for (y, c, cn), s in acc.items()]
        rows.sort(key=lambda r: (-r[3], r[0], r[1], r[2]))
        return rows[:100]

    def q43(self):
        d = self.d
        ss = d.tables["store_sales"]
        dd = d.tables["date_dim"]
        day_names = _decode(d, "date_dim", "d_day_name")
        dinfo = {k: (y, day_names[i]) for i, (k, y) in enumerate(zip(
            dd["d_date_sk"].tolist(), dd["d_year"].tolist()))}
        st = d.tables["store"]
        snames = _decode(d, "store", "s_store_name")
        sids = _decode(d, "store", "s_store_id")
        smap = {}
        for i, sk in enumerate(st["s_store_sk"].tolist()):
            if st["s_gmt_offset"][i] == -5:
                smap[sk] = (snames[i], sids[i])
        order = [b"Sunday", b"Monday", b"Tuesday", b"Wednesday",
                 b"Thursday", b"Friday", b"Saturday"]
        acc: dict = collections.defaultdict(lambda: [0] * 7)
        for dk, sk, p in zip(ss["ss_sold_date_sk"].tolist(),
                             ss["ss_store_sk"].tolist(),
                             ss["ss_sales_price"].tolist()):
            y, dn = dinfo[dk]
            if y != 2000 or sk not in smap:
                continue
            acc[smap[sk]][order.index(dn)] += p
        rows = [(k[0], k[1], *v) for k, v in acc.items()]
        rows.sort(key=lambda r: (r[0], r[1]))
        return rows[:100]

    def q52(self):
        acc = self._brand_rollup(manager_id=1, moy=11, year=2000)
        rows = [(y, b, bn, s) for (y, b, bn), s in acc.items()]
        rows.sort(key=lambda r: (r[0], -r[3], r[1]))
        return rows[:100]

    def q55(self):
        acc = self._brand_rollup(manager_id=28, moy=11, year=1999)
        rows = [(b, bn, s) for (y, b, bn), s in acc.items()]
        rows.sort(key=lambda r: (-r[2], r[0]))
        return rows[:100]

    def q96(self):
        d = self.d
        ss = d.tables["store_sales"]
        hd_ok = {sk for sk, c in zip(
            d.tables["household_demographics"]["hd_demo_sk"].tolist(),
            d.tables["household_demographics"]["hd_dep_count"].tolist())
            if c == 7}
        snames = _decode(d, "store", "s_store_name")
        s_ok = {sk for i, sk in enumerate(
            d.tables["store"]["s_store_sk"].tolist())
            if snames[i] == b"ese"}
        n = 0
        for tk, hk, sk in zip(ss["ss_sold_time_sk"].tolist(),
                              ss["ss_hdemo_sk"].tolist(),
                              ss["ss_store_sk"].tolist()):
            h, mnt = tk // 3600, (tk % 3600) // 60
            if h == 20 and mnt >= 30 and hk in hd_ok and sk in s_ok:
                n += 1
        return [(n,)]

    # ---- batch-1 additions (q15/q32/q34/q46/q65/q68/q73/q79/q98) ----

    def _hd(self):
        if getattr(self, "_hd_cache", None) is not None:
            return self._hd_cache
        hd = self.d.tables["household_demographics"]
        bp = _decode(self.d, "household_demographics",
                     "hd_buy_potential")
        self._hd_cache = {sk: (int(dep), int(veh), b)
                          for sk, dep, veh, b in zip(
                              hd["hd_demo_sk"].tolist(),
                              hd["hd_dep_count"].tolist(),
                              hd["hd_vehicle_count"].tolist(), bp)}
        return self._hd_cache

    def _dd(self):
        if getattr(self, "_dd_cache", None) is not None:
            return self._dd_cache
        dd = self.d.tables["date_dim"]
        self._dd_cache = {
            sk: (int(y), int(m), int(dom), int(dow), int(q),
                 int(dt), int(ms))
            for sk, y, m, dom, dow, q, dt, ms in zip(
                dd["d_date_sk"].tolist(), dd["d_year"].tolist(),
                dd["d_moy"].tolist(), dd["d_dom"].tolist(),
                dd["d_dow"].tolist(), dd["d_qoy"].tolist(),
                dd["d_date"].tolist(), dd["d_month_seq"].tolist())}
        return self._dd_cache

    def _cust(self):
        if getattr(self, "_cust_cache", None) is not None:
            return self._cust_cache
        c = self.d.tables["customer"]
        fn = _decode(self.d, "customer", "c_first_name")
        ln = _decode(self.d, "customer", "c_last_name")
        sal = _decode(self.d, "customer", "c_salutation")
        fl = _decode(self.d, "customer", "c_preferred_cust_flag")
        self._cust_cache = {
            sk: (ln[i], fn[i], sal[i], fl[i],
                 int(c["c_current_addr_sk"][i]))
            for i, sk in enumerate(c["c_customer_sk"].tolist())}
        return self._cust_cache

    def q15(self):
        d = self.d
        cs = d.tables["catalog_sales"]
        dd = self._dd()
        cust = self._cust()
        ca = d.tables["customer_address"]
        zips = _decode(d, "customer_address", "ca_zip")
        states = _decode(d, "customer_address", "ca_state")
        ai = {sk: i for i, sk in
              enumerate(ca["ca_address_sk"].tolist())}
        tz = {b"85669", b"86197", b"88274", b"83405", b"86475",
              b"85392", b"85460", b"80348", b"81792"}
        ts = {b"CA", b"WA", b"GA"}
        acc: dict = collections.defaultdict(int)
        for dk, ck, sp in zip(cs["cs_sold_date_sk"].tolist(),
                              cs["cs_bill_customer_sk"].tolist(),
                              cs["cs_sales_price"].tolist()):
            y, _m, _dom, _dow, q, _dt, _ms = dd[dk]
            if y != 1998 or q != 2:
                continue
            i = ai[cust[ck][4]]
            if not (zips[i][:5] in tz or states[i] in ts
                    or sp > 50000):
                continue
            acc[zips[i]] += sp
        return sorted(acc.items())[:100]

    def _excess_discount(self, fact, date_col, item_col, amt_col,
                         manu_id, lo_s, hi_s):
        d = self.d
        f = d.tables[fact]
        dd = self._dd()
        lo = int(np.datetime64(lo_s, "D").astype(int))
        hi = int(np.datetime64(hi_s, "D").astype(int))
        manu = {sk for sk, m in zip(
            d.tables["item"]["i_item_sk"].tolist(),
            d.tables["item"]["i_manufact_id"].tolist())
            if m == manu_id}
        by_item: dict = collections.defaultdict(lambda: [0, 0])
        rows = []
        for dk, ik, amt in zip(f[date_col].tolist(),
                               f[item_col].tolist(),
                               f[amt_col].tolist()):
            if not (lo <= dd[dk][5] <= hi):
                continue
            st = by_item[ik]
            st[0] += amt
            st[1] += 1
            rows.append((ik, amt))
        excess = 0
        any_row = False
        for ik, amt in rows:
            if ik in manu:
                sm, n = by_item[ik]
                if amt > 1.3 * (sm / n):
                    excess += amt
                    any_row = True
        return [(excess if any_row else None,)]

    def q32(self):
        return self._excess_discount(
            "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
            "cs_ext_discount_amt", 66, "2002-03-29", "2002-06-27")

    def _ticket_counts(self, dom_ok, bp_set, dep_pred, years,
                       county_set):
        """(ticket, customer) -> line count under q34/q73 filters."""
        d = self.d
        ss = d.tables["store_sales"]
        dd = self._dd()
        hd = self._hd()
        counties = _decode(d, "store", "s_county")
        s_ok = {sk for i, sk in enumerate(
            d.tables["store"]["s_store_sk"].tolist())
            if counties[i] in county_set}
        acc: dict = collections.defaultdict(int)
        for dk, sk, hk, tn, ck in zip(
                ss["ss_sold_date_sk"].tolist(),
                ss["ss_store_sk"].tolist(),
                ss["ss_hdemo_sk"].tolist(),
                ss["ss_ticket_number"].tolist(),
                ss["ss_customer_sk"].tolist()):
            y, _m, dom, _dow, _q, _dt, _ms = dd[dk]
            dep, veh, bp = hd[hk]
            if y not in years or not dom_ok(dom) or sk not in s_ok \
                    or bp not in bp_set or veh <= 0 \
                    or not dep_pred(dep, veh):
                continue
            acc[(tn, ck)] += 1
        return acc

    def q34(self):
        acc = self._ticket_counts(
            lambda dom: 1 <= dom <= 3 or 25 <= dom <= 28,
            {b">10000", b"Unknown"},
            lambda dep, veh: dep > 1.2 * veh,
            {2000, 2001, 2002},
            {b"Salem County", b"Terrell County", b"Arthur County",
             b"Oglethorpe County", b"Lunenburg County",
             b"Perry County", b"Halifax County", b"Sumner County"})
        cust = self._cust()
        rows = [(cust[ck][0], cust[ck][1], cust[ck][2], cust[ck][3],
                 tn, c)
                for (tn, ck), c in acc.items() if 15 <= c <= 20]
        # c_preferred_cust_flag DESC, everything else ASC
        rows.sort(key=lambda r: (r[0], r[1], r[2],
                                 _desc_bytes(r[3]), r[4]))
        return rows

    def q73(self):
        acc = self._ticket_counts(
            lambda dom: 1 <= dom <= 2,
            {b">10000", b"5001-10000"},
            lambda dep, veh: dep > veh,
            {2000, 2001, 2002},
            {b"Lea County", b"Furnas County", b"Pennington County",
             b"Bronx County"})
        cust = self._cust()
        rows = [(cust[ck][0], cust[ck][1], cust[ck][2], cust[ck][3],
                 tn, c)
                for (tn, ck), c in acc.items() if 1 <= c <= 5]
        rows.sort(key=lambda r: (-r[5], r[0], r[4]))
        return rows

    def _ticket_sums(self, row_ok, cols):
        """(ticket, customer, addr) -> [sums of cols] under a filter."""
        d = self.d
        ss = d.tables["store_sales"]
        dd = self._dd()
        hd = self._hd()
        acc: dict = {}
        arrs = [ss[c].tolist() for c in cols]
        for i, (dk, sk, hk, tn, ck, ak) in enumerate(zip(
                ss["ss_sold_date_sk"].tolist(),
                ss["ss_store_sk"].tolist(),
                ss["ss_hdemo_sk"].tolist(),
                ss["ss_ticket_number"].tolist(),
                ss["ss_customer_sk"].tolist(),
                ss["ss_addr_sk"].tolist())):
            if not row_ok(dd[dk], sk, hd[hk]):
                continue
            st = acc.setdefault((tn, ck, ak), [0] * len(cols))
            for j, a in enumerate(arrs):
                st[j] += a[i]
        return acc

    def _city_move_rows(self, acc):
        """q46/q68 shape: join customer + current address, keep rows
        whose current city differs from the bought city."""
        d = self.d
        cust = self._cust()
        cities = _decode(d, "customer_address", "ca_city")
        ai = {sk: i for i, sk in enumerate(
            d.tables["customer_address"]["ca_address_sk"].tolist())}
        rows = []
        for (tn, ck, ak), sums in acc.items():
            bought = cities[ai[ak]]
            cur = cities[ai[cust[ck][4]]]
            if cur == bought:
                continue
            rows.append((cust[ck][0], cust[ck][1], cur, bought, tn,
                         *sums))
        return rows

    def q46(self):
        store_ok = self._city_stores(
            {b"Five Forks", b"Oakland", b"Fairview", b"Winchester",
             b"Farmington"})

        def ok(dinfo, sk, hdinfo):
            y, _m, _dom, dow, _q, _dt, _ms = dinfo
            dep, veh, _bp = hdinfo
            return (y in (2000, 2001, 2002) and dow in (6, 0)
                    and sk in store_ok and (dep == 0 or veh == 1))

        acc = self._ticket_sums(ok, ("ss_coupon_amt",
                                     "ss_net_profit"))
        rows = self._city_move_rows(acc)
        rows.sort(key=lambda r: r[:5])
        return rows[:100]

    def q68(self):
        store_ok = self._city_stores({b"Pleasant Hill", b"Bethel"})

        def ok(dinfo, sk, hdinfo):
            y, _m, dom, _dow, _q, _dt, _ms = dinfo
            dep, veh, _bp = hdinfo
            return (y in (1999, 2000, 2001) and 1 <= dom <= 2
                    and sk in store_ok and (dep == 4 or veh == 0))

        acc = self._ticket_sums(ok, ("ss_ext_sales_price",
                                     "ss_ext_list_price",
                                     "ss_ext_tax"))
        rows = [(ln, fn, cur, bought, tn, esp, etax, elp)
                for ln, fn, cur, bought, tn, esp, elp, etax
                in self._city_move_rows(acc)]
        rows.sort(key=lambda r: (r[0], r[4]))
        return rows[:100]

    def _city_stores(self, names):
        cities = _decode(self.d, "store", "s_city")
        return {sk for i, sk in enumerate(
            self.d.tables["store"]["s_store_sk"].tolist())
            if cities[i] in names}

    def q79(self):
        d = self.d
        st = d.tables["store"]
        cities = _decode(d, "store", "s_city")
        emp_ok = {sk: cities[i] for i, sk in
                  enumerate(st["s_store_sk"].tolist())
                  if 200 <= st["s_number_employees"][i] <= 295}

        def ok(dinfo, sk, hdinfo):
            y, _m, _dom, dow, _q, _dt, _ms = dinfo
            dep, veh, _bp = hdinfo
            return (y in (1998, 1999, 2000) and dow == 1
                    and sk in emp_ok and (dep == 0 or veh > 3))

        ss = d.tables["store_sales"]
        dd = self._dd()
        hd = self._hd()
        acc: dict = {}
        for dk, sk, hk, tn, ck, amt, pr in zip(
                ss["ss_sold_date_sk"].tolist(),
                ss["ss_store_sk"].tolist(),
                ss["ss_hdemo_sk"].tolist(),
                ss["ss_ticket_number"].tolist(),
                ss["ss_customer_sk"].tolist(),
                ss["ss_coupon_amt"].tolist(),
                ss["ss_net_profit"].tolist()):
            if not ok(dd[dk], sk, hd[hk]):
                continue
            st2 = acc.setdefault((tn, ck, emp_ok.get(sk)), [0, 0])
            st2[0] += amt
            st2[1] += pr
        cust = self._cust()
        rows = [(cust[ck][0], cust[ck][1], city[:30], tn, a, p)
                for (tn, ck, city), (a, p) in acc.items()]
        rows.sort(key=lambda r: (r[0], r[1], r[2], r[5], r[3]))
        return rows[:100]

    def q65(self):
        d = self.d
        ss = d.tables["store_sales"]
        dd = self._dd()
        rev: dict = collections.defaultdict(int)
        for dk, sk, ik, sp in zip(ss["ss_sold_date_sk"].tolist(),
                                  ss["ss_store_sk"].tolist(),
                                  ss["ss_item_sk"].tolist(),
                                  ss["ss_sales_price"].tolist()):
            if 48 <= dd[dk][6] <= 59:
                rev[(sk, ik)] += sp
        per_store: dict = collections.defaultdict(list)
        for (sk, _ik), r in rev.items():
            per_store[sk].append(r)
        ave = {sk: sum(v) / len(v) for sk, v in per_store.items()}
        it = d.tables["item"]
        ii = {sk: i for i, sk in enumerate(it["i_item_sk"].tolist())}
        si = {sk: i for i, sk in enumerate(
            d.tables["store"]["s_store_sk"].tolist())}
        snames = _decode(d, "store", "s_store_name")
        descs = _decode(d, "item", "i_item_desc")
        brands = _decode(d, "item", "i_brand")
        rows = []
        for (sk, ik), r in rev.items():
            if r <= 0.1 * ave[sk]:
                i = ii[ik]
                rows.append((snames[si[sk]], descs[i], r,
                             int(it["i_current_price"][i]),
                             int(it["i_wholesale_cost"][i]),
                             brands[i]))
        rows.sort(key=lambda x: (x[0], x[1], x[2], x[3],
                                 x[4], x[5]))
        return rows[:100]

    def q98(self):
        return self._class_share(
            "store_sales", "ss_sold_date_sk", "ss_item_sk",
            "ss_ext_sales_price", {b"Home", b"Sports", b"Men"},
            "2002-01-05", "2002-02-04")

    # ---- batch-2 additions (q12/q20/q21/q37/q45/q69/q82/q92/q99) ----

    def _item_info(self):
        if getattr(self, "_item_cache", None) is None:
            it = self.d.tables["item"]
            self._item_cache = ({sk: i for i, sk in
                                 enumerate(it["i_item_sk"].tolist())},
                                it)
        return self._item_cache

    def _class_share(self, fact, date_col, item_col, price_col,
                     cats, lo_s, hi_s):
        d = self.d
        f = d.tables[fact]
        dd = self._dd()
        lo = int(np.datetime64(lo_s, "D").astype(int))
        hi = int(np.datetime64(hi_s, "D").astype(int))
        ii, it = self._item_info()
        cats_d = _decode(d, "item", "i_category")
        classes = _decode(d, "item", "i_class")
        ids = _decode(d, "item", "i_item_id")
        descs = _decode(d, "item", "i_item_desc")
        acc: dict = collections.defaultdict(int)
        for dk, ik, p in zip(f[date_col].tolist(),
                             f[item_col].tolist(),
                             f[price_col].tolist()):
            if not (lo <= dd[dk][5] <= hi):
                continue
            i = ii[ik]
            if cats_d[i] not in cats:
                continue
            acc[(ids[i], descs[i], cats_d[i], classes[i],
                 int(it["i_current_price"][i]))] += p
        ctot: dict = collections.defaultdict(int)
        for (_i, _de, _ca, cl, _pr), r in acc.items():
            ctot[cl] += r
        rows = [(k[0], k[1], k[2], k[3], k[4], r,
                 r * 100.0 / ctot[k[3]])
                for k, r in acc.items()]
        rows.sort(key=lambda x: (x[2], x[3], x[0], x[1], x[6]))
        return rows[:100]

    def q12(self):
        return self._class_share(
            "web_sales", "ws_sold_date_sk", "ws_item_sk",
            "ws_ext_sales_price",
            {b"Electronics", b"Books", b"Women"},
            "1998-01-06", "1998-02-05")

    def q20(self):
        return self._class_share(
            "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
            "cs_ext_sales_price",
            {b"Shoes", b"Electronics", b"Children"},
            "2001-03-14", "2001-04-13")

    def q21(self):
        d = self.d
        inv = d.tables["inventory"]
        dd = self._dd()
        cut = int(np.datetime64("1999-03-20", "D").astype(int))
        lo = int(np.datetime64("1999-02-18", "D").astype(int))
        hi = int(np.datetime64("1999-04-19", "D").astype(int))
        ii, it = self._item_info()
        ids = _decode(d, "item", "i_item_id")
        wnames = _decode(d, "warehouse", "w_warehouse_name")
        wi = {sk: i for i, sk in enumerate(
            d.tables["warehouse"]["w_warehouse_sk"].tolist())}
        acc: dict = collections.defaultdict(lambda: [0, 0])
        for dk, ik, wk, q in zip(inv["inv_date_sk"].tolist(),
                                 inv["inv_item_sk"].tolist(),
                                 inv["inv_warehouse_sk"].tolist(),
                                 inv["inv_quantity_on_hand"].tolist()):
            dt = dd[dk][5]
            if not (lo <= dt <= hi):
                continue
            i = ii[ik]
            if not (99 <= it["i_current_price"][i] <= 149):
                continue
            st = acc[(wnames[wi[wk]], ids[i])]
            if dt < cut:
                st[0] += q
            else:
                st[1] += q
        rows = [(w, iid, b, a) for (w, iid), (b, a) in acc.items()
                if b > 0 and 3 * a >= 2 * b and 2 * a <= 3 * b]
        rows.sort(key=lambda r: (r[0], r[1]))
        return rows[:100]

    def _inv_items(self, fact, item_col, price_lo, price_hi, manus,
                   lo_s, hi_s):
        d = self.d
        inv = d.tables["inventory"]
        dd = self._dd()
        lo = int(np.datetime64(lo_s, "D").astype(int))
        hi = int(np.datetime64(hi_s, "D").astype(int))
        ii, it = self._item_info()
        ids = _decode(d, "item", "i_item_id")
        descs = _decode(d, "item", "i_item_desc")
        sold = set(d.tables[fact][item_col].tolist())
        keep = set()
        for dk, ik, q in zip(inv["inv_date_sk"].tolist(),
                             inv["inv_item_sk"].tolist(),
                             inv["inv_quantity_on_hand"].tolist()):
            if not (lo <= dd[dk][5] <= hi) or not (100 <= q <= 500):
                continue
            i = ii[ik]
            if not (price_lo <= it["i_current_price"][i] <= price_hi):
                continue
            if it["i_manufact_id"][i] not in manus or ik not in sold:
                continue
            keep.add((ids[i], descs[i], int(it["i_current_price"][i])))
        return sorted(keep)[:100]

    def q37(self):
        return self._inv_items("catalog_sales", "cs_item_sk",
                               3900, 6900, {765, 886, 889, 728},
                               "2001-01-16", "2001-03-17")

    def q82(self):
        return self._inv_items("store_sales", "ss_item_sk",
                               4900, 7900, {80, 675, 292, 17},
                               "2001-01-28", "2001-03-29")

    def q45(self):
        d = self.d
        ws = d.tables["web_sales"]
        dd = self._dd()
        cust = self._cust()
        ca = d.tables["customer_address"]
        zips = _decode(d, "customer_address", "ca_zip")
        counties = _decode(d, "customer_address", "ca_county")
        ai = {sk: i for i, sk in
              enumerate(ca["ca_address_sk"].tolist())}
        tz = {b"85669", b"86197", b"88274", b"83405", b"86475",
              b"85392", b"85460", b"80348", b"81792"}
        hot_items = {2, 3, 5, 7, 11, 13, 17, 19, 23, 29}
        acc: dict = collections.defaultdict(int)
        for dk, ck, ik, sp in zip(ws["ws_sold_date_sk"].tolist(),
                                  ws["ws_bill_customer_sk"].tolist(),
                                  ws["ws_item_sk"].tolist(),
                                  ws["ws_sales_price"].tolist()):
            y, _m, _dom, _dow, q, _dt, _ms = dd[dk]
            if y != 1998 or q != 1:
                continue
            i = ai[cust[ck][4]]
            if not (zips[i][:5] in tz or ik in hot_items):
                continue
            acc[(zips[i], counties[i])] += sp
        return sorted((k[0], k[1], v) for k, v in acc.items())[:100]

    def q69(self):
        d = self.d
        dd = self._dd()

        def active(fact, date_col, cust_col):
            out = set()
            f = d.tables[fact]
            for dk, ck in zip(f[date_col].tolist(),
                              f[cust_col].tolist()):
                y, m = dd[dk][0], dd[dk][1]
                if y == 2001 and 2 <= m <= 4:
                    out.add(ck)
            return out

        store = active("store_sales", "ss_sold_date_sk",
                       "ss_customer_sk")
        web = active("web_sales", "ws_sold_date_sk",
                     "ws_bill_customer_sk")
        cat = active("catalog_sales", "cs_sold_date_sk",
                     "cs_bill_customer_sk")
        ca = d.tables["customer_address"]
        states = _decode(d, "customer_address", "ca_state")
        ai = {sk: i for i, sk in
              enumerate(ca["ca_address_sk"].tolist())}
        cd = d.tables["customer_demographics"]
        g = _decode(d, "customer_demographics", "cd_gender")
        m_ = _decode(d, "customer_demographics", "cd_marital_status")
        e = _decode(d, "customer_demographics", "cd_education_status")
        cr = _decode(d, "customer_demographics", "cd_credit_rating")
        di = {sk: i for i, sk in enumerate(cd["cd_demo_sk"].tolist())}
        cust = d.tables["customer"]
        acc: dict = collections.defaultdict(int)
        for ck, ak, cdk in zip(cust["c_customer_sk"].tolist(),
                               cust["c_current_addr_sk"].tolist(),
                               cust["c_current_cdemo_sk"].tolist()):
            if states[ai[ak]] not in (b"MO", b"MN", b"AZ"):
                continue
            if ck not in store or ck in web or ck in cat:
                continue
            i = di[cdk]
            acc[(g[i], m_[i], e[i],
                 int(cd["cd_purchase_estimate"][i]), cr[i])] += 1
        rows = [(k[0], k[1], k[2], c, k[3], c, k[4], c)
                for k, c in acc.items()]
        rows.sort(key=lambda r: (r[0], r[1], r[2], r[4], r[6]))
        return rows[:100]

    def q92(self):
        return self._excess_discount(
            "web_sales", "ws_sold_date_sk", "ws_item_sk",
            "ws_ext_discount_amt", 356, "2001-03-12", "2001-06-10")

    def q99(self):
        d = self.d
        cs = d.tables["catalog_sales"]
        dd = self._dd()
        wnames = _decode(d, "warehouse", "w_warehouse_name")
        wi = {sk: i for i, sk in enumerate(
            d.tables["warehouse"]["w_warehouse_sk"].tolist())}
        smt = _decode(d, "ship_mode", "sm_type")
        smi = {sk: i for i, sk in enumerate(
            d.tables["ship_mode"]["sm_ship_mode_sk"].tolist())}
        ccn = _decode(d, "call_center", "cc_name")
        cci = {sk: i for i, sk in enumerate(
            d.tables["call_center"]["cc_call_center_sk"].tolist())}
        acc: dict = collections.defaultdict(lambda: [0] * 5)
        for sold, ship, wk, smk, cck in zip(
                cs["cs_sold_date_sk"].tolist(),
                cs["cs_ship_date_sk"].tolist(),
                cs["cs_warehouse_sk"].tolist(),
                cs["cs_ship_mode_sk"].tolist(),
                cs["cs_call_center_sk"].tolist()):
            if not (36 <= dd[ship][6] <= 47):
                continue
            lag = ship - sold
            st = acc[(wnames[wi[wk]][:20], smt[smi[smk]],
                      ccn[cci[cck]])]
            if lag <= 30:
                st[0] += 1
            elif lag <= 60:
                st[1] += 1
            elif lag <= 90:
                st[2] += 1
            elif lag <= 120:
                st[3] += 1
            else:
                st[4] += 1
        rows = [(k[0], k[1], k[2], *v) for k, v in acc.items()]
        rows.sort(key=lambda r: (r[0], r[1], r[2]))
        return rows[:100]

    # -- channel-union queries (q33/q56/q60/q71) --

    def _item_pos(self):
        it = self.d.tables["item"]
        sks = it["i_item_sk"]
        pos = np.full(int(sks.max()) + 1, -1, dtype=np.int64)
        pos[sks] = np.arange(len(sks))
        return pos

    _CHANNELS = (
        ("store_sales", "ss_sold_date_sk", "ss_item_sk",
         "ss_addr_sk", "ss_ext_sales_price"),
        ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
         "cs_bill_addr_sk", "cs_ext_sales_price"),
        ("web_sales", "ws_sold_date_sk", "ws_item_sk",
         "ws_bill_addr_sk", "ws_ext_sales_price"),
    )

    def _chan_union(self, year, moy, item_ok, key_of):
        """Three-channel union: ext_sales_price summed by an item
        attribute, branches filtered to (year, moy) x gmt_offset -5."""
        d = self.d
        dd = d.tables["date_dim"]
        dok = dd["d_date_sk"][(dd["d_year"] == year)
                              & (dd["d_moy"] == moy)]
        ca = d.tables["customer_address"]
        aok = ca["ca_address_sk"][ca["ca_gmt_offset"] == -5]
        pos = self._item_pos()
        acc: dict = collections.defaultdict(int)
        for t, dk, ik, ak, p in self._CHANNELS:
            tb = d.tables[t]
            m = np.isin(tb[dk], dok) & np.isin(tb[ak], aok)
            rows = pos[tb[ik][m]]
            price = tb[p][m]
            keep = item_ok[rows]
            for r, pp in zip(rows[keep].tolist(), price[keep].tolist()):
                acc[key_of(r)] += pp
        return acc

    def q33(self):
        it = self.d.tables["item"]
        cats = _decode(self.d, "item", "i_category")
        # IN (select i_manufact_id ... where category='Electronics'):
        # every item of any manufacturer with >= 1 Electronics item
        # qualifies, regardless of that item's own category
        manu_ok = set(
            it["i_manufact_id"][cats == b"Electronics"].tolist())
        item_ok = np.array(
            [int(m) in manu_ok for m in it["i_manufact_id"]])
        acc = self._chan_union(
            1998, 5, item_ok,
            lambda r: int(it["i_manufact_id"][r]))
        return sorted(acc.items(), key=lambda kv: (kv[1], kv[0]))[:100]

    def q56(self):
        colors = _decode(self.d, "item", "i_color")
        ids = _decode(self.d, "item", "i_item_id")
        ok = np.isin(colors, [b"slate", b"blanched", b"cornsilk"])
        acc = self._chan_union(2001, 2, ok, lambda r: ids[r])
        return sorted(acc.items(), key=lambda kv: (kv[1], kv[0]))[:100]

    def q60(self):
        cats = _decode(self.d, "item", "i_category")
        ids = _decode(self.d, "item", "i_item_id")
        acc = self._chan_union(1998, 9, cats == b"Music",
                               lambda r: ids[r])
        return sorted(acc.items(), key=lambda kv: (kv[0], kv[1]))[:100]

    def q71(self):
        d = self.d
        it = d.tables["item"]
        dd = d.tables["date_dim"]
        dok = dd["d_date_sk"][(dd["d_year"] == 1999)
                              & (dd["d_moy"] == 11)]
        brands = _decode(d, "item", "i_brand")
        pos = self._item_pos()
        acc: dict = collections.defaultdict(int)
        for t, dk, ik, ak, p in self._CHANNELS:
            tk = {"store_sales": "ss_sold_time_sk",
                  "catalog_sales": "cs_sold_time_sk",
                  "web_sales": "ws_sold_time_sk"}[t]
            tb = d.tables[t]
            m = np.isin(tb[dk], dok)
            rows = pos[tb[ik][m]]
            tks = tb[tk][m]
            price = tb[p][m]
            hour = tks // 3600
            keep = (it["i_manager_id"][rows] == 1) & (
                ((hour >= 6) & (hour < 9))
                | ((hour >= 17) & (hour < 21)))
            for r, tsec, pp in zip(rows[keep].tolist(),
                                   tks[keep].tolist(),
                                   price[keep].tolist()):
                acc[(int(it["i_brand_id"][r]), brands[r],
                     int(tsec) // 3600,
                     (int(tsec) % 3600) // 60)] += pp
        rows_ = [(*k, v) for k, v in acc.items()]
        rows_.sort(key=lambda r: (-r[4], r[0], r[2], r[3]))
        return rows_

    # -- returns-chain queries (q1/q25/q29/q40/q50/q93) --

    def _date_cols(self, sks):
        """(year, moy, date) arrays for date-sk array, via the
        contiguous sk layout d_date_sk = _D0_SK + row."""
        dd = self.d.tables["date_dim"]
        idx = np.asarray(sks) - _D0_SK
        return dd["d_year"][idx], dd["d_moy"][idx], dd["d_date"][idx]

    def q1(self):
        d = self.d
        sr = d.tables["store_returns"]
        yr, _, _ = self._date_cols(sr["sr_returned_date_sk"])
        m = yr == 2000
        acc: dict = collections.defaultdict(int)
        for c, s, a in zip(sr["sr_customer_sk"][m].tolist(),
                           sr["sr_store_sk"][m].tolist(),
                           sr["sr_return_amt"][m].tolist()):
            acc[(c, s)] += a
        per_store: dict = collections.defaultdict(list)
        for (c, s), t in acc.items():
            per_store[s].append(t)
        st = d.tables["store"]
        states = _decode(d, "store", "s_state")
        tn = {sk for sk, stt in zip(st["s_store_sk"].tolist(), states)
              if stt == b"TN"}
        cids = _decode(d, "customer", "c_customer_id")
        out = []
        for (c, s), t in acc.items():
            if s in tn and t > 1.2 * (sum(per_store[s])
                                      / len(per_store[s])):
                out.append(cids[c - 1])
        out.sort()
        return [(x,) for x in out[:100]]

    def _chain_rows(self, d1_ok, d2_ok, d3_ok):
        """(ss_row, sr_row, cs_row) triples of the q25/q29 join chain:
        store sale (d1) -> its return (d2) -> catalog purchases by the
        same (customer, item) (d3)."""
        d = self.d
        ss, sr = d.tables["store_sales"], d.tables["store_returns"]
        cs = d.tables["catalog_sales"]
        ss_rows: dict = collections.defaultdict(list)
        for i, (c, k, t) in enumerate(zip(
                ss["ss_customer_sk"].tolist(),
                ss["ss_item_sk"].tolist(),
                ss["ss_ticket_number"].tolist())):
            if d1_ok[i]:
                ss_rows[(c, k, t)].append(i)
        cs_rows: dict = collections.defaultdict(list)
        for j, (c, k) in enumerate(zip(
                cs["cs_bill_customer_sk"].tolist(),
                cs["cs_item_sk"].tolist())):
            if d3_ok[j]:
                cs_rows[(c, k)].append(j)
        out = []
        for r, (c, k, t) in enumerate(zip(
                sr["sr_customer_sk"].tolist(),
                sr["sr_item_sk"].tolist(),
                sr["sr_ticket_number"].tolist())):
            if not d2_ok[r]:
                continue
            for i in ss_rows.get((c, k, t), ()):
                for j in cs_rows.get((c, k), ()):
                    out.append((i, r, j))
        return out

    def _chain_agg(self, d1_ok, d2_ok, d3_ok, ss_col, sr_col, cs_col):
        d = self.d
        ss, sr = d.tables["store_sales"], d.tables["store_returns"]
        cs = d.tables["catalog_sales"]
        it, st = d.tables["item"], d.tables["store"]
        iids = _decode(d, "item", "i_item_id")
        idescs = _decode(d, "item", "i_item_desc")
        sids = _decode(d, "store", "s_store_id")
        snames = _decode(d, "store", "s_store_name")
        ipos = self._item_pos()
        spos = {sk: i for i, sk in enumerate(
            st["s_store_sk"].tolist())}
        acc: dict = collections.defaultdict(lambda: [0, 0, 0])
        for i, r, j in self._chain_rows(d1_ok, d2_ok, d3_ok):
            ir = ipos[ss["ss_item_sk"][i]]
            sp = spos[ss["ss_store_sk"][i]]
            k = (iids[ir], idescs[ir], sids[sp], snames[sp])
            acc[k][0] += int(ss[ss_col][i])
            acc[k][1] += int(sr[sr_col][r])
            acc[k][2] += int(cs[cs_col][j])
        rows = [(*k, *v) for k, v in sorted(acc.items())]
        return rows[:100]

    def q25(self):
        d = self.d
        y1, m1, _ = self._date_cols(
            d.tables["store_sales"]["ss_sold_date_sk"])
        y2, m2, _ = self._date_cols(
            d.tables["store_returns"]["sr_returned_date_sk"])
        y3, m3, _ = self._date_cols(
            d.tables["catalog_sales"]["cs_sold_date_sk"])
        return self._chain_agg(
            (y1 == 2001) & (m1 == 4),
            (y2 == 2001) & (m2 >= 4) & (m2 <= 10),
            (y3 == 2001) & (m3 >= 4) & (m3 <= 10),
            "ss_net_profit", "sr_net_loss", "cs_net_profit")

    def q29(self):
        d = self.d
        y1, m1, _ = self._date_cols(
            d.tables["store_sales"]["ss_sold_date_sk"])
        y2, m2, _ = self._date_cols(
            d.tables["store_returns"]["sr_returned_date_sk"])
        y3, _, _ = self._date_cols(
            d.tables["catalog_sales"]["cs_sold_date_sk"])
        return self._chain_agg(
            (y1 == 1999) & (m1 == 9),
            (y2 == 1999) & (m2 >= 9) & (m2 <= 12),
            np.isin(y3, (1999, 2000, 2001)),
            "ss_quantity", "sr_return_quantity", "cs_quantity")

    def q40(self):
        d = self.d
        cs = d.tables["catalog_sales"]
        cr = d.tables["catalog_returns"]
        it = d.tables["item"]
        refund = {(o, k): c for o, k, c in zip(
            cr["cr_order_number"].tolist(),
            cr["cr_item_sk"].tolist(),
            cr["cr_refunded_cash"].tolist())}
        wstates = _decode(d, "warehouse", "w_state")
        wpos = {sk: i for i, sk in enumerate(
            d.tables["warehouse"]["w_warehouse_sk"].tolist())}
        iids = _decode(d, "item", "i_item_id")
        ipos = self._item_pos()
        _, _, dates = self._date_cols(cs["cs_sold_date_sk"])
        pivot = int((np.datetime64("2000-03-11", "D")
                     - np.datetime64("1970-01-01", "D")).astype(int))
        lo = pivot - 30
        hi = pivot + 30
        acc: dict = collections.defaultdict(lambda: [0, 0])
        for j, (dt, ik, wk, o, p) in enumerate(zip(
                dates.tolist(), cs["cs_item_sk"].tolist(),
                cs["cs_warehouse_sk"].tolist(),
                cs["cs_order_number"].tolist(),
                cs["cs_sales_price"].tolist())):
            if not (lo <= dt <= hi):
                continue
            ir = ipos[ik]
            if not (99 <= it["i_current_price"][ir] <= 149):
                continue
            net = p - refund.get((o, ik), 0)
            k = (wstates[wpos[wk]], iids[ir])
            acc[k][0 if dt < pivot else 1] += net
        rows = [(*k, *v) for k, v in sorted(acc.items())]
        return rows[:100]

    def q50(self):
        d = self.d
        ss, sr = d.tables["store_sales"], d.tables["store_returns"]
        y2, m2, _ = self._date_cols(sr["sr_returned_date_sk"])
        sold = dict()
        for i, (c, k, t) in enumerate(zip(
                ss["ss_customer_sk"].tolist(),
                ss["ss_item_sk"].tolist(),
                ss["ss_ticket_number"].tolist())):
            sold.setdefault((c, k, t), []).append(i)
        st = d.tables["store"]
        sids = _decode(d, "store", "s_store_id")
        snames = _decode(d, "store", "s_store_name")
        spos = {sk: i for i, sk in enumerate(
            st["s_store_sk"].tolist())}
        acc: dict = collections.defaultdict(lambda: [0] * 5)
        for r in np.flatnonzero((y2 == 2001) & (m2 == 8)).tolist():
            key = (sr["sr_customer_sk"][r], sr["sr_item_sk"][r],
                   sr["sr_ticket_number"][r])
            for i in sold.get(key, ()):
                lag = int(sr["sr_returned_date_sk"][r]
                          - ss["ss_sold_date_sk"][i])
                sp = spos[ss["ss_store_sk"][i]]
                st_ = acc[(snames[sp], sids[sp])]
                if lag <= 30:
                    st_[0] += 1
                elif lag <= 60:
                    st_[1] += 1
                elif lag <= 90:
                    st_[2] += 1
                elif lag <= 120:
                    st_[3] += 1
                else:
                    st_[4] += 1
        rows = [(*k, *v) for k, v in sorted(acc.items())]
        return rows[:100]

    def q93(self):
        d = self.d
        ss, sr = d.tables["store_sales"], d.tables["store_returns"]
        rdesc = _decode(d, "reason", "r_reason_desc")
        rok = {sk for sk, t in zip(
            d.tables["reason"]["r_reason_sk"].tolist(), rdesc)
            if t == b"Stopped working"}
        pairs: dict = collections.defaultdict(list)
        for i, (k, t) in enumerate(zip(
                ss["ss_item_sk"].tolist(),
                ss["ss_ticket_number"].tolist())):
            pairs[(k, t)].append(i)
        acc: dict = collections.defaultdict(int)
        for r, (k, t, rk, q) in enumerate(zip(
                sr["sr_item_sk"].tolist(),
                sr["sr_ticket_number"].tolist(),
                sr["sr_reason_sk"].tolist(),
                sr["sr_return_quantity"].tolist())):
            if rk not in rok:
                continue
            for i in pairs.get((k, t), ()):
                acc[int(ss["ss_customer_sk"][i])] += (
                    int(ss["ss_quantity"][i]) - q
                ) * int(ss["ss_sales_price"][i])
        rows = sorted(acc.items(), key=lambda kv: (kv[1], kv[0]))
        return rows[:100]

    # -- web-channel queries (q16/q94/q62/q81/q30) --

    @staticmethod
    def _days(s: str) -> int:
        return int((np.datetime64(s, "D")
                    - np.datetime64("1970-01-01", "D")).astype(int))

    def _ship_no_return(self, fact, pfx, returns, r_pfx, row_ok):
        """q16/q94 shape: lines shipped in a window whose order has a
        sibling line from another warehouse and no return."""
        d = self.d
        tb = d.tables[fact]
        _, _, dates = self._date_cols(tb[pfx + "ship_date_sk"])
        lo, hi = self._days("1999-02-01"), self._days("1999-04-01")
        wh_sets: dict = collections.defaultdict(set)
        for o, w in zip(tb[pfx + "order_number"].tolist(),
                        tb[pfx + "warehouse_sk"].tolist()):
            wh_sets[o].add(w)
        returned = set(
            d.tables[returns][r_pfx + "order_number"].tolist())
        orders: set = set()
        ship = profit = 0
        for i, (o, dt) in enumerate(zip(
                tb[pfx + "order_number"].tolist(), dates.tolist())):
            if not (lo <= dt <= hi) or not row_ok[i]:
                continue
            if len(wh_sets[o]) < 2 or o in returned:
                continue
            orders.add(o)
            ship += int(tb[pfx + "ext_ship_cost"][i])
            profit += int(tb[pfx + "net_profit"][i])
        if not orders:
            return [(0, None, None)]
        return [(len(orders), ship, profit)]

    def _addr_state_ok(self, sks, state: bytes):
        states = _decode(self.d, "customer_address", "ca_state")
        return states[np.asarray(sks) - 1] == state

    def q16(self):
        d = self.d
        cs = d.tables["catalog_sales"]
        counties = _decode(d, "call_center", "cc_county")
        cc_ok = {sk for sk, c in zip(
            d.tables["call_center"]["cc_call_center_sk"].tolist(),
            counties) if c == b"Salem County"}
        row_ok = self._addr_state_ok(cs["cs_ship_addr_sk"], b"GA") & \
            np.array([c in cc_ok
                      for c in cs["cs_call_center_sk"].tolist()])
        return self._ship_no_return(
            "catalog_sales", "cs_", "catalog_returns", "cr_", row_ok)

    def q94(self):
        d = self.d
        ws = d.tables["web_sales"]
        comp = _decode(d, "web_site", "web_company_name")
        site_ok = {sk for sk, c in zip(
            d.tables["web_site"]["web_site_sk"].tolist(), comp)
            if c == b"ought"}
        row_ok = self._addr_state_ok(ws["ws_ship_addr_sk"], b"GA") & \
            np.array([s in site_ok
                      for s in ws["ws_web_site_sk"].tolist()])
        return self._ship_no_return(
            "web_sales", "ws_", "web_returns", "wr_", row_ok)

    def q62(self):
        d = self.d
        ws = d.tables["web_sales"]
        dd = self._dd()
        wnames = _decode(d, "warehouse", "w_warehouse_name")
        wi = {sk: i for i, sk in enumerate(
            d.tables["warehouse"]["w_warehouse_sk"].tolist())}
        smt = _decode(d, "ship_mode", "sm_type")
        smi = {sk: i for i, sk in enumerate(
            d.tables["ship_mode"]["sm_ship_mode_sk"].tolist())}
        wn = _decode(d, "web_site", "web_name")
        wsi = {sk: i for i, sk in enumerate(
            d.tables["web_site"]["web_site_sk"].tolist())}
        acc: dict = collections.defaultdict(lambda: [0] * 5)
        for sold, ship, wk, smk, sk in zip(
                ws["ws_sold_date_sk"].tolist(),
                ws["ws_ship_date_sk"].tolist(),
                ws["ws_warehouse_sk"].tolist(),
                ws["ws_ship_mode_sk"].tolist(),
                ws["ws_web_site_sk"].tolist()):
            if not (36 <= dd[ship][6] <= 47):
                continue
            lag = ship - sold
            st = acc[(wnames[wi[wk]][:20], smt[smi[smk]],
                      wn[wsi[sk]])]
            if lag <= 30:
                st[0] += 1
            elif lag <= 60:
                st[1] += 1
            elif lag <= 90:
                st[2] += 1
            elif lag <= 120:
                st[3] += 1
            else:
                st[4] += 1
        rows = [(k[0], k[1], k[2], *v) for k, v in acc.items()]
        rows.sort(key=lambda r: (r[0], r[1], r[2]))
        return rows[:100]

    def _ctr_over_state_avg(self, rt, pfx, amt_col, state_lit):
        """q81/q30 shape: returners above 1.2x their return-state
        average, restricted to customers whose CURRENT address is in
        state_lit."""
        d = self.d
        tb = d.tables[rt]
        yr, _, _ = self._date_cols(tb[pfx + "returned_date_sk"])
        states = _decode(d, "customer_address", "ca_state")
        acc: dict = collections.defaultdict(int)
        for ok, c, a, amt in zip(
                (yr == 2000).tolist(),
                tb[pfx + "returning_customer_sk"].tolist(),
                tb[pfx + "returning_addr_sk"].tolist(),
                tb[amt_col].tolist()):
            if ok:
                acc[(c, states[a - 1])] += amt
        per_state: dict = collections.defaultdict(list)
        for (c, st), t in acc.items():
            per_state[st].append(t)
        cust = d.tables["customer"]
        cur_state = states[cust["c_current_addr_sk"] - 1]
        cids = _decode(d, "customer", "c_customer_id")
        sal = _decode(d, "customer", "c_salutation")
        fn = _decode(d, "customer", "c_first_name")
        ln = _decode(d, "customer", "c_last_name")
        out = []
        for (c, st), t in acc.items():
            if t <= 1.2 * (sum(per_state[st]) / len(per_state[st])):
                continue
            if cur_state[c - 1] != state_lit:
                continue
            out.append((cids[c - 1], sal[c - 1], fn[c - 1],
                        ln[c - 1], t))
        out.sort()
        return out[:100]

    def q61(self):
        d = self.d
        ss = d.tables["store_sales"]
        y, m, _ = self._date_cols(ss["ss_sold_date_sk"])
        cats = _decode(d, "item", "i_category")
        ipos = self._item_pos()
        st = d.tables["store"]
        s_ok = set(st["s_store_sk"][
            st["s_gmt_offset"] == -5].tolist())
        pr = d.tables["promotion"]
        promo_ok = set(pr["p_promo_sk"][
            (_decode(d, "promotion", "p_channel_dmail") == b"Y")
            | (_decode(d, "promotion", "p_channel_email") == b"Y")
            | (_decode(d, "promotion", "p_channel_tv") == b"Y")
        ].tolist())
        cust_addr = d.tables["customer"]["c_current_addr_sk"]
        addr_gmt = d.tables["customer_address"]["ca_gmt_offset"]
        total = promos = n_rows = 0
        for i in np.flatnonzero((y == 1998) & (m == 11)).tolist():
            if ss["ss_store_sk"][i] not in s_ok:
                continue
            if cats[ipos[ss["ss_item_sk"][i]]] != b"Jewelry":
                continue
            if addr_gmt[cust_addr[ss["ss_customer_sk"][i] - 1] - 1] \
                    != -5:
                continue
            p = int(ss["ss_ext_sales_price"][i])
            total += p
            n_rows += 1
            if ss["ss_promo_sk"][i] in promo_ok:
                promos += p
        if not n_rows:
            return [(None, None)]
        return [(promos, total)]

    def q88(self):
        d = self.d
        ss = d.tables["store_sales"]
        hd = d.tables["household_demographics"]
        dep = hd["hd_dep_count"]
        veh = hd["hd_vehicle_count"]
        hd_ok = set(hd["hd_demo_sk"][
            ((dep == 4) & (veh <= 6)) | ((dep == 2) & (veh <= 4))
            | ((dep == 0) & (veh <= 2))].tolist())
        st = d.tables["store"]
        names = _decode(d, "store", "s_store_name")
        s_ok = {sk for sk, nm in zip(st["s_store_sk"].tolist(), names)
                if nm == b"ese"}
        bands = [0] * 8
        for t, h, s in zip(ss["ss_sold_time_sk"].tolist(),
                           ss["ss_hdemo_sk"].tolist(),
                           ss["ss_store_sk"].tolist()):
            if h not in hd_ok or s not in s_ok:
                continue
            half = t // 1800  # half-hour index in the day
            if 17 <= half <= 24:  # 8:30 .. 12:30
                bands[half - 17] += 1
        return [tuple(bands)]

    def q91(self):
        d = self.d
        cr = d.tables["catalog_returns"]
        yr, _, _ = self._date_cols(cr["cr_returned_date_sk"])
        ccn = _decode(d, "call_center", "cc_name")
        cci = {sk: i for i, sk in enumerate(
            d.tables["call_center"]["cc_call_center_sk"].tolist())}
        cust = d.tables["customer"]
        cd = d.tables["customer_demographics"]
        ms = _decode(d, "customer_demographics", "cd_marital_status")
        es = _decode(d, "customer_demographics", "cd_education_status")
        cd_ok = {}
        for sk, m_, e_ in zip(cd["cd_demo_sk"].tolist(), ms, es):
            if (m_ == b"M" and e_ == b"Unknown") or (
                    m_ == b"W" and e_ == b"Advanced Degree"):
                cd_ok[sk] = (m_, e_)
        hd = d.tables["household_demographics"]
        bp = _decode(d, "household_demographics", "hd_buy_potential")
        hd_ok = {sk for sk, b in zip(hd["hd_demo_sk"].tolist(), bp)
                 if b.startswith(b"Unknown")}
        acc: dict = collections.defaultdict(int)
        for ok, cc, c, loss in zip(
                (yr == 1998).tolist(),
                cr["cr_call_center_sk"].tolist(),
                cr["cr_returning_customer_sk"].tolist(),
                cr["cr_net_loss"].tolist()):
            if not ok:
                continue
            band = cd_ok.get(int(cust["c_current_cdemo_sk"][c - 1]))
            if band is None:
                continue
            if int(cust["c_current_hdemo_sk"][c - 1]) not in hd_ok:
                continue
            acc[(ccn[cci[cc]], band[0], band[1])] += loss
        rows = [(*k, v) for k, v in acc.items()]
        rows.sort(key=lambda r: (-r[3], r[0], r[1], r[2]))
        return rows

    def q17(self):
        d = self.d
        ss, sr = d.tables["store_sales"], d.tables["store_returns"]
        cs = d.tables["catalog_sales"]
        y1, m1, _ = self._date_cols(ss["ss_sold_date_sk"])
        y2, m2, _ = self._date_cols(sr["sr_returned_date_sk"])
        y3, m3, _ = self._date_cols(cs["cs_sold_date_sk"])
        qoy = lambda m: (m - 1) // 3 + 1  # noqa: E731
        triples = self._chain_rows(
            (y1 == 2001) & (qoy(m1) == 1),
            (y2 == 2001) & (qoy(m2) <= 3),
            (y3 == 2001) & (qoy(m3) <= 3))
        it, st = d.tables["item"], d.tables["store"]
        iids = _decode(d, "item", "i_item_id")
        idescs = _decode(d, "item", "i_item_desc")
        states = _decode(d, "store", "s_state")
        ipos = self._item_pos()
        spos = {sk: i for i, sk in enumerate(
            st["s_store_sk"].tolist())}
        acc: dict = collections.defaultdict(
            lambda: ([], [], []))
        for i, r, j in triples:
            ir = ipos[ss["ss_item_sk"][i]]
            sp = spos[ss["ss_store_sk"][i]]
            vals = acc[(iids[ir], idescs[ir], states[sp])]
            vals[0].append(int(ss["ss_quantity"][i]))
            vals[1].append(int(sr["sr_return_quantity"][r]))
            vals[2].append(int(cs["cs_quantity"][j]))

        def stats(v):
            sd = float(np.std(v, ddof=1)) if len(v) >= 2 else None
            return (len(v), float(np.mean(v)), sd)

        rows = [(*k, *stats(v[0]), *stats(v[1]), *stats(v[2]))
                for k, v in sorted(acc.items())]
        return rows[:100]

    def q39(self):
        d = self.d
        inv = d.tables["inventory"]
        y, m, _ = self._date_cols(inv["inv_date_sk"])
        acc: dict = collections.defaultdict(list)
        sel = np.flatnonzero((y == 2001) & (m <= 2))
        for w, i, mm, q in zip(
                inv["inv_warehouse_sk"][sel].tolist(),
                inv["inv_item_sk"][sel].tolist(), m[sel].tolist(),
                inv["inv_quantity_on_hand"][sel].tolist()):
            acc[(w, i, mm)].append(q)
        st = {}
        for k, v in acc.items():
            if len(v) < 2:
                continue
            mean = float(np.mean(v))
            sd = float(np.std(v, ddof=1))
            if mean > 0 and sd / mean > 0.5:
                st[k] = (mean, sd)
        out = []
        for (w, i, mm), (mean1, sd1) in sorted(st.items()):
            if mm != 1:
                continue
            two = st.get((w, i, 2))
            if two is not None:
                out.append((w, i, 1, mean1, sd1, 2, two[0], two[1]))
        return out[:100]

    def q9(self):
        ss = self.d.tables["store_sales"]
        q = ss["ss_quantity"]
        out = []
        for lo in (1, 21, 41, 61, 81):
            m = (q >= lo) & (q <= lo + 19)
            col = ("ss_ext_discount_amt" if int(m.sum()) > 10000
                   else "ss_net_paid")
            out.append(float(ss[col][m].mean()) / 100.0)
        return [tuple(out)]

    def _year_totals(self, fact, cust_col, date_col, vals):
        """(customer, year) -> sum of the precomputed per-row ``vals``
        over 1998/1999 (the q74/q11/q4 year_total accumulation)."""
        tb = self.d.tables[fact]
        y, _, _ = self._date_cols(tb[date_col])
        acc: dict = collections.defaultdict(int)
        sel = np.flatnonzero(np.isin(y, (1998, 1999)))
        for yy, c, p in zip(y[sel].tolist(),
                            tb[cust_col][sel].tolist(),
                            np.asarray(vals)[sel].tolist()):
            acc[(c, yy)] += p
        return acc

    def _year_ratio_customers(self, value_cols):
        """q74/q11 shape: customers whose 1998->1999 web revenue ratio
        beats the store ratio; ``value_cols`` maps channel prefix ->
        per-row revenue column(s) (first minus the rest)."""
        d = self.d

        def vals_of(fact, cols):
            v = d.tables[fact][cols[0]].astype(np.int64)
            for extra in cols[1:]:
                v = v - d.tables[fact][extra]
            return v

        st = self._year_totals(
            "store_sales", "ss_customer_sk", "ss_sold_date_sk",
            vals_of("store_sales", value_cols["ss_"]))
        wt = self._year_totals(
            "web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
            vals_of("web_sales", value_cols["ws_"]))
        n_cust = len(d.tables["customer"]["c_customer_sk"])
        for c in range(1, n_cust + 1):
            s1, s2 = st.get((c, 1998)), st.get((c, 1999))
            w1, w2 = wt.get((c, 1998)), wt.get((c, 1999))
            if None in (s1, s2, w1, w2) or s1 <= 0 or w1 <= 0:
                continue
            if w2 / w1 > s2 / s1:
                yield c

    def q74(self):
        d = self.d
        cids = _decode(d, "customer", "c_customer_id")
        fn = _decode(d, "customer", "c_first_name")
        ln = _decode(d, "customer", "c_last_name")
        out = [(cids[c - 1], fn[c - 1], ln[c - 1])
               for c in self._year_ratio_customers(
                   {"ss_": ("ss_net_paid",),
                    "ws_": ("ws_net_paid",)})]
        out.sort()
        return out[:100]

    def _channel_profit_totals(self, fact, pfx, cust_col):
        """q4's per-row profit: list - wholesale - discount + sales."""
        tb = self.d.tables[fact]
        vals = (tb[pfx + "ext_list_price"].astype(np.int64)
                - tb[pfx + "ext_wholesale_cost"]
                - tb[pfx + "ext_discount_amt"]
                + tb[pfx + "ext_sales_price"])
        return self._year_totals(fact, cust_col,
                                 pfx + "sold_date_sk", vals)

    def q4(self):
        d = self.d
        cids = _decode(d, "customer", "c_customer_id")
        fn = _decode(d, "customer", "c_first_name")
        ln = _decode(d, "customer", "c_last_name")
        st = self._channel_profit_totals(
            "store_sales", "ss_", "ss_customer_sk")
        ct = self._channel_profit_totals(
            "catalog_sales", "cs_", "cs_bill_customer_sk")
        wt = self._channel_profit_totals(
            "web_sales", "ws_", "ws_bill_customer_sk")
        out = []
        for c in range(1, len(cids) + 1):
            legs = [(t.get((c, 1998)), t.get((c, 1999)))
                    for t in (st, ct, wt)]
            if any(a is None or b is None for a, b in legs):
                continue
            (s1, s2), (c1, c2), (w1, w2) = legs
            if s1 <= 0 or c1 <= 0 or w1 <= 0:
                continue
            if c2 / c1 > s2 / s1 and c2 / c1 > w2 / w1:
                out.append((cids[c - 1], fn[c - 1], ln[c - 1]))
        out.sort()
        return out[:100]

    def q11(self):
        d = self.d
        cids = _decode(d, "customer", "c_customer_id")
        flags = _decode(d, "customer", "c_preferred_cust_flag")
        out = [(cids[c - 1], flags[c - 1])
               for c in self._year_ratio_customers(
                   {"ss_": ("ss_ext_list_price",
                            "ss_ext_discount_amt"),
                    "ws_": ("ws_ext_list_price",
                            "ws_ext_discount_amt")})]
        out.sort()
        return out[:100]

    def q36(self):
        d = self.d
        ss = d.tables["store_sales"]
        y, _, _ = self._date_cols(ss["ss_sold_date_sk"])
        cats = _decode(d, "item", "i_category")
        classes = _decode(d, "item", "i_class")
        ipos = self._item_pos()
        st = d.tables["store"]
        states = _decode(d, "store", "s_state")
        s_ok = {sk for sk, sst in zip(st["s_store_sk"].tolist(),
                                      states) if sst == b"TN"}
        acc: dict = collections.defaultdict(lambda: [0, 0])
        for i in np.flatnonzero(y == 2001).tolist():
            if ss["ss_store_sk"][i] not in s_ok:
                continue
            ir = ipos[ss["ss_item_sk"][i]]
            a = acc[(cats[ir], classes[ir])]
            a[0] += int(ss["ss_net_profit"][i])
            a[1] += int(ss["ss_ext_sales_price"][i])
        rows = [(p / s, c_, cl) for (c_, cl), (p, s) in acc.items()
                if s]
        rows.sort(key=lambda r: (r[0], r[1], r[2]))
        return rows[:100]

    def q86(self):
        d = self.d
        ws = d.tables["web_sales"]
        dd = self._dd()
        cats = _decode(d, "item", "i_category")
        classes = _decode(d, "item", "i_class")
        ipos = self._item_pos()
        acc: dict = collections.defaultdict(int)
        for dk, ik, p in zip(ws["ws_sold_date_sk"].tolist(),
                             ws["ws_item_sk"].tolist(),
                             ws["ws_net_paid"].tolist()):
            if not (24 <= dd[dk][6] <= 35):
                continue
            ir = ipos[ik]
            acc[(cats[ir], classes[ir])] += p
        rows = [(v, c_, cl) for (c_, cl), v in acc.items()]
        rows.sort(key=lambda r: (-r[0], r[1], r[2]))
        return rows[:100]

    def q22(self):
        d = self.d
        inv = d.tables["inventory"]
        dd = self._dd()
        iids = _decode(d, "item", "i_item_id")
        brands = _decode(d, "item", "i_brand")
        classes = _decode(d, "item", "i_class")
        cats = _decode(d, "item", "i_category")
        ipos = self._item_pos()
        acc: dict = collections.defaultdict(lambda: [0, 0])
        for dk, ik, q in zip(inv["inv_date_sk"].tolist(),
                             inv["inv_item_sk"].tolist(),
                             inv["inv_quantity_on_hand"].tolist()):
            if not (24 <= dd[dk][6] <= 35):
                continue
            ir = ipos[ik]
            a = acc[(iids[ir], brands[ir], classes[ir], cats[ir])]
            a[0] += q
            a[1] += 1
        rows = [(k[0], k[1], k[2], k[3], s / n)
                for k, (s, n) in acc.items()]
        rows.sort(key=lambda r: (r[4], r[0], r[1], r[2], r[3]))
        return rows[:100]

    def _monthly_dev(self, key_col, period_of, sort_key):
        """q53/q63 shape: per-(item attribute, period) revenue vs the
        attribute's average over its periods, >10% deviations kept."""
        d = self.d
        ss = d.tables["store_sales"]
        y, m, _ = self._date_cols(ss["ss_sold_date_sk"])
        cats = _decode(d, "item", "i_category")
        classes = _decode(d, "item", "i_class")
        it = d.tables["item"]
        ipos = self._item_pos()
        set_a_cat = {b"Books", b"Children", b"Electronics"}
        set_a_cls = {b"class#01", b"class#02", b"class#03"}
        set_b_cat = {b"Women", b"Music", b"Men"}
        set_b_cls = {b"class#04", b"class#05", b"class#06"}
        acc: dict = collections.defaultdict(int)
        for i in np.flatnonzero(y == 1999).tolist():
            ir = ipos[ss["ss_item_sk"][i]]
            c_, cl = cats[ir], classes[ir]
            if not ((c_ in set_a_cat and cl in set_a_cls)
                    or (c_ in set_b_cat and cl in set_b_cls)):
                continue
            acc[(int(it[key_col][ir]), period_of(int(m[i])))] += int(
                ss["ss_sales_price"][i])
        groups: dict = collections.defaultdict(list)
        for (kid, _p), s in acc.items():
            groups[kid].append(s)
        rows = []
        for (kid, period), s in acc.items():
            avg = (sum(groups[kid]) / len(groups[kid])) / 100.0
            sv = s / 100.0
            if avg > 0 and abs(sv - avg) / avg > 0.1:
                rows.append((kid, period, s, avg))
        rows.sort(key=sort_key)
        return rows[:100]

    def _bought_in(self, fact, cust_col, date_col, date_ok):
        tb = self.d.tables[fact]
        y_m = self._date_cols(tb[date_col])
        ok = date_ok(*y_m)
        return set(tb[cust_col][ok].tolist())

    def _q10_shape(self, date_ok):
        """Customers with a store purchase AND a web-or-catalog
        purchase in the window -> their cdemo rows."""
        store = self._bought_in("store_sales", "ss_customer_sk",
                                "ss_sold_date_sk", date_ok)
        remote = (self._bought_in("web_sales", "ws_bill_customer_sk",
                                  "ws_sold_date_sk", date_ok)
                  | self._bought_in("catalog_sales",
                                    "cs_bill_customer_sk",
                                    "cs_sold_date_sk", date_ok))
        return store & remote

    def q10(self):
        d = self.d
        ok_counties = {b"Salem County", b"Terrell County",
                       b"Arthur County", b"Oglethorpe County",
                       b"Lunenburg County"}
        counties = _decode(d, "customer_address", "ca_county")
        cust = d.tables["customer"]
        cd = d.tables["customer_demographics"]
        g = _decode(d, "customer_demographics", "cd_gender")
        ms = _decode(d, "customer_demographics", "cd_marital_status")
        es = _decode(d, "customer_demographics",
                     "cd_education_status")
        cr = _decode(d, "customer_demographics", "cd_credit_rating")
        buyers = self._q10_shape(
            lambda y, m, _d: (y == 2002) & (m >= 1) & (m <= 4))
        acc: dict = collections.Counter()
        for c in buyers:
            a_row = int(cust["c_current_addr_sk"][c - 1]) - 1
            if counties[a_row] not in ok_counties:
                continue
            i = int(cust["c_current_cdemo_sk"][c - 1]) - 1
            acc[(g[i], ms[i], es[i],
                 int(cd["cd_purchase_estimate"][i]), cr[i],
                 int(cd["cd_dep_count"][i]))] += 1
        rows = [(*k, n) for k, n in sorted(acc.items())]
        return rows[:100]

    def q35(self):
        d = self.d
        cust = d.tables["customer"]
        cd = d.tables["customer_demographics"]
        g = _decode(d, "customer_demographics", "cd_gender")
        ms = _decode(d, "customer_demographics", "cd_marital_status")
        states = _decode(d, "customer_address", "ca_state")
        buyers = self._q10_shape(
            lambda y, m, _d: (y == 2002) & (m <= 9))
        acc: dict = collections.Counter()
        for c in buyers:
            a_row = int(cust["c_current_addr_sk"][c - 1]) - 1
            i = int(cust["c_current_cdemo_sk"][c - 1]) - 1
            dep = int(cd["cd_dep_count"][i])
            acc[(states[a_row], g[i], ms[i], dep)] += 1
        rows = [(*k, n, k[3], k[3], float(k[3]))
                for k, n in sorted(acc.items())]
        return rows[:100]

    def q63(self):
        return self._monthly_dev(
            "i_manager_id", lambda m: m,
            lambda r: (r[0], r[3], r[2], r[1]))

    def q53(self):
        return self._monthly_dev(
            "i_manufact_id", lambda m: (m - 1) // 3 + 1,
            lambda r: (r[3], r[2], r[0], r[1]))

    def q67(self):
        d = self.d
        ss = d.tables["store_sales"]
        dd = self._dd()
        cats = _decode(d, "item", "i_category")
        classes = _decode(d, "item", "i_class")
        brands = _decode(d, "item", "i_brand")
        iids = _decode(d, "item", "i_item_id")
        ipos = self._item_pos()
        sids = _decode(d, "store", "s_store_id")
        spos = {sk: i for i, sk in enumerate(
            d.tables["store"]["s_store_sk"].tolist())}
        acc: dict = collections.defaultdict(int)
        for dk, ik, sk, p, q in zip(
                ss["ss_sold_date_sk"].tolist(),
                ss["ss_item_sk"].tolist(),
                ss["ss_store_sk"].tolist(),
                ss["ss_sales_price"].tolist(),
                ss["ss_quantity"].tolist()):
            info = dd[dk]  # (year, moy, dom, dow, qoy, date, mseq)
            if not (24 <= info[6] <= 35):
                continue
            ir = ipos[ik]
            sp = spos[sk]
            acc[(cats[ir], classes[ir], brands[ir], iids[ir],
                 info[0], info[4], info[1], sids[sp])] += p * q
        by_cat: dict = collections.defaultdict(list)
        for k, s in acc.items():
            by_cat[k[0]].append((k, s))
        rows = []
        for cat, cells in by_cat.items():
            cells.sort(key=lambda kv: -kv[1])
            rk = 0
            prev = None
            for i, (k, s) in enumerate(cells):
                if s != prev:
                    rk = i + 1
                if rk > 100:
                    break
                rows.append((*k[:4], k[4], k[5], k[6], k[7], s, rk))
                prev = s
        rows.sort(key=lambda r: (r[0], r[9], r[1], r[2], r[3], r[4],
                                 r[5], r[6], r[7]))
        return rows[:100]

    def q70(self):
        d = self.d
        ss = d.tables["store_sales"]
        dd = self._dd()
        states = _decode(d, "store", "s_state")
        counties = _decode(d, "store", "s_county")
        spos = {sk: i for i, sk in enumerate(
            d.tables["store"]["s_store_sk"].tolist())}
        acc: dict = collections.defaultdict(int)
        for dk, sk, p in zip(ss["ss_sold_date_sk"].tolist(),
                             ss["ss_store_sk"].tolist(),
                             ss["ss_net_profit"].tolist()):
            if not (24 <= dd[dk][6] <= 35):
                continue
            sp = spos[sk]
            acc[(states[sp], counties[sp])] += p
        by_state: dict = collections.defaultdict(list)
        for (st, co), s in acc.items():
            by_state[st].append((co, s))
        rows = []
        for st, cells in by_state.items():
            cells.sort(key=lambda kv: -kv[1])
            rk = 0
            prev = None
            for i, (co, s) in enumerate(cells):
                if s != prev:
                    rk = i + 1
                rows.append((st, co, s, rk))
                prev = s
        rows.sort(key=lambda r: (r[0], r[3], r[1]))
        return rows[:100]

    def q44(self):
        d = self.d
        ss = d.tables["store_sales"]
        acc: dict = collections.defaultdict(lambda: [0, 0])
        for sk, ik, p in zip(ss["ss_store_sk"].tolist(),
                             ss["ss_item_sk"].tolist(),
                             ss["ss_net_profit"].tolist()):
            if sk == 4:
                a = acc[ik]
                a[0] += p
                a[1] += 1
        avgs = sorted(
            ((s / n_, ik) for ik, (s, n_) in acc.items()))
        iids = _decode(d, "item", "i_item_id")
        ipos = self._item_pos()
        worst = [ik for _a, ik in avgs[:10]]
        best = [ik for _a, ik in sorted(
            ((-a, ik) for a, ik in avgs))[:10]]
        return [(r + 1, iids[ipos[b]], iids[ipos[w]])
                for r, (b, w) in enumerate(zip(best, worst))]

    def q89(self):
        d = self.d
        ss = d.tables["store_sales"]
        y, m, _ = self._date_cols(ss["ss_sold_date_sk"])
        cats = _decode(d, "item", "i_category")
        brands = _decode(d, "item", "i_brand")
        classes = _decode(d, "item", "i_class")
        ipos = self._item_pos()
        snames = _decode(d, "store", "s_store_name")
        spos = {sk: i for i, sk in enumerate(
            d.tables["store"]["s_store_sk"].tolist())}
        set_a_cat = {b"Books", b"Electronics", b"Sports"}
        set_a_cls = {b"class#01", b"class#02", b"class#03"}
        set_b_cat = {b"Men", b"Jewelry", b"Women"}
        set_b_cls = {b"class#04", b"class#05", b"class#06"}
        acc: dict = collections.defaultdict(int)
        for i in np.flatnonzero(y == 1999).tolist():
            ir = ipos[ss["ss_item_sk"][i]]
            c_, cl = cats[ir], classes[ir]
            if not ((c_ in set_a_cat and cl in set_a_cls)
                    or (c_ in set_b_cat and cl in set_b_cls)):
                continue
            sp = spos[ss["ss_store_sk"][i]]
            acc[(c_, brands[ir], snames[sp], int(m[i]))] += int(
                ss["ss_sales_price"][i])
        groups: dict = collections.defaultdict(list)
        for (c_, b, sn, _moy), s in acc.items():
            groups[(c_, b, sn)].append(s)
        rows = []
        for (c_, b, sn, moy), s in acc.items():
            vals = groups[(c_, b, sn)]
            avg = (sum(vals) / len(vals)) / 100.0
            sv = s / 100.0
            if avg > 0 and abs(sv - avg) / avg > 0.1:
                rows.append((c_, b, sn, moy, s, avg, sv - avg))
        rows.sort(key=lambda r: (r[6], r[0], r[1], r[2], r[3]))
        return rows[:100]

    def q2(self):
        d = self.d
        dd = d.tables["date_dim"]
        dnames = _decode(d, "date_dim", "d_day_name")
        wk_of = dict(zip(dd["d_date_sk"].tolist(),
                         dd["d_week_seq"].tolist()))
        day_of = dict(zip(dd["d_date_sk"].tolist(), dnames))
        order = [b"Sunday", b"Monday", b"Tuesday", b"Wednesday",
                 b"Thursday", b"Friday", b"Saturday"]
        acc: dict = collections.defaultdict(lambda: [0] * 7)
        for fact, dk, pk in (
                ("web_sales", "ws_sold_date_sk",
                 "ws_ext_sales_price"),
                ("catalog_sales", "cs_sold_date_sk",
                 "cs_ext_sales_price")):
            tb = d.tables[fact]
            for sk, p in zip(tb[dk].tolist(), tb[pk].tolist()):
                acc[wk_of[sk]][order.index(day_of[sk])] += p
        weeks_of = {
            yy: set(dd["d_week_seq"][dd["d_year"] == yy].tolist())
            for yy in (2001, 2002)}
        out = []
        for w in sorted(weeks_of[2001]):
            if w not in acc or (w + 53) not in acc:
                continue
            if (w + 53) not in weeks_of[2002]:
                continue
            z = acc[w + 53]
            if any(v <= 0 for v in z):
                continue
            yv = acc[w]
            out.append((int(w), *(yv[i] / z[i] for i in range(7))))
        return out

    def q38(self):
        d = self.d
        ln = _decode(d, "customer", "c_last_name")
        fn = _decode(d, "customer", "c_first_name")

        def triples(fact, cust_col, date_col):
            tb = d.tables[fact]
            _, _, dates = self._date_cols(tb[date_col])
            dd = d.tables["date_dim"]
            seq_ok = (dd["d_month_seq"] >= 24) & (dd["d_month_seq"]
                                                  <= 35)
            ok_dates = set(dd["d_date"][seq_ok].tolist())
            out = set()
            for c, dt in zip(tb[cust_col].tolist(), dates.tolist()):
                if dt in ok_dates:
                    out.add((ln[c - 1], fn[c - 1], dt))
            return out

        n = len(triples("store_sales", "ss_customer_sk",
                        "ss_sold_date_sk")
                & triples("catalog_sales", "cs_bill_customer_sk",
                          "cs_sold_date_sk")
                & triples("web_sales", "ws_bill_customer_sk",
                          "ws_sold_date_sk"))
        return [(n,)]

    def q31(self):
        d = self.d
        counties = _decode(d, "customer_address", "ca_county")

        def qsums(fact, date_col, addr_col, price_col):
            tb = d.tables[fact]
            y, m, _ = self._date_cols(tb[date_col])
            acc: dict = collections.defaultdict(int)
            sel = np.flatnonzero((y == 2000) & (m <= 9))
            for a, mm, p in zip(tb[addr_col][sel].tolist(),
                                m[sel].tolist(),
                                tb[price_col][sel].tolist()):
                acc[(counties[a - 1], (mm - 1) // 3 + 1)] += p
            return acc

        ssq = qsums("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                    "ss_ext_sales_price")
        wsq = qsums("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                    "ws_ext_sales_price")
        out = []
        for county in sorted(set(k[0] for k in ssq)):
            s = [ssq.get((county, q)) for q in (1, 2, 3)]
            w = [wsq.get((county, q)) for q in (1, 2, 3)]
            if None in s or None in w or s[0] <= 0 or s[1] <= 0 \
                    or w[0] <= 0 or w[1] <= 0:
                continue
            if w[1] / w[0] > s[1] / s[0] and w[2] / w[1] > s[2] / s[1]:
                out.append((county, 2000, w[1] / w[0], s[1] / s[0],
                            w[2] / w[1], s[2] / s[1]))
        return out

    def q27(self):
        d = self.d
        ss = d.tables["store_sales"]
        y, _, _ = self._date_cols(ss["ss_sold_date_sk"])
        cd = d.tables["customer_demographics"]
        g = _decode(d, "customer_demographics", "cd_gender")
        ms = _decode(d, "customer_demographics", "cd_marital_status")
        es = _decode(d, "customer_demographics", "cd_education_status")
        cd_ok = {sk for sk, a, b, c in zip(
            cd["cd_demo_sk"].tolist(), g, ms, es)
            if a == b"M" and b == b"S" and c == b"College"}
        st = d.tables["store"]
        states = _decode(d, "store", "s_state")
        s_ok = {sk for sk, sst in zip(st["s_store_sk"].tolist(),
                                      states) if sst == b"TN"}
        iids = _decode(d, "item", "i_item_id")
        ipos = self._item_pos()
        acc: dict = collections.defaultdict(lambda: [0] * 5)
        for i in np.flatnonzero(y == 2002).tolist():
            if ss["ss_cdemo_sk"][i] not in cd_ok:
                continue
            if ss["ss_store_sk"][i] not in s_ok:
                continue
            a = acc[(iids[ipos[ss["ss_item_sk"][i]]], b"TN")]
            a[0] += 1
            a[1] += int(ss["ss_quantity"][i])
            a[2] += int(ss["ss_list_price"][i])
            a[3] += int(ss["ss_coupon_amt"][i])
            a[4] += int(ss["ss_sales_price"][i])
        rows = [(k[0], k[1], a[1] / a[0], a[2] / a[0] / 100,
                 a[3] / a[0] / 100, a[4] / a[0] / 100)
                for k, a in sorted(acc.items())]
        return rows[:100]

    def q18(self):
        d = self.d
        cs = d.tables["catalog_sales"]
        y, _, _ = self._date_cols(cs["cs_sold_date_sk"])
        cd = d.tables["customer_demographics"]
        g = _decode(d, "customer_demographics", "cd_gender")
        es = _decode(d, "customer_demographics", "cd_education_status")
        cd_ok = {sk for sk, a, b in zip(cd["cd_demo_sk"].tolist(),
                                        g, es)
                 if a == b"F" and b == b"Unknown"}
        dep = dict(zip(cd["cd_demo_sk"].tolist(),
                       cd["cd_dep_count"].tolist()))
        cust = d.tables["customer"]
        ca = d.tables["customer_address"]
        ca_states = _decode(d, "customer_address", "ca_state")
        countries = _decode(d, "customer_address", "ca_country")
        counties = _decode(d, "customer_address", "ca_county")
        ok_states = {b"MS", b"GA", b"NM", b"OH", b"TX"}
        iids = _decode(d, "item", "i_item_id")
        ipos = self._item_pos()
        acc: dict = collections.defaultdict(lambda: [0] * 8)
        for j in np.flatnonzero(y == 1998).tolist():
            cdk = cs["cs_bill_cdemo_sk"][j]
            if cdk not in cd_ok:
                continue
            c = int(cs["cs_bill_customer_sk"][j]) - 1
            if int(cust["c_birth_month"][c]) not in (1, 6, 8, 9,
                                                     12, 2):
                continue
            a_row = int(cust["c_current_addr_sk"][c]) - 1
            if ca_states[a_row] not in ok_states:
                continue
            k = (iids[ipos[cs["cs_item_sk"][j]]], countries[a_row],
                 ca_states[a_row], counties[a_row])
            a = acc[k]
            a[0] += 1
            a[1] += int(cs["cs_quantity"][j])
            a[2] += int(cs["cs_list_price"][j])
            a[3] += int(cs["cs_coupon_amt"][j])
            a[4] += int(cs["cs_sales_price"][j])
            a[5] += int(cs["cs_net_profit"][j])
            a[6] += int(cust["c_birth_year"][c])
            a[7] += int(dep[cdk])
        rows = [(k[0], k[1], k[2], k[3], a[1] / a[0],
                 a[2] / a[0] / 100, a[3] / a[0] / 100,
                 a[4] / a[0] / 100, a[5] / a[0] / 100,
                 a[6] / a[0], a[7] / a[0])
                for k, a in sorted(acc.items())]
        return rows[:100]

    def q81(self):
        return self._ctr_over_state_avg(
            "catalog_returns", "cr_", "cr_return_amount", b"GA")

    def q30(self):
        return self._ctr_over_state_avg(
            "web_returns", "wr_", "wr_return_amt", b"MO")


def run_tpcds(sf: float = 0.01, queries=None, iterations: int = 1,
              seed: int = 42, verify: bool = True):
    """Plan+execute the query set; optionally verify vs the reference.
    Returns [(name, best_seconds, result_rows)]."""
    import time

    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan import Database, execute_plan, to_host
    from ydb_tpu.sql.parser import parse
    from ydb_tpu.sql.planner import Catalog, plan_select_full

    data = TpcdsData(sf=sf, seed=seed)
    db = Database(
        sources={t: ColumnSource(cols, SCHEMAS[t], data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts,
    )
    catalog = Catalog(schemas=dict(SCHEMAS),
                      primary_keys=dict(PRIMARY_KEYS),
                      dicts=data.dicts)
    names = queries or sorted(QUERIES, key=lambda q: int(q[1:]))
    want = reference_answers(data, names) if verify else {}
    results = []
    for name in names:
        from ydb_tpu.workload.runner import scalar_exec_for

        pq = plan_select_full(parse(QUERIES[name]), catalog,
                              scalar_exec_for(db))
        out = to_host(execute_plan(pq.plan, db))  # warmup/compile
        if verify:
            verify_result(name, out, want[name], data, pq)
        best = float("inf")
        for _ in range(max(1, iterations)):
            t0 = time.monotonic()
            out = to_host(execute_plan(pq.plan, db))
            best = min(best, time.monotonic() - t0)
        results.append((name, best, out.num_rows))
    return results


# verification column layout per query: (name, kind) where kind is
# int | str | dec (scaled cents -> compare exactly) | avg (float)
_VERIFY_COLS = {
    "q3": (("d_year", "int"), ("i_brand_id", "int"), ("i_brand", "str"),
           ("sum_agg", "dec")),
    "q6": (("ca_state", "str"), ("cnt", "int")),
    "q7": (("i_item_id", "str"), ("agg1", "avg"), ("agg2", "avg"),
           ("agg3", "avg"), ("agg4", "avg")),
    "q13": (("avg_qty", "avg"), ("avg_esp", "avg"),
            ("avg_ewc", "avg"), ("sum_ewc", "dec")),
    "q48": (("total_qty", "int"),),
    "q19": (("i_brand_id", "int"), ("i_brand", "str"),
            ("i_manufact_id", "int"), ("i_manufact", "str"),
            ("ext_price", "dec")),
    "q26": (("i_item_id", "str"), ("agg1", "avg"), ("agg2", "avg"),
            ("agg3", "avg"), ("agg4", "avg")),
    "q42": (("d_year", "int"), ("i_category_id", "int"),
            ("i_category", "str"), ("sum_agg", "dec")),
    "q43": (("s_store_name", "str"), ("s_store_id", "str"),
            ("sun_sales", "dec"), ("mon_sales", "dec"),
            ("tue_sales", "dec"), ("wed_sales", "dec"),
            ("thu_sales", "dec"), ("fri_sales", "dec"),
            ("sat_sales", "dec")),
    "q52": (("d_year", "int"), ("i_brand_id", "int"), ("i_brand", "str"),
            ("ext_price", "dec")),
    "q55": (("i_brand_id", "int"), ("i_brand", "str"),
            ("ext_price", "dec")),
    "q96": (("cnt", "int"),),
    "q15": (("ca_zip", "str"), ("total", "dec")),
    "q32": (("excess", "dec"),),
    "q34": (("c_last_name", "str"), ("c_first_name", "str"),
            ("c_salutation", "str"), ("c_preferred_cust_flag", "str"),
            ("ss_ticket_number", "int"), ("cnt", "int")),
    "q46": (("c_last_name", "str"), ("c_first_name", "str"),
            ("ca_city", "str"), ("bought_city", "str"),
            ("ss_ticket_number", "int"), ("amt", "dec"),
            ("profit", "dec")),
    "q65": (("s_store_name", "str"), ("i_item_desc", "str"),
            ("revenue", "dec"), ("i_current_price", "dec"),
            ("i_wholesale_cost", "dec"), ("i_brand", "str")),
    "q68": (("c_last_name", "str"), ("c_first_name", "str"),
            ("ca_city", "str"), ("bought_city", "str"),
            ("ss_ticket_number", "int"), ("extended_price", "dec"),
            ("extended_tax", "dec"), ("list_price", "dec")),
    "q73": (("c_last_name", "str"), ("c_first_name", "str"),
            ("c_salutation", "str"), ("c_preferred_cust_flag", "str"),
            ("ss_ticket_number", "int"), ("cnt", "int")),
    "q79": (("c_last_name", "str"), ("c_first_name", "str"),
            ("city30", "str"), ("ss_ticket_number", "int"),
            ("amt", "dec"), ("profit", "dec")),
    "q1": (("c_customer_id", "str"),),
    "q25": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("s_store_id", "str"), ("s_store_name", "str"),
            ("store_sales_profit", "dec"),
            ("store_returns_loss", "dec"),
            ("catalog_sales_profit", "dec")),
    "q29": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("s_store_id", "str"), ("s_store_name", "str"),
            ("store_sales_quantity", "int"),
            ("store_returns_quantity", "int"),
            ("catalog_sales_quantity", "int")),
    "q40": (("w_state", "str"), ("i_item_id", "str"),
            ("sales_before", "dec"), ("sales_after", "dec")),
    "q50": (("s_store_name", "str"), ("s_store_id", "str"),
            ("d30", "int"), ("d60", "int"), ("d90", "int"),
            ("d120", "int"), ("dmore", "int")),
    "q93": (("ss_customer_sk", "int"), ("sumsales", "dec")),
    "q16": (("order_count", "int"), ("total_shipping_cost", "dec"),
            ("total_net_profit", "dec")),
    "q94": (("order_count", "int"), ("total_shipping_cost", "dec"),
            ("total_net_profit", "dec")),
    "q62": (("wname", "str"), ("sm_type", "str"), ("web_name", "str"),
            ("d30", "int"), ("d60", "int"), ("d90", "int"),
            ("d120", "int"), ("dmore", "int")),
    "q81": (("c_customer_id", "str"), ("c_salutation", "str"),
            ("c_first_name", "str"), ("c_last_name", "str"),
            ("ctr_total_return", "dec")),
    "q30": (("c_customer_id", "str"), ("c_salutation", "str"),
            ("c_first_name", "str"), ("c_last_name", "str"),
            ("ctr_total_return", "dec")),
    "q61": (("promotions", "dec"), ("total", "dec")),
    "q17": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("s_state", "str"),
            ("store_sales_quantitycount", "int"),
            ("store_sales_quantityave", "avg"),
            ("store_sales_quantitystdev", "avg"),
            ("store_returns_quantitycount", "int"),
            ("store_returns_quantityave", "avg"),
            ("store_returns_quantitystdev", "avg"),
            ("catalog_sales_quantitycount", "int"),
            ("catalog_sales_quantityave", "avg"),
            ("catalog_sales_quantitystdev", "avg")),
    "q39": (("wsk", "int"), ("isk", "int"), ("moy1", "int"),
            ("mean1", "avg"), ("stdev1", "avg"), ("moy2", "int"),
            ("mean2", "avg"), ("stdev2", "avg")),
    "q9": (("bucket1", "avg"), ("bucket2", "avg"), ("bucket3", "avg"),
           ("bucket4", "avg"), ("bucket5", "avg")),
    "q74": (("customer_id", "str"), ("customer_first_name", "str"),
            ("customer_last_name", "str")),
    "q11": (("customer_id", "str"), ("flag", "str")),
    "q4": (("customer_id", "str"), ("customer_first_name", "str"),
           ("customer_last_name", "str")),
    "q38": (("cnt", "int"),),
    "q36": (("gross_margin", "avg"), ("i_category", "str"),
            ("i_class", "str")),
    "q86": (("total_sum", "dec"), ("i_category", "str"),
            ("i_class", "str")),
    "q22": (("i_item_id", "str"), ("i_brand", "str"),
            ("i_class", "str"), ("i_category", "str"),
            ("qoh", "avg")),
    "q53": (("i_manufact_id", "int"), ("d_qoy", "int"),
            ("sum_sales", "dec"), ("avg_quarterly_sales", "avg")),
    "q10": (("cd_gender", "str"), ("cd_marital_status", "str"),
            ("cd_education_status", "str"),
            ("cd_purchase_estimate", "int"),
            ("cd_credit_rating", "str"), ("cd_dep_count", "int"),
            ("cnt", "int")),
    "q35": (("ca_state", "str"), ("cd_gender", "str"),
            ("cd_marital_status", "str"), ("cd_dep_count", "int"),
            ("cnt1", "int"), ("mn", "int"), ("mx", "int"),
            ("av", "avg")),
    "q63": (("i_manager_id", "int"), ("d_moy", "int"),
            ("sum_sales", "dec"), ("avg_monthly_sales", "avg")),
    "q67": (("i_category", "str"), ("i_class", "str"),
            ("i_brand", "str"), ("i_item_id", "str"),
            ("d_year", "int"), ("d_qoy", "int"), ("d_moy", "int"),
            ("s_store_id", "str"), ("sumsales", "dec"),
            ("rk", "int")),
    "q70": (("s_state", "str"), ("s_county", "str"),
            ("sumsales", "dec"), ("rk", "int")),
    "q44": (("rnk", "int"), ("best_performing", "str"),
            ("worst_performing", "str")),
    "q89": (("i_category", "str"), ("i_brand", "str"),
            ("s_store_name", "str"), ("d_moy", "int"),
            ("sum_sales", "dec"), ("avg_monthly_sales", "avg"),
            ("diff", "avg")),
    "q2": (("week1", "int"), ("sun_ratio", "avg"),
           ("mon_ratio", "avg"), ("tue_ratio", "avg"),
           ("wed_ratio", "avg"), ("thu_ratio", "avg"),
           ("fri_ratio", "avg"), ("sat_ratio", "avg")),
    "q31": (("ca_county", "str"), ("d_year", "int"),
            ("web_q1_q2_increase", "avg"),
            ("store_q1_q2_increase", "avg"),
            ("web_q2_q3_increase", "avg"),
            ("store_q2_q3_increase", "avg")),
    "q27": (("i_item_id", "str"), ("s_state", "str"), ("agg1", "avg"),
            ("agg2", "avg"), ("agg3", "avg"), ("agg4", "avg")),
    "q18": (("i_item_id", "str"), ("ca_country", "str"),
            ("ca_state", "str"), ("ca_county", "str"),
            ("agg1", "avg"), ("agg2", "avg"), ("agg3", "avg"),
            ("agg4", "avg"), ("agg5", "avg"), ("agg6", "avg"),
            ("agg7", "avg")),
    "q88": (("h8_30_to_9", "int"), ("h9_to_9_30", "int"),
            ("h9_30_to_10", "int"), ("h10_to_10_30", "int"),
            ("h10_30_to_11", "int"), ("h11_to_11_30", "int"),
            ("h11_30_to_12", "int"), ("h12_to_12_30", "int")),
    "q91": (("cc_name", "str"), ("cd_marital_status", "str"),
            ("cd_education_status", "str"), ("returns_loss", "dec")),
    "q33": (("i_manufact_id", "int"), ("total_sales", "dec")),
    "q56": (("i_item_id", "str"), ("total_sales", "dec")),
    "q60": (("i_item_id", "str"), ("total_sales", "dec")),
    "q71": (("brand_id", "int"), ("brand", "str"), ("t_hour", "int"),
            ("t_minute", "int"), ("ext_price", "dec")),
    "q98": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("i_category", "str"), ("i_class", "str"),
            ("i_current_price", "dec"), ("itemrevenue", "dec"),
            ("revenueratio", "avg")),
    "q12": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("i_category", "str"), ("i_class", "str"),
            ("i_current_price", "dec"), ("itemrevenue", "dec"),
            ("revenueratio", "avg")),
    "q20": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("i_category", "str"), ("i_class", "str"),
            ("i_current_price", "dec"), ("itemrevenue", "dec"),
            ("revenueratio", "avg")),
    "q21": (("w_warehouse_name", "str"), ("i_item_id", "str"),
            ("inv_before", "int"), ("inv_after", "int")),
    "q37": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("i_current_price", "dec")),
    "q45": (("ca_zip", "str"), ("ca_county", "str"),
            ("total", "dec")),
    "q69": (("cd_gender", "str"), ("cd_marital_status", "str"),
            ("cd_education_status", "str"), ("cnt1", "int"),
            ("cd_purchase_estimate", "int"), ("cnt2", "int"),
            ("cd_credit_rating", "str"), ("cnt3", "int")),
    "q82": (("i_item_id", "str"), ("i_item_desc", "str"),
            ("i_current_price", "dec")),
    "q92": (("excess", "dec"),),
    "q99": (("wname", "str"), ("sm_type", "str"), ("cc_name", "str"),
            ("d30", "int"), ("d60", "int"), ("d90", "int"),
            ("d120", "int"), ("dmore", "int")),
}

# reference rows carry avgs pre-descaled; engine avg output of a DEC2
# column is a double that still needs descaling only when the engine
# kept decimal typing -- col_out handles both via the schema.


def verify_result(name, out, want, data, pq=None) -> None:
    spec = _VERIFY_COLS[name]
    got_cols = []
    for col, kind in spec:
        v, _ok = out.cols[col]
        arr = np.asarray(v)
        if kind == "str":
            src = col
            if pq is not None:
                src = pq.dict_aliases.get(col, col)
            got_cols.append(data.dicts[src].decode(arr))
        elif kind == "dec":
            t = out.schema.field(col).type
            if t.is_decimal:
                got_cols.append([int(x) for x in arr])
            else:
                got_cols.append([int(round(float(x) * 100))
                                 for x in arr])
        elif kind == "avg":
            t = out.schema.field(col).type
            scale = 10.0 ** t.scale if t.is_decimal else 1.0
            got_cols.append([float(x) / scale for x in arr])
        else:
            got_cols.append([int(x) for x in arr])
    ok_cols = [np.asarray(out.cols[col][1], dtype=bool)
               for col, _k in spec]
    got = list(zip(*got_cols)) if got_cols else []
    assert len(got) == len(want), \
        (name, len(got), len(want), got[:3], want[:3])
    for i, (gi, wi) in enumerate(zip(got, want)):
        for j, ((col, kind), g, w) in enumerate(zip(spec, gi, wi)):
            if w is None:
                # zero-input aggregate: the engine must mark the
                # value NULL (validity false), not fabricate one
                assert not ok_cols[j][i], (name, col, g)
            elif kind == "avg":
                assert abs(g - w) < 1e-9, (name, col, g, w)
            elif kind == "dec":
                ww = int(round(w)) if not isinstance(w, int) else w
                assert g == ww, (name, col, g, w)
            else:
                assert g == w, (name, col, g, w)
