"""Embedded workload runner (the `ydb workload tpch` analog,
public/lib/ydb_cli benchmark_utils.cpp; SURVEY.md layer 9)."""

from __future__ import annotations

import time

from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.workload import tpch
from ydb_tpu.workload.queries import TPCH


def tpch_database(data: tpch.TpchData) -> tuple[Database, Catalog]:
    db = Database(
        sources={
            t: ColumnSource(cols, data.schema(t), data.dicts)
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )
    catalog = Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys=dict(tpch.PRIMARY_KEYS),
        dicts=data.dicts,
    )
    return db, catalog


def scalar_exec_for(db: Database):
    """Uncorrelated-scalar-subquery executor bound to a Database."""
    def scalar_exec(plan_node, t):
        out = to_host(execute_plan(plan_node, db))
        col = out.schema.names[0]
        v, ok = out.cols[col]
        if len(v) != 1:
            raise ValueError(f"scalar subquery returned {len(v)} rows")
        return v[0].item(), bool(ok[0])

    return scalar_exec


def run_tpch(sf: float = 0.01, queries: list[str] | None = None,
             iterations: int = 1, seed: int = 42):
    """Returns [(name, best_seconds, result_rows)]. The first run of a
    query includes XLA compilation; timing takes the best of
    ``iterations`` post-warmup runs."""
    data = tpch.TpchData(sf=sf, seed=seed)
    db, catalog = tpch_database(data)
    names = queries or sorted(TPCH)
    results = []
    for name in names:
        sql = TPCH[name]
        plan = plan_select_full(parse(sql), catalog,
                                scalar_exec_for(db)).plan
        out = to_host(execute_plan(plan, db))  # warmup/compile
        best = float("inf")
        for _ in range(max(1, iterations)):
            t0 = time.monotonic()
            out = to_host(execute_plan(plan, db))
            best = min(best, time.monotonic() - t0)
        results.append((name, best, out.num_rows))
    return results
