"""Embedded workload runner (the `ydb workload tpch` analog,
public/lib/ydb_cli benchmark_utils.cpp; SURVEY.md layer 9)."""

from __future__ import annotations

import time

from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select
from ydb_tpu.workload import tpch
from ydb_tpu.workload.queries import TPCH

TPCH_PRIMARY_KEYS = {
    "orders": ("o_orderkey",), "customer": ("c_custkey",),
    "supplier": ("s_suppkey",), "nation": ("n_nationkey",),
    "region": ("r_regionkey",),
    "lineitem": ("l_orderkey", "l_linenumber"),
}


def tpch_database(data: tpch.TpchData) -> tuple[Database, Catalog]:
    db = Database(
        sources={
            t: ColumnSource(cols, data.schema(t), data.dicts)
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )
    catalog = Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys=dict(TPCH_PRIMARY_KEYS),
        dicts=data.dicts,
    )
    return db, catalog


def run_tpch(sf: float = 0.01, queries: list[str] | None = None,
             iterations: int = 1, seed: int = 42):
    """Returns [(name, best_seconds, result_rows)]. The first run of a
    query includes XLA compilation; timing takes the best of
    ``iterations`` post-warmup runs."""
    data = tpch.TpchData(sf=sf, seed=seed)
    db, catalog = tpch_database(data)
    names = queries or sorted(TPCH)
    results = []
    for name in names:
        sql = TPCH[name]
        plan = plan_select(parse(sql), catalog)
        out = to_host(execute_plan(plan, db))  # warmup/compile
        best = float("inf")
        for _ in range(max(1, iterations)):
            t0 = time.monotonic()
            out = to_host(execute_plan(plan, db))
            best = min(best, time.monotonic() - t0)
        results.append((name, best, out.num_rows))
    return results
